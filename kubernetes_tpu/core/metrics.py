"""Scheduler metrics: Prometheus-style registry + the reference's series.

Re-expresses pkg/scheduler/metrics/metrics.go (names at :265-615) over a
dependency-free metrics core (component-base/metrics analogue). Series are
registered on a module-level Registry; `expose()` renders the Prometheus text
format for a /metrics endpoint.
"""

from __future__ import annotations

import time
from bisect import bisect_left
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

# Histogram buckets (metrics.go uses exponential buckets starting 0.001).
DURATION_BUCKETS = (0.001, 0.002, 0.004, 0.008, 0.016, 0.032, 0.064, 0.128,
                    0.256, 0.512, 1.024, 2.048, 4.096, 8.192, 16.384)


class Metric:
    def __init__(self, name: str, help_text: str, label_names: Tuple[str, ...] = ()):
        self.name = name
        self.help = help_text
        self.label_names = label_names


class Counter(Metric):
    def __init__(self, name, help_text, label_names=()):
        super().__init__(name, help_text, tuple(label_names))
        self._values: Dict[Tuple[str, ...], float] = {}

    def inc(self, *labels: str, value: float = 1.0) -> None:
        key = tuple(labels)
        self._values[key] = self._values.get(key, 0.0) + value

    def value(self, *labels: str) -> float:
        return self._values.get(tuple(labels), 0.0)

    def expose(self) -> List[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} counter"]
        # Snapshot-copy before iterating: /metrics renders on an HTTP
        # thread while the scheduling loop mutates the series dicts —
        # sorted() iterates and would raise RuntimeError on a concurrent
        # resize. dict.copy() is a single C-level op under the GIL.
        for key, v in sorted(self._values.copy().items()):
            out.append(f"{self.name}{_fmt_labels(self.label_names, key)} {v}")
        return out


class Gauge(Metric):
    def __init__(self, name, help_text, label_names=(), fn: Optional[Callable] = None):
        super().__init__(name, help_text, tuple(label_names))
        self._values: Dict[Tuple[str, ...], float] = {}
        self._fn = fn  # callback gauge

    def set(self, value: float, *labels: str) -> None:
        self._values[tuple(labels)] = value

    def value(self, *labels: str) -> float:
        return self._values.get(tuple(labels), 0.0)

    def expose(self) -> List[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} gauge"]
        # Callback gauges return a fresh dict; stored values snapshot-copy
        # (concurrent scrape vs scheduling-loop set(), as in Counter).
        values = self._fn() if self._fn is not None else self._values.copy()
        for key, v in sorted(values.items()):
            out.append(f"{self.name}{_fmt_labels(self.label_names, key)} {v}")
        return out


class Histogram(Metric):
    """Counts are stored PER-BUCKET (non-cumulative) so observe() is O(1)
    via bisect — it runs several times per pod on a >10k pods/s path — and
    converted to Prometheus cumulative form at expose/percentile time."""

    def __init__(self, name, help_text, label_names=(), buckets=DURATION_BUCKETS):
        super().__init__(name, help_text, tuple(label_names))
        self.buckets = tuple(buckets)
        self._counts: Dict[Tuple[str, ...], List[int]] = {}
        self._sums: Dict[Tuple[str, ...], float] = {}
        self._totals: Dict[Tuple[str, ...], int] = {}

    def observe(self, value: float, *labels: str) -> None:
        key = labels
        counts = self._counts.get(key)
        if counts is None:
            # +1 slot: the +Inf bucket
            counts = self._counts.setdefault(key, [0] * (len(self.buckets) + 1))
        counts[bisect_left(self.buckets, value)] += 1
        self._sums[key] = self._sums.get(key, 0.0) + value
        self._totals[key] = self._totals.get(key, 0) + 1

    def _cumulative(self, key, counts: Optional[Dict] = None) -> List[int]:
        out = []
        c = 0
        # list() copy: observe() increments slots in place on the
        # scheduling loop while a scrape renders — per-slot reads are
        # GIL-atomic, the copy just pins one consistent-length view.
        for v in list((counts if counts is not None
                       else self._counts).get(key, ())):
            c += v
            out.append(c)
        return out

    def count(self, *labels: str) -> int:
        return self._totals.get(tuple(labels), 0)

    def sum(self, *labels: str) -> float:
        return self._sums.get(tuple(labels), 0.0)

    def percentile(self, q: float, *labels: str) -> float:
        """Bucket-interpolated percentile (perf collector support); mass in
        the +Inf bucket reports the top finite bound."""
        key = tuple(labels)
        total = self._totals.get(key, 0)
        if total == 0:
            return 0.0
        target = q * total
        cum_prev = 0
        cums = self._cumulative(key)
        for i, b in enumerate(self.buckets):
            cum = cums[i]
            if cum >= target:
                lo = self.buckets[i - 1] if i else 0.0
                span = cum - cum_prev
                frac = (target - cum_prev) / span if span else 1.0
                return lo + (b - lo) * frac
            cum_prev = cum
        return self.buckets[-1]

    def expose(self) -> List[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} histogram"]
        # Snapshot-copy all three series dicts before iterating (scrape
        # thread vs scheduling loop; see Counter.expose). A key present in
        # totals but racing into counts/sums reads back zero this scrape.
        totals = self._totals.copy()
        sums = self._sums.copy()
        counts = self._counts.copy()
        for key in sorted(totals):
            cums = self._cumulative(key, counts) or [0] * (len(self.buckets) + 1)
            for i, b in enumerate(self.buckets):
                labels = _fmt_labels(self.label_names + ("le",), key + (str(b),))
                out.append(f"{self.name}_bucket{labels} {cums[i]}")
            inf = _fmt_labels(self.label_names + ("le",), key + ("+Inf",))
            out.append(f"{self.name}_bucket{inf} {cums[-1]}")
            out.append(f"{self.name}_sum{_fmt_labels(self.label_names, key)} {sums.get(key, 0.0)}")
            out.append(f"{self.name}_count{_fmt_labels(self.label_names, key)} {totals[key]}")
        return out


def _fmt_labels(names: Tuple[str, ...], values: Tuple[str, ...]) -> str:
    if not names:
        return ""
    pairs = ",".join(f'{n}="{v}"' for n, v in zip(names, values))
    return "{" + pairs + "}"


class Registry:
    def __init__(self):
        self._metrics: List[Metric] = []

    def register(self, m: Metric) -> Metric:
        self._metrics.append(m)
        return m

    def expose(self) -> str:
        lines: List[str] = []
        for m in self._metrics:
            lines.extend(m.expose())
        return "\n".join(lines) + "\n"


class SchedulerMetrics:
    """The scheduler's series (metrics/metrics.go:265-615 subset that the
    perf harness and tests consume)."""

    def __init__(self):
        self.registry = Registry()
        r = self.registry.register
        self.schedule_attempts = r(Counter(
            "scheduler_schedule_attempts_total",
            "Number of attempts to schedule pods, by result and profile.",
            ("result", "profile")))
        self.scheduling_attempt_duration = r(Histogram(
            "scheduler_scheduling_attempt_duration_seconds",
            "Scheduling attempt latency (scheduling algorithm + binding).",
            ("result", "profile")))
        self.pod_scheduling_sli_duration = r(Histogram(
            "scheduler_pod_scheduling_sli_duration_seconds",
            "E2e latency for a pod being scheduled, from first attempt.",
            ("attempts",)))
        self.e2e_scheduling_duration = r(Histogram(
            "scheduler_e2e_scheduling_duration_seconds",
            "End-to-end pod scheduling latency, queue admission -> bound "
            "(fed from pod.e2e span ends; docs/OBSERVABILITY.md). Extended "
            "buckets: late pods in a large drain legitimately wait tens of "
            "seconds in the queue.",
            buckets=DURATION_BUCKETS + (32.768, 65.536, 131.072)))
        self.framework_extension_point_duration = r(Histogram(
            "scheduler_framework_extension_point_duration_seconds",
            "Latency per extension point.", ("extension_point", "status", "profile")))
        self.plugin_execution_duration = r(Histogram(
            "scheduler_plugin_execution_duration_seconds",
            "Plugin execution latency.", ("plugin", "extension_point", "status")))
        self.pending_pods = r(Gauge(
            "scheduler_pending_pods",
            "Pending pods by queue (active/backoff/unschedulable/gated).",
            ("queue",)))
        self.queue_incoming_pods = r(Counter(
            "scheduler_queue_incoming_pods_total",
            "Pods added to queues by event and queue.", ("queue", "event")))
        self.preemption_attempts = r(Counter(
            "scheduler_preemption_attempts_total", "Preemption attempts."))
        self.preemption_victims = r(Histogram(
            "scheduler_preemption_victims", "Victims per preemption.",
            buckets=(1, 2, 4, 8, 16, 32, 64)))
        self.batch_attempts = r(Counter(
            "scheduler_batch_attempts_total",
            "Device batch dispatches, by outcome.", ("result",)))
        self.batch_size = r(Histogram(
            "scheduler_batch_size", "Pods per device batch.",
            buckets=(1, 8, 64, 256, 512, 1024, 2048, 4096)))
        self.podgroup_schedule_attempts = r(Counter(
            "scheduler_podgroup_schedule_attempts_total",
            "Gang scheduling attempts, by result.", ("result",)))
        self.generated_placements = r(Histogram(
            "scheduler_podgroup_generated_placements",
            "Candidate placements generated per pod-group cycle "
            "(metrics.RecordGeneratedPlacements).",
            buckets=(1, 2, 4, 8, 16, 32, 64, 128)))
        self.goroutines = r(Gauge(
            "scheduler_goroutines",
            "In-flight concurrent work by kind (metrics.go Goroutines); the "
            "TPU build's analogue counts in-flight device dispatches.",
            ("work",)))
        self.cache_size = r(Gauge(
            "scheduler_cache_size", "Cache object counts.", ("type",)))
        # ---- full reference-series parity (metrics.go:265-615) ------------
        self.pod_scheduling_attempts = r(Histogram(
            "scheduler_pod_scheduling_attempts",
            "Number of attempts to successfully schedule a pod.",
            buckets=(1, 2, 4, 8, 16)))
        self.scheduling_algorithm_duration = r(Histogram(
            "scheduler_scheduling_algorithm_duration_seconds",
            "Scheduling algorithm latency (filter+score, no binding)."))
        self.event_handling_duration = r(Histogram(
            "scheduler_event_handling_duration_seconds",
            "Event handling latency by event kind.", ("event",)))
        self.inflight_events = r(Gauge(
            "scheduler_inflight_events",
            "Entries in the in-flight event log.", (), fn=None))
        self.queued_entities = r(Gauge(
            "scheduler_queued_entities",
            "Queued entities by kind (pod/podgroup/composite).", ("kind",)))
        self.unschedulable_pods = r(Gauge(
            "scheduler_unschedulable_pods",
            "Pods in the unschedulable store, by plugin that rejected them.",
            ("plugin",)))
        self.queue_incoming_entities = r(Counter(
            "scheduler_queue_incoming_entities_total",
            "Group/composite entities added to queues by event.",
            ("queue", "event")))
        # Overload/fairness plane (docs/RESILIENCE.md § overload &
        # fairness): per-tenant starvation truth — how long each
        # namespace's longest-waiting runnable entity has sat in the
        # active/backoff queues. Callback gauge fed from
        # PriorityQueue.starvation_by_namespace at scrape time.
        self.queue_starvation = r(Gauge(
            "scheduler_queue_starvation_seconds",
            "Per-namespace longest wait (seconds) of a runnable queued "
            "entity since queue admission — the starvation signal the "
            "fair-dequeue plane bounds.", ("namespace",)))
        self.permit_wait_duration = r(Histogram(
            "scheduler_permit_wait_duration_seconds",
            "Time pods spend waiting on Permit.", ("result",)))
        self.queueing_hint_execution_duration = r(Histogram(
            "scheduler_queueing_hint_execution_duration_seconds",
            "QueueingHintFn execution latency.", ("plugin", "event")))
        self.plugin_evaluation_total = r(Counter(
            "scheduler_plugin_evaluation_total",
            "Plugin evaluations by plugin/extension point/profile.",
            ("plugin", "extension_point", "profile")))
        # async API dispatcher (backend/api_dispatcher metrics)
        self.async_api_call_execution_total = r(Counter(
            "scheduler_async_api_call_execution_total",
            "Async API calls executed, by call type and result.",
            ("call_type", "result")))
        self.async_api_call_execution_duration = r(Histogram(
            "scheduler_async_api_call_execution_duration_seconds",
            "Async API call execution latency.", ("call_type", "result")))
        self.pending_async_api_calls = r(Gauge(
            "scheduler_pending_async_api_calls",
            "Queued async API calls not yet executed.", ()))
        self.async_api_call_retries = r(Counter(
            "scheduler_async_api_call_retries_total",
            "Transient-failure replays of async API calls (backoff retries "
            "that happened BEFORE a call landed in the error inbox).",
            ("call_type",)))
        # resilience layer (core/backoff.py; docs/RESILIENCE.md)
        self.device_path_fallback = r(Counter(
            "scheduler_device_path_fallback_total",
            "Scheduling work rerouted from the device kernel path to the "
            "host Evaluator, by reason (exception class, 'unsupported', or "
            "'breaker_open').", ("reason",)))
        self.device_breaker_state = r(Gauge(
            "scheduler_device_path_breaker_open",
            "1 while the device-path circuit breaker is open (host path "
            "pinned for the cool-down), else 0.", ()))
        # opportunistic batching (runtime/batch.go series), generalized to
        # device sessions: a "flush" is a session invalidation.
        self.batch_cache_flushed = r(Counter(
            "scheduler_batch_cache_flushed_total",
            "Batch/session state flushes (session invalidations), by reason.",
            ("reason",)))
        self.pod_scheduled_after_flush = r(Counter(
            "scheduler_pod_scheduled_after_flush_total",
            "Pods scheduled in the first batch after a flush.", ()))
        # incremental session resume (event-journal delta rebuilds)
        self.plan_rebuild_total = r(Counter(
            "scheduler_plan_rebuild_total",
            "Device-session plan acquisitions, by kind: 'full' = complete "
            "snapshot→features rebuild, 'resume' = untouched cache hit, "
            "'delta' = journal-driven row patch of a live plan+carry; "
            "'plane' splits mesh (sharded) sessions from single-device — "
            "a mesh 'full' tears down and re-uploads the whole sharded "
            "state, the cost the delta patches exist to avoid.",
            ("kind", "plane")))
        self.plan_rebuild_dirty_rows = r(Counter(
            "scheduler_plan_rebuild_dirty_rows_total",
            "Node rows re-encoded + scattered by delta plan patches.", ()))
        self.get_node_hint_duration = r(Histogram(
            "scheduler_get_node_hint_duration_seconds",
            "Batch reuse lookup latency (session-resume check)."))
        # score-hint fast path (models/score_hints.py; KEP-5598
        # OpportunisticBatch, cross-cycle)
        self.hint_cache_hits = r(Counter(
            "scheduler_hint_cache_hits_total",
            "Pods bound through the score-hint fast path (no device "
            "dispatch), by matching signature kind: 'exact' | 'neutral' "
            "(namespace-erased).", ("reason",)))
        self.hint_cache_misses = r(Counter(
            "scheduler_hint_cache_misses_total",
            "Hint-path fall-throughs to the normal batch, by reason: "
            "'empty' = no live hint, 'signature' = different pod shape, "
            "'stale' = freshness fence tripped (see invalidations), "
            "'infeasible' = no node passed the hinted walk, plus "
            "pod-eligibility reasons (claims/unsupported/extender/"
            "unsignable/profile/affinity_gate).", ("reason",)))
        self.hint_cache_invalidations = r(Counter(
            "scheduler_hint_cache_invalidations_total",
            "Hint invalidations, by reason: journal event kinds "
            "(pod_terms/pns_taint/structural/other/namespace), "
            "'journal_gap', 'foreign_attempt', 'state_unwind', "
            "'nomination', 'affinity_transition' (0->1 affinity-pod "
            "transition disables hints cluster-wide), 'bind_conflict' "
            "(single-NODE invalidation, the hint survives), "
            "'device_failure'.", ("reason",)))
        self.hint_validation_duration = r(Histogram(
            "scheduler_hint_validation_duration_seconds",
            "Host-side hint validate+select latency per consulted pod "
            "(journal replay + the kernel's selection math in numpy)."))
        # shard plane (kubernetes_tpu/shard/): optimistic multi-scheduler
        self.bind_conflict_total = r(Counter(
            "scheduler_bind_conflict_total",
            "Optimistic-binding conflicts (409 from the binding "
            "subresource), by reason: 'already_bound' = another scheduler "
            "bound the pod first, 'capacity' = the commit would overcommit "
            "the node (Omega transaction validation), 'conflict' = "
            "unclassified 409.", ("reason",)))
        self.shard_owned_shards = r(Gauge(
            "scheduler_shard_owned_shards",
            "Shard ranges this scheduler currently owns (1 = its own; more "
            "after adopting an expired peer's range)."))
        self.shard_lease_renewals = r(Counter(
            "scheduler_shard_lease_renewals_total",
            "Successful shard-lease renewals through the apiserver.", ()))
        self.shard_adoptions = r(Counter(
            "scheduler_shard_adoptions_total",
            "Expired peer shard ranges adopted (lease-expiry failover).",
            ()))
        # watch-cache read plane (core/watchcache.py): per-shard decode
        # cost by wire form — 'full' = whole pod/node wire, 'slim' = the
        # shard filter's NodeInfo-accounting projection. Callback gauges
        # fed from the HTTP clientset's reflector counters.
        self.watch_decoded_events = r(Gauge(
            "scheduler_watch_decoded_events",
            "Watch events this scheduler decoded, by wire form "
            "(shard-filtered streams deliver foreign plain pods slim) "
            "and codec (core/wire.py negotiated binary vs JSON).",
            ("form", "codec")))
        self.watch_decoded_bytes = r(Gauge(
            "scheduler_watch_decoded_bytes",
            "Watch stream bytes this scheduler decoded, by wire form "
            "and codec.",
            ("form", "codec")))
        # placement / pod-group series
        self.generated_placements_total = r(Counter(
            "scheduler_generated_placements_total",
            "Candidate placements generated.", ()))
        self.placement_evaluations = r(Counter(
            "scheduler_placement_evaluations_total",
            "Candidate placement evaluations, by backend.", ("backend",)))
        self.placement_evaluation_duration = r(Histogram(
            "scheduler_placement_evaluation_duration_seconds",
            "Latency of evaluating ALL candidate placements for a group."))
        self.podgroup_scheduling_algorithm_duration = r(Histogram(
            "scheduler_podgroup_scheduling_algorithm_duration_seconds",
            "Pod-group scheduling algorithm latency."))
        self.podgroup_scheduling_attempt_duration = r(Histogram(
            "scheduler_podgroup_scheduling_attempt_duration_seconds",
            "Pod-group scheduling attempt latency incl. commit.",
            ("result",)))
        self.store_schedule_results_duration = r(Histogram(
            "scheduler_store_schedule_results_duration_seconds",
            "Latency of persisting scheduling results to the pod-group "
            "state store."))
        # preemption depth series
        self.preemption_evaluation_duration = r(Histogram(
            "scheduler_preemption_evaluation_duration_seconds",
            "Preemption candidate evaluation (dry run) latency."))
        self.preemption_execution_duration = r(Histogram(
            "scheduler_preemption_execution_duration_seconds",
            "Preemption execution (victim preparation) latency."))
        self.preemption_goroutines_duration = r(Histogram(
            "scheduler_preemption_goroutines_duration_seconds",
            "Async victim-deletion work latency (executor.go analogue)."))
        self.preemption_goroutines_execution_total = r(Counter(
            "scheduler_preemption_goroutines_execution_total",
            "Async victim-deletion executions, by result.", ("result",)))
        self.preemption_pdb_violations = r(Counter(
            "scheduler_preemption_pdb_violations_total",
            "Victims selected despite PDB violation (no PDB API yet: "
            "registered for parity, always 0).", ()))
        self.preemption_workload_disruptions = r(Counter(
            "scheduler_preemption_workload_disruptions",
            "Workloads disrupted by pod-group preemption.", ()))
        self.workload_preemption_attempts = r(Counter(
            "scheduler_workload_preemption_attempts_total",
            "Pod-group (workload) preemption attempts, by result.",
            ("result",)))
        self.workload_preemption_victims = r(Histogram(
            "scheduler_workload_preemption_victims",
            "Victims per pod-group preemption.",
            buckets=(1, 2, 4, 8, 16, 32, 64)))
        # DRA binding conditions (dra_bindingconditions_*): the binding-
        # conditions protocol is not implemented (allocation is synchronous
        # in-cycle), registered for name parity and future wiring.
        self.dra_bindingconditions_allocations = r(Counter(
            "scheduler_dra_bindingconditions_allocations_total",
            "DRA allocations carrying binding conditions (not implemented: "
            "allocation is synchronous; always 0).", ("result",)))
        self.dra_bindingconditions_wait_duration = r(Histogram(
            "scheduler_dra_bindingconditions_wait_duration_seconds",
            "Wait for DRA binding conditions (not implemented; empty)."))

    def expose(self) -> str:
        return self.registry.expose()


@dataclass
class _Timer:
    start: float = field(default_factory=time.perf_counter)

    def elapsed(self) -> float:
        return time.perf_counter() - self.start


class MetricAsyncRecorder:
    """Buffered off-thread metric recording (pkg/scheduler/metrics/
    metric_recorder.go MetricAsyncRecorder): hot paths append observations
    to a bounded buffer and a flusher thread applies them to the histograms
    on an interval — the scheduling loop never pays the registry's dict
    work. observe() drops on overflow (the reference's channel send is
    non-blocking too), counting drops for observability."""

    def __init__(self, interval: float = 0.05, capacity: int = 4096):
        import threading
        from collections import deque

        # Unbounded deque + explicit capacity check: deque(maxlen) would
        # silently evict the OLDEST observation when two racing observers
        # both pass a len() check — an uncounted loss. With no maxlen the
        # worst case of the (benign) check-then-append race is a few entries
        # over capacity, all of which still flush.
        self._buf = deque()
        self._capacity = capacity
        self._interval = interval
        self.dropped = 0
        self._stop = threading.Event()
        self._flushed = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="metric-recorder", daemon=True)
        self._thread.start()

    def observe(self, histogram: Histogram, value: float, *labels: str) -> None:
        if len(self._buf) >= self._capacity:
            self.dropped += 1
            return
        self._buf.append((histogram, value, labels))

    def _run(self) -> None:
        while not self._stop.wait(self._interval):
            self.flush_now()
        self.flush_now()

    def flush_now(self) -> None:
        buf = self._buf
        while buf:
            try:
                histogram, value, labels = buf.popleft()
            except IndexError:
                break
            histogram.observe(value, *labels)
        self._flushed.set()

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=2)
        self.flush_now()
