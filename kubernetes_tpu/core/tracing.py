"""Lightweight scheduling-step tracing + event recording.

The reference logs any scheduling step that exceeds 100ms through utiltrace
(schedule_one.go:574-575) and emits API Events per scheduling outcome
(EventRecorder, schedule_one.go:1138). This module is the framework's
equivalent: a per-cycle trace with a slow-step threshold wired to Python
logging (structured key=value formatting, klog-style), plus a bounded
in-memory event recorder the server can expose.
"""

from __future__ import annotations

import logging
import time
from collections import deque
from typing import Deque, Dict, List, Optional, Tuple

logger = logging.getLogger("kubernetes_tpu")

SLOW_STEP_THRESHOLD_S = 0.1  # schedule_one.go:574 — log any step > 100ms


class StepTrace:
    """utiltrace.New analogue: one trace per scheduling attempt; steps are
    recorded with durations and the whole trace is logged when it crosses
    the threshold. On a breach, individual steps over the reference's
    stepThreshold (threshold / #steps, utiltrace trace.go) are named
    explicitly, each emits a forced span event, and the flight recorder is
    asked for a forensic dump (core/spans.py request_dump)."""

    __slots__ = ("name", "fields", "t0", "steps", "_last", "ctx")

    def __init__(self, name: str, ctx=None, **fields):
        self.name = name
        self.fields = fields
        self.ctx = ctx  # optional spans.SpanContext tying the trace to a pod
        self.t0 = time.perf_counter()
        self._last = self.t0
        self.steps: List[Tuple[str, float]] = []

    def step(self, msg: str) -> None:
        now = time.perf_counter()
        self.steps.append((msg, now - self._last))
        self._last = now

    def log_if_long(self, threshold: float = SLOW_STEP_THRESHOLD_S) -> float:
        total = time.perf_counter() - self.t0
        if total > threshold:
            # stepThreshold (utiltrace): with the total over budget, any
            # step carrying more than its even share is an offender.
            step_threshold = threshold / max(1, len(self.steps))
            slow = [(m, d) for m, d in self.steps if d > step_threshold]
            kv = " ".join(f"{k}={v}" for k, v in self.fields.items())
            parts = "; ".join(f"{m}: {d*1000:.0f}ms" for m, d in self.steps)
            offenders = "; ".join(f"{m}: {d*1000:.0f}ms" for m, d in slow)
            logger.warning("slow scheduling step: %s %s total=%.0fms (%s)"
                           "%s", self.name, kv, total * 1000, parts,
                           f" slow step(s) over {step_threshold*1000:.0f}ms: "
                           f"{offenders}" if offenders else "")
            self._emit_breach(slow, total)
        return total

    def _emit_breach(self, slow: List[Tuple[str, float]],
                     total: float) -> None:
        """One forced span event per offending step + a flight-recorder
        dump request (rate-limited there)."""
        from . import spans
        tracer = spans.default_tracer()
        if tracer.enabled:
            ctx = self.ctx if (self.ctx is not None and self.ctx.sampled) \
                else tracer.proc_ctx()
            base = {k: str(v) for k, v in self.fields.items()
                    if k not in ("start", "duration", "parent", "name",
                                 "ctx")}
            for msg, dur in slow:
                attrs = dict(base, step=msg, trace_name=self.name,
                             total_ms=round(total * 1e3, 3))
                tracer.record("trace.slow_step", ctx, dur, **attrs)
        spans.request_dump("slow_step")


class Event:
    """A minimal core/v1 Event (reason + message + involved object).

    `message` accepts either a plain string or a (fmt, args) tuple — the
    latter defers %-formatting until the message is actually read
    (EventRecorder runs once per scheduled pod on a >10k pods/s path; the
    reference buys the same headroom with an async broadcaster)."""

    __slots__ = ("object_key", "reason", "_message", "type", "count",
                 "timestamp", "evicted")

    def __init__(self, object_key: str, reason: str, message,
                 type: str = "Normal", count: int = 1,
                 timestamp: Optional[float] = None, evicted: bool = False):
        self.object_key = object_key
        self.reason = reason
        self._message = message
        self.type = type
        self.count = count
        self.timestamp = time.time() if timestamp is None else timestamp
        self.evicted = evicted

    @property
    def message(self) -> str:
        m = self._message
        if isinstance(m, tuple):
            m = m[0] % m[1]
            self._message = m
        return m

    @message.setter
    def message(self, value) -> None:
        self._message = value


class EventRecorder:
    """EventRecorder (client-go tools/record) analogue: bounded buffer with
    reference-style aggregation by (object, reason). The aggregation index
    is pruned in step with deque eviction, so memory stays O(capacity) and
    every eventf is O(1) — this runs once per scheduled pod on a path
    benchmarked at >10k pods/s."""

    def __init__(self, capacity: int = 1000):
        self.events: Deque[Event] = deque(maxlen=capacity)
        self._agg: Dict[Tuple[str, str], Event] = {}

    def eventf(self, object_key: str, event_type: str, reason: str,
               message: str) -> None:
        key = (object_key, reason)
        existing = self._agg.get(key)
        if existing is not None and not existing.evicted:
            existing.count += 1
            existing.message = message
            existing.timestamp = time.time()
            return
        ev = Event(object_key=object_key, reason=reason, message=message,
                   type=event_type)
        if self.events.maxlen and len(self.events) == self.events.maxlen:
            old = self.events[0]  # about to be evicted by the append
            old.evicted = True
            okey = (old.object_key, old.reason)
            if self._agg.get(okey) is old:
                del self._agg[okey]
        self._agg[key] = ev
        self.events.append(ev)

    def for_object(self, object_key: str) -> List[Event]:
        return [e for e in self.events if e.object_key == object_key]

    def recent(self, object_key: Optional[str] = None,
               limit: int = 256) -> List[Event]:
        """Newest-first read side. Aggregated events mutate count/timestamp
        IN PLACE (eventf), so the deque's insertion order goes stale the
        moment an aggregate re-fires — this re-sorts by the live timestamp,
        which is what the /debug/events surface and the flight recorder
        serve. O(capacity log capacity) on a read-only debug path."""
        evs: List[Event] = []
        for _ in range(4):
            try:
                evs = [e for e in self.events
                       if object_key is None or e.object_key == object_key]
                break
            except RuntimeError:
                # eventf() appended concurrently (scheduling thread vs the
                # flight-recorder/debug-endpoint reader) — deque iteration
                # raises instead of tearing; retry against the new state.
                continue
        evs.sort(key=lambda e: e.timestamp, reverse=True)
        return evs[:limit]
