"""Lightweight scheduling-step tracing + event recording.

The reference logs any scheduling step that exceeds 100ms through utiltrace
(schedule_one.go:574-575) and emits API Events per scheduling outcome
(EventRecorder, schedule_one.go:1138). This module is the framework's
equivalent: a per-cycle trace with a slow-step threshold wired to Python
logging (structured key=value formatting, klog-style), plus a bounded
in-memory event recorder the server can expose.
"""

from __future__ import annotations

import logging
import time
from collections import deque
from typing import Deque, Dict, List, Optional, Tuple

logger = logging.getLogger("kubernetes_tpu")

SLOW_STEP_THRESHOLD_S = 0.1  # schedule_one.go:574 — log any step > 100ms


class StepTrace:
    """utiltrace.New analogue: one trace per scheduling attempt; steps are
    recorded with durations and the whole trace is logged when it crosses
    the threshold."""

    __slots__ = ("name", "fields", "t0", "steps", "_last")

    def __init__(self, name: str, **fields):
        self.name = name
        self.fields = fields
        self.t0 = time.perf_counter()
        self._last = self.t0
        self.steps: List[Tuple[str, float]] = []

    def step(self, msg: str) -> None:
        now = time.perf_counter()
        self.steps.append((msg, now - self._last))
        self._last = now

    def log_if_long(self, threshold: float = SLOW_STEP_THRESHOLD_S) -> float:
        total = time.perf_counter() - self.t0
        if total > threshold:
            kv = " ".join(f"{k}={v}" for k, v in self.fields.items())
            parts = "; ".join(f"{m}: {d*1000:.0f}ms" for m, d in self.steps)
            logger.warning("slow scheduling step: %s %s total=%.0fms (%s)",
                           self.name, kv, total * 1000, parts)
        return total


class Event:
    """A minimal core/v1 Event (reason + message + involved object).

    `message` accepts either a plain string or a (fmt, args) tuple — the
    latter defers %-formatting until the message is actually read
    (EventRecorder runs once per scheduled pod on a >10k pods/s path; the
    reference buys the same headroom with an async broadcaster)."""

    __slots__ = ("object_key", "reason", "_message", "type", "count",
                 "timestamp", "evicted")

    def __init__(self, object_key: str, reason: str, message,
                 type: str = "Normal", count: int = 1,
                 timestamp: Optional[float] = None, evicted: bool = False):
        self.object_key = object_key
        self.reason = reason
        self._message = message
        self.type = type
        self.count = count
        self.timestamp = time.time() if timestamp is None else timestamp
        self.evicted = evicted

    @property
    def message(self) -> str:
        m = self._message
        if isinstance(m, tuple):
            m = m[0] % m[1]
            self._message = m
        return m

    @message.setter
    def message(self, value) -> None:
        self._message = value


class EventRecorder:
    """EventRecorder (client-go tools/record) analogue: bounded buffer with
    reference-style aggregation by (object, reason). The aggregation index
    is pruned in step with deque eviction, so memory stays O(capacity) and
    every eventf is O(1) — this runs once per scheduled pod on a path
    benchmarked at >10k pods/s."""

    def __init__(self, capacity: int = 1000):
        self.events: Deque[Event] = deque(maxlen=capacity)
        self._agg: Dict[Tuple[str, str], Event] = {}

    def eventf(self, object_key: str, event_type: str, reason: str,
               message: str) -> None:
        key = (object_key, reason)
        existing = self._agg.get(key)
        if existing is not None and not existing.evicted:
            existing.count += 1
            existing.message = message
            existing.timestamp = time.time()
            return
        ev = Event(object_key=object_key, reason=reason, message=message,
                   type=event_type)
        if self.events.maxlen and len(self.events) == self.events.maxlen:
            old = self.events[0]  # about to be evicted by the append
            old.evicted = True
            okey = (old.object_key, old.reason)
            if self._agg.get(okey) is old:
                del self._agg[okey]
        self._agg[key] = ev
        self.events.append(ev)

    def for_object(self, object_key: str) -> List[Event]:
        return [e for e in self.events if e.object_key == object_key]
