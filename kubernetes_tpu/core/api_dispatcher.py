"""Async API dispatcher: decouples scheduling cycles from API write RTT.

Re-expresses pkg/scheduler/backend/api_dispatcher/ (APIDispatcher
api_dispatcher.go:32, relevance-merging call_queue.go) and the call
implementations in framework/api_calls/ (pod_binding.go:32 PodBindingCall,
pod_status_patch.go). Gated by SchedulerAsyncAPICalls
(kube_features.go:1048).

Execution modes:
- inline  — calls run at enqueue (deterministic; default for tests/bench
  where the "API server" is an in-process dict and there is no RTT to hide);
- thread  — a worker thread drains the queue, overlapping binding writes
  with the next scheduling cycle exactly like the reference's goroutine.

Merging semantics (call_queue.go): one pending slot per (call_type, object
uid); a newly enqueued call replaces a queued one when its relevance is >=
the queued call's (e.g. a binding supersedes a pending status patch).
"""

from __future__ import annotations

import queue
import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

# Call types + relevance (api_calls/ relevances: binding > status patch).
CALL_STATUS_PATCH = "pod_status_patch"
CALL_BINDING = "pod_binding"
RELEVANCE = {CALL_STATUS_PATCH: 1, CALL_BINDING: 2}


@dataclass
class APICall:
    call_type: str
    object_uid: str
    execute: Callable[[], None]
    on_error: Optional[Callable[[Exception], None]] = None

    @property
    def relevance(self) -> int:
        return RELEVANCE.get(self.call_type, 0)


class APIDispatcher:
    def __init__(self, mode: str = "inline"):
        assert mode in ("inline", "thread")
        self.mode = mode
        self._pending: Dict[Tuple[str, str], APICall] = {}
        self._order: List[Tuple[str, str]] = []
        self._lock = threading.Lock()
        self._wake = threading.Event()
        self._stop = False
        self._thread: Optional[threading.Thread] = None
        self.executed = 0
        self.merged = 0
        self.errors: List[str] = []
        if mode == "thread":
            self._thread = threading.Thread(target=self._run, daemon=True)
            self._thread.start()

    # -- enqueue (api_dispatcher.go Add) -----------------------------------

    def add(self, call: APICall) -> None:
        if self.mode == "inline":
            self._execute(call)
            return
        key = (call.call_type, call.object_uid)
        skip_key = (CALL_STATUS_PATCH, call.object_uid) \
            if call.call_type == CALL_BINDING else None
        with self._lock:
            if key in self._pending:
                self.merged += 1  # replace: newest call wins its slot
                self._pending[key] = call
            else:
                self._pending[key] = call
                self._order.append(key)
            # A binding makes a queued status patch for the same pod
            # irrelevant (call_queue.go relevance merging).
            if skip_key and skip_key in self._pending:
                self._pending.pop(skip_key)
                self._order.remove(skip_key)
                self.merged += 1
        self._wake.set()

    def _execute(self, call: APICall) -> None:
        try:
            call.execute()
            self.executed += 1
        except Exception as e:  # noqa: BLE001
            self.errors.append(f"{call.call_type}/{call.object_uid}: {e!r}")
            if call.on_error is not None:
                call.on_error(e)

    # -- worker ------------------------------------------------------------

    def _next(self) -> Optional[APICall]:
        with self._lock:
            while self._order:
                key = self._order.pop(0)
                call = self._pending.pop(key, None)
                if call is not None:
                    return call
        return None

    def _run(self) -> None:
        while not self._stop:
            call = self._next()
            if call is None:
                self._wake.wait(timeout=0.05)
                self._wake.clear()
                continue
            self._execute(call)

    def flush(self, timeout: float = 5.0) -> None:
        """Drain everything (test/bench determinism barrier)."""
        if self.mode == "inline":
            return
        import time
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                if not self._order:
                    return
            self._wake.set()
            time.sleep(0.001)

    def close(self) -> None:
        self._stop = True
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=1.0)

    def pending_count(self) -> int:
        with self._lock:
            return len(self._order)
