"""Async API dispatcher: decouples scheduling cycles from API write RTT.

Re-expresses pkg/scheduler/backend/api_dispatcher/ (APIDispatcher
api_dispatcher.go:32, relevance-merging call_queue.go) and the call
implementations in framework/api_calls/ (pod_binding.go:32 PodBindingCall,
pod_status_patch.go). Gated by SchedulerAsyncAPICalls
(kube_features.go:1048).

Execution modes:
- inline  — calls run at enqueue (deterministic; default for tests/bench
  where the "API server" is an in-process dict and there is no RTT to hide);
- thread  — a worker thread drains the queue, overlapping binding writes
  with the next scheduling cycle exactly like the reference's goroutine.

Merging semantics (call_queue.go): one pending slot per (call_type, object
uid); a newly enqueued call replaces a queued one when its relevance is >=
the queued call's (e.g. a binding supersedes a pending status patch).
"""

from __future__ import annotations

import queue
import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

# Call types + relevance (api_calls/ relevances: deletion > binding > patch).
CALL_STATUS_PATCH = "pod_status_patch"
CALL_BINDING = "pod_binding"
CALL_DELETE = "pod_deletion"
RELEVANCE = {CALL_STATUS_PATCH: 1, CALL_BINDING: 2, CALL_DELETE: 3}


@dataclass
class APICall:
    call_type: str
    object_uid: str
    execute: Callable[[], None]
    on_error: Optional[Callable[[Exception], None]] = None
    # Bulk seam (DefaultBinder): a run of consecutive queued calls sharing
    # the SAME bulk_execute callable drains as one batch on the thread
    # worker — one API round-trip (and one worker GIL wakeup) per batch
    # instead of per call. bind_args carries the call's (pod, node_name)
    # for the batch executor. bulk_execute(calls) returns one
    # Optional[Exception] per call, or raises for a whole-batch transport
    # failure (retried under the same budget as single calls — safe because
    # the binding subresource answers same-node replays idempotently).
    bind_args: Optional[tuple] = None
    bulk_execute: Optional[Callable[[List["APICall"]], list]] = None
    # Wire trace context (core/spans.py format_ctx) riding the queued call:
    # a deferred call can execute well behind its enqueue on a loaded
    # shard, so failure records name the ORIGINAL pod trace (see _fail —
    # `trace=<ctx>` in the error log links an async bind failure to its
    # merged cross-process trace in the analyzer).
    trace_ctx: Optional[str] = None

    @property
    def relevance(self) -> int:
        return RELEVANCE.get(self.call_type, 0)

    def _fail(self, err) -> str:
        """Error-log line for a failed execution, trace-attributed."""
        tag = f" trace={self.trace_ctx}" if self.trace_ctx else ""
        return f"{self.call_type}/{self.object_uid}{tag}: {err!r}"


class APIDispatcher:
    def __init__(self, mode: str = "inline", metrics=None, retry=None):
        assert mode in ("inline", "thread")
        self.mode = mode
        self.metrics = metrics  # SchedulerMetrics (async_api_call_* series)
        # Transient-failure retry budget per call (client-go request retry):
        # a bind that hits a connection reset / 5xx replays with backoff
        # BEFORE landing in the error inbox — drain_errors only sees calls
        # that stayed broken through the whole budget. Inline mode shares
        # the config; its sleeps run on the scheduling thread, so the
        # defaults are small (RetryConfig caps well under a watch timeout).
        from .backoff import RetryConfig
        self._retry_cfg = retry or RetryConfig()
        self.retried = 0  # replays across all calls (tests/metrics)
        self._pending: Dict[Tuple[str, str], APICall] = {}
        self._order: List[Tuple[str, str]] = []
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._in_flight = 0
        self._stop = False
        self._thread: Optional[threading.Thread] = None
        self.executed = 0
        self.merged = 0
        self.errors: List[str] = []
        # Thread-mode failures land here instead of running on_error on the
        # worker thread: on_error handlers mutate cache/queue state owned by
        # the scheduling loop, so the loop drains this inbox itself
        # (drain_errors), keeping all cache/queue mutation single-threaded.
        self._error_inbox: List[Tuple[APICall, Exception]] = []
        if mode == "thread":
            self._thread = threading.Thread(target=self._run, daemon=True)
            self._thread.start()

    # -- enqueue (api_dispatcher.go Add) -----------------------------------

    def add(self, call: APICall) -> None:
        if self.mode == "inline":
            self._execute(call)
            return
        key = (call.call_type, call.object_uid)
        skip_key = (CALL_STATUS_PATCH, call.object_uid) \
            if call.call_type == CALL_BINDING else None
        with self._cv:
            if key in self._pending:
                self.merged += 1  # replace: newest call wins its slot
                self._pending[key] = call
            else:
                self._pending[key] = call
                self._order.append(key)
            # A binding makes a queued status patch for the same pod
            # irrelevant (call_queue.go relevance merging).
            if skip_key and skip_key in self._pending:
                self._pending.pop(skip_key)
                self._order.remove(skip_key)
                self.merged += 1
            self._cv.notify_all()

    def _execute(self, call: APICall, defer_errors: bool = False) -> None:
        import time as _time
        _t0 = _time.perf_counter()
        delays = self._retry_cfg.delays()
        while True:
            try:
                call.execute()
                self.executed += 1
                if self.metrics is not None:
                    self.metrics.async_api_call_execution_total.inc(
                        call.call_type, "success")
                    self.metrics.async_api_call_execution_duration.observe(
                        _time.perf_counter() - _t0, call.call_type, "success")
                return
            except Exception as e:  # noqa: BLE001
                if self._retry_cfg.retriable(e):
                    try:
                        delay = next(delays)
                    except StopIteration:
                        pass  # budget exhausted: fall through to the inbox
                    else:
                        self.retried += 1
                        if self.metrics is not None:
                            self.metrics.async_api_call_retries.inc(
                                call.call_type)
                        _time.sleep(delay)
                        continue
                self.errors.append(call._fail(e))
                if self.metrics is not None:
                    self.metrics.async_api_call_execution_total.inc(
                        call.call_type, "error")
                    self.metrics.async_api_call_execution_duration.observe(
                        _time.perf_counter() - _t0, call.call_type, "error")
                if call.on_error is None:
                    return
                if defer_errors:
                    with self._cv:
                        self._error_inbox.append((call, e))
                else:
                    call.on_error(e)
                return

    # -- worker ------------------------------------------------------------

    # Batch cap: bounds the server-side write-lock hold per bulk request
    # (~0.3ms/bind), so one shard's burst never stalls peers' binds or
    # lease renews for more than a few tens of ms.
    BULK_MAX = 128

    def _run(self) -> None:
        while not self._stop:
            with self._cv:
                call = None
                while self._order:
                    key = self._order.pop(0)
                    call = self._pending.pop(key, None)
                    if call is not None:
                        break
                if call is None:
                    self._cv.wait(timeout=0.05)
                    continue
                batch = [call]
                # Drain the run of batchable calls queued behind it (stop at
                # the first call with a different executor: cross-type FIFO
                # order is preserved — a queued status patch still lands
                # after the binds enqueued before it).
                while (call.bulk_execute is not None and self._order
                        and len(batch) < APIDispatcher.BULK_MAX):
                    nxt = self._pending.get(self._order[0])
                    if nxt is None:
                        self._order.pop(0)  # merged-away slot
                        continue
                    # == not `is`: bound methods are materialized fresh on
                    # every attribute access, so identity never matches —
                    # method equality compares (__self__, __func__).
                    if nxt.bulk_execute != call.bulk_execute:
                        break
                    self._order.pop(0)
                    self._pending.pop((nxt.call_type, nxt.object_uid), None)
                    batch.append(nxt)
                self._in_flight += 1
            try:
                if len(batch) > 1:
                    self._execute_bulk(batch)
                else:
                    self._execute(call, defer_errors=True)
            finally:
                with self._cv:
                    self._in_flight -= 1
                    self._cv.notify_all()

    def _execute_bulk(self, calls: List[APICall]) -> None:
        """One batch through bulk_execute, with the same transient-retry
        budget as _execute; per-item failures land in the error inbox for
        the scheduling loop to drain (never run on this thread)."""
        import time as _time
        _t0 = _time.perf_counter()
        delays = self._retry_cfg.delays()
        while True:
            try:
                results = calls[0].bulk_execute(calls)
                break
            except Exception as e:  # noqa: BLE001 - whole-batch transport
                if self._retry_cfg.retriable(e):
                    try:
                        delay = next(delays)
                    except StopIteration:
                        pass  # budget exhausted: every call fails below
                    else:
                        self.retried += 1
                        if self.metrics is not None:
                            self.metrics.async_api_call_retries.inc(
                                calls[0].call_type)
                        _time.sleep(delay)
                        continue
                results = [e] * len(calls)
                break
        dur = _time.perf_counter() - _t0
        if len(results) < len(calls):  # defensive: short executor response
            results = list(results) + [RuntimeError("short bulk response")] \
                * (len(calls) - len(results))
        deferred = []
        for call, err in zip(calls, results):
            outcome = "success" if err is None else "error"
            if self.metrics is not None:
                self.metrics.async_api_call_execution_total.inc(
                    call.call_type, outcome)
                self.metrics.async_api_call_execution_duration.observe(
                    dur / len(calls), call.call_type, outcome)
            if err is None:
                self.executed += 1
                continue
            self.errors.append(call._fail(err))
            if call.on_error is not None:
                deferred.append((call, err))
        if deferred:
            with self._cv:
                self._error_inbox.extend(deferred)

    def has_errors(self) -> bool:
        """Cheap emptiness probe (list read is atomic under the GIL)."""
        return bool(self._error_inbox)

    def drain_errors(self) -> List[Tuple[APICall, Exception]]:
        """Take pending (call, exception) failures. The scheduling loop calls
        this and runs on_error handlers on its own thread."""
        with self._cv:
            out, self._error_inbox = self._error_inbox, []
        return out

    def flush(self, timeout: float = 5.0) -> None:
        """True drain barrier: waits until the queue is empty AND no call is
        mid-execution on the worker (test/bench determinism barrier)."""
        if self.mode == "inline":
            return
        with self._cv:
            self._cv.wait_for(
                lambda: not self._order and self._in_flight == 0, timeout=timeout)

    def close(self) -> None:
        self._stop = True
        with self._cv:
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=1.0)

    def pending_count(self) -> int:
        with self._lock:
            return len(self._order)

    def idle(self) -> bool:
        """Nothing queued and nothing mid-execution (inline mode executes at
        enqueue, so it is always idle)."""
        if self.mode == "inline":
            return True
        with self._lock:
            return not self._order and self._in_flight == 0
