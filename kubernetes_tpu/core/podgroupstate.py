"""Persistent scheduled-group-pods store (the fork's
backend/podgroupstate/podgroupstate.go, 573 LoC, reduced): a
generation-versioned index of BOUND pods per PodGroup, maintained
incrementally from the watch feed instead of re-scanned O(all pods) per
group cycle. Placement generation and PodGroupPodsCount scoring read it to
pin a partially-scheduled gang's topology domain and to count its members.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

from ..api.types import Pod


class PodGroupState:
    """group key -> {pod uid: pod} over bound (node-assigned) group members.
    Single-writer (the scheduling loop's event handlers); `generation`
    advances on every mutation so per-cycle consumers can snapshot-compare
    (podgroupstate.go's generation contract)."""

    def __init__(self):
        self._by_group: Dict[Tuple[str, str], Dict[str, Pod]] = {}
        self.generation = 0

    def _key(self, pod: Pod) -> Tuple[str, str]:
        return (pod.namespace, pod.pod_group)

    def record_bound(self, pod: Pod) -> None:
        if not pod.pod_group or not pod.node_name:
            return
        members = self._by_group.setdefault(self._key(pod), {})
        if pod.uid not in members:
            self.generation += 1  # benign re-updates of a member don't bump
        members[pod.uid] = pod

    def remove(self, pod: Pod) -> None:
        if not pod.pod_group:
            return
        members = self._by_group.get(self._key(pod))
        if members and members.pop(pod.uid, None) is not None:
            if not members:
                del self._by_group[self._key(pod)]
            self.generation += 1

    def scheduled_pods(self, namespace: str, group_name: str) -> List[Pod]:
        return list(self._by_group.get((namespace, group_name), {}).values())

    def count(self, namespace: str, group_name: str) -> int:
        return len(self._by_group.get((namespace, group_name), {}))
