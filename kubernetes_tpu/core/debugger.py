"""Cache debugger: dump + compare scheduler state against the API truth.

Re-expresses pkg/scheduler/backend/cache/debugger/ (debugger.go:59
ListenForSignal — SIGUSR2 triggers CompareCache + Dump): the comparer diffs
the scheduler cache against the clientset's authoritative objects (the
informer stand-in), the dumper renders queue + cache contents.
"""

from __future__ import annotations

import signal
from typing import Dict, List


class CacheDebugger:
    def __init__(self, scheduler):
        self.scheduler = scheduler

    # -- comparer (debugger/comparer.go) -----------------------------------

    def compare(self) -> List[str]:
        """Differences between the cache and the clientset truth."""
        s = self.scheduler
        problems: List[str] = []
        api_nodes = set(s.clientset.nodes)
        cache_nodes = {n for n, ni in s.cache.nodes.items() if ni.node is not None}
        for missing in api_nodes - cache_nodes:
            problems.append(f"node {missing} in API but not in cache")
        for stale in cache_nodes - api_nodes:
            problems.append(f"node {stale} in cache but deleted from API")
        api_assigned = {
            uid: p.node_name for uid, p in s.clientset.pods.items() if p.node_name}
        cache_pods = {
            uid: st.pod.node_name for uid, st in s.cache.pod_states.items()}
        for uid, node in api_assigned.items():
            got = cache_pods.get(uid)
            if got is None:
                problems.append(f"pod {uid} assigned to {node} in API but not cached")
            elif got != node:
                problems.append(f"pod {uid} cached on {got}, API says {node}")
        for uid in set(cache_pods) - set(api_assigned):
            if uid not in s.cache.assumed_pods:
                problems.append(f"pod {uid} cached but not assigned in API")
        return problems

    # -- dumper (debugger/dumper.go) ---------------------------------------

    def dump(self) -> str:
        s = self.scheduler
        lines = []
        member = getattr(s, "shard_member", None)
        if member is not None:
            # Shard plane: ownership, lease ages, and conflict/requeue
            # counts — enough to tell a wedged shard (stale own lease, zero
            # requeues) from a conflict-storming one from one dump.
            lines.append(
                f"Shard member {member.identity}: "
                f"owned={sorted(member.owned)} of {member.count} shards, "
                f"renewals={member.renewals} adoptions={member.adoptions}")
            for lease in member.lease_view():
                lines.append(
                    f"  lease {lease['name']}: holder={lease['holder'] or '-'}"
                    f" age={lease['ageSeconds']:.2f}s"
                    f"/{lease['leaseDurationSeconds']:.2f}s"
                    f"{' EXPIRED' if lease['expired'] else ''}")
            lines.append(
                f"  bind_conflicts={getattr(s, 'bind_conflicts', 0)} "
                f"conflict_requeues={getattr(s, 'conflict_requeues', 0)}")
        lines.append("Dump of cached NodeInfo:")
        for name, ni in s.cache.nodes.items():
            lines.append(
                f"  {name}: pods={len(ni.pods)} "
                f"requested(cpu={ni.requested.milli_cpu}m mem={ni.requested.memory}) "
                f"allocatable(cpu={ni.allocatable.milli_cpu}m mem={ni.allocatable.memory}) "
                f"gen={ni.generation}")
        lines.append(f"Assumed pods: {sorted(s.cache.assumed_pods)}")
        active, backoff, unsched = s.queue.pending_counts()
        lines.append(f"Queue: active={active} backoff={backoff} unschedulable={unsched}")
        for q in s.queue.active_q.items():
            lines.append(f"  activeQ: {q.pod.namespace}/{q.pod.name}")
        for q in s.queue.backoff_q.items():
            lines.append(f"  backoffQ: {q.pod.namespace}/{q.pod.name}")
        for uid, q in s.queue.unschedulable.items():
            lines.append(
                f"  unschedulable: {q.pod.namespace}/{q.pod.name} "
                f"plugins={sorted(q.unschedulable_plugins)}")
        return "\n".join(lines)

    def listen_for_signal(self, signum: int = signal.SIGUSR2) -> None:
        """debugger.go:59 ListenForSignal."""

        def handler(_sig, _frame):
            problems = self.compare()
            print(self.dump())
            for p in problems:
                print("cache mismatch:", p)

        signal.signal(signum, handler)
