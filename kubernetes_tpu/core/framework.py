"""The scheduler framework: extension-point vocabulary, Status codes,
CycleState, and the plugin-dispatch runtime.

Re-expresses the stable plugin API of staging/src/k8s.io/kube-scheduler/framework
(interface.go: PreEnqueue :447, QueueSort :461, PreFilter :520, Filter :549,
PostFilter :578, PreScore :632, Score :653, Reserve :670, PreBind :686,
PostBind :703, Permit :714, Bind :727) and the concrete dispatcher
pkg/scheduler/framework/runtime/framework.go (frameworkImpl :58).

Differences from the reference, by design (TPU-first):
- No goroutine Parallelizer: per-node fan-out is replaced either by plain
  loops (host oracle path) or by one dense pods×nodes device kernel
  (kubernetes_tpu/ops.kernel) surfaced through a BatchEvaluator hook.
- Plugins are duck-typed: a plugin implements an extension point by defining
  the method (pre_filter/filter/score/...), mirroring Go interface checks.
"""

from __future__ import annotations

import math
import random
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from ..api.types import Node, Pod
from .node_info import NodeInfo, PodInfo

MAX_NODE_SCORE = 100
MIN_NODE_SCORE = 0
MAX_TOTAL_SCORE = (1 << 63) - 1

# ---------------------------------------------------------------------------
# Status (staging kube-scheduler framework/types.go Code)
# ---------------------------------------------------------------------------

SUCCESS = 0
ERROR = 1
UNSCHEDULABLE = 2
UNSCHEDULABLE_AND_UNRESOLVABLE = 3
WAIT = 4
SKIP = 5
PENDING = 6


@dataclass
class Status:
    code: int = SUCCESS
    reasons: tuple = ()
    plugin: str = ""
    # Optimistic-binding conflict (HTTP 409 from the binding subresource:
    # AlreadyBound / OutOfCapacity): another scheduler's commit won the
    # shared state. Not an error and not unschedulable — the scheduler
    # requeues through the backoffQ and re-plans against the watch feed.
    conflict: bool = False
    # Flow-control shed (HTTP 429 from the apiserver's admission plane,
    # core/flowcontrol.py): the write never ran — like a conflict, the pod
    # only needs to wait out a backoff (the server's Retry-After horizon),
    # never the unschedulable pool, and never the error log.
    shed: bool = False

    @classmethod
    def bind_conflict(cls, *reasons: str, plugin: str = "") -> "Status":
        return cls(ERROR, tuple(reasons), plugin, conflict=True)

    @classmethod
    def bind_shed(cls, *reasons: str, plugin: str = "") -> "Status":
        return cls(ERROR, tuple(reasons), plugin, shed=True)

    @classmethod
    def unschedulable(cls, *reasons: str, plugin: str = "") -> "Status":
        return cls(UNSCHEDULABLE, tuple(reasons), plugin)

    @classmethod
    def unresolvable(cls, *reasons: str, plugin: str = "") -> "Status":
        return cls(UNSCHEDULABLE_AND_UNRESOLVABLE, tuple(reasons), plugin)

    @classmethod
    def error(cls, *reasons: str, plugin: str = "") -> "Status":
        return cls(ERROR, tuple(reasons), plugin)

    @classmethod
    def skip(cls, plugin: str = "") -> "Status":
        return cls(SKIP, (), plugin)

    def is_success(self) -> bool:
        return self.code == SUCCESS

    def is_skip(self) -> bool:
        return self.code == SKIP

    def is_rejected(self) -> bool:
        return self.code in (UNSCHEDULABLE, UNSCHEDULABLE_AND_UNRESOLVABLE, PENDING)

    def is_unresolvable(self) -> bool:
        return self.code == UNSCHEDULABLE_AND_UNRESOLVABLE

    def message(self) -> str:
        return "; ".join(self.reasons)


OK = Status()

# Distinguishes "memoized as unsignable (None)" from "not memoized" in the
# template-shared signature holder (sign_pod).
_SIG_MISS = object()


# ---------------------------------------------------------------------------
# CycleState (pkg/scheduler/framework/cycle_state.go)
# ---------------------------------------------------------------------------


class CycleState:
    """Per-scheduling-cycle typed KV store + skip sets."""

    __slots__ = ("_data", "skip_filter_plugins", "skip_score_plugins", "skip_pre_bind_plugins",
                 "recorded_plugin_durations")

    def __init__(self):
        self._data: Dict[str, Any] = {}
        self.skip_filter_plugins: set = set()
        self.skip_score_plugins: set = set()
        self.skip_pre_bind_plugins: set = set()
        self.recorded_plugin_durations: Dict[str, float] = {}

    def write(self, key: str, value: Any) -> None:
        self._data[key] = value

    def read(self, key: str) -> Any:
        return self._data.get(key)

    def delete(self, key: str) -> None:
        self._data.pop(key, None)

    def clone(self) -> "CycleState":
        """Clone for what-if simulation (nominated pods, preemption dry runs).
        Mirrors cycle_state.go Clone(): values implementing clone() are deep-
        cloned so simulations can't corrupt the real cycle's plugin state."""
        c = CycleState()
        c._data = {
            k: (v.clone() if hasattr(v, "clone") else v) for k, v in self._data.items()
        }
        c.skip_filter_plugins = set(self.skip_filter_plugins)
        c.skip_score_plugins = set(self.skip_score_plugins)
        c.skip_pre_bind_plugins = set(self.skip_pre_bind_plugins)
        return c


# ---------------------------------------------------------------------------
# Diagnosis (schedule_one.go Diagnosis / NodeToStatus)
# ---------------------------------------------------------------------------


@dataclass
class Diagnosis:
    node_to_status: Dict[str, Status] = field(default_factory=dict)
    absent_nodes_status: Status = field(default_factory=lambda: Status(UNSCHEDULABLE_AND_UNRESOLVABLE))
    unschedulable_plugins: set = field(default_factory=set)
    pending_plugins: set = field(default_factory=set)
    pre_filter_msg: str = ""


class FitError(Exception):
    """schedule_one.go FitError — pod didn't fit any node."""

    def __init__(self, pod: Pod, num_all_nodes: int, diagnosis: Diagnosis):
        self.pod = pod
        self.num_all_nodes = num_all_nodes
        self.diagnosis = diagnosis
        rejected = sum(1 for s in diagnosis.node_to_status.values() if s.is_rejected())
        super().__init__(
            f"0/{num_all_nodes} nodes are available for pod {pod.namespace}/{pod.name} "
            f"({rejected} rejected): {diagnosis.pre_filter_msg}"
        )


# ---------------------------------------------------------------------------
# PreFilterResult (interface.go PreFilterResult — node subset narrowing)
# ---------------------------------------------------------------------------


@dataclass
class PreFilterResult:
    node_names: Optional[set] = None  # None => all nodes

    def all_nodes(self) -> bool:
        return self.node_names is None

    def merge(self, other: "PreFilterResult") -> "PreFilterResult":
        if self.all_nodes() and other.all_nodes():
            return PreFilterResult(None)
        if self.all_nodes():
            return PreFilterResult(set(other.node_names))
        if other.all_nodes():
            return PreFilterResult(set(self.node_names))
        return PreFilterResult(self.node_names & other.node_names)


@dataclass
class NodeScore:
    name: str
    score: int


# ---------------------------------------------------------------------------
# Framework (profile) runtime
# ---------------------------------------------------------------------------


def default_normalize_score(max_priority: int, reverse: bool, scores: List[NodeScore]) -> None:
    """plugins/helper/normalize_score.go DefaultNormalizeScore."""
    max_count = 0
    for s in scores:
        if s.score > max_count:
            max_count = s.score
    if max_count == 0:
        if reverse:
            for s in scores:
                s.score = max_priority
        return
    for s in scores:
        score = max_priority * s.score // max_count
        if reverse:
            score = max_priority - score
        s.score = score


@dataclass
class Placement:
    """A named candidate node subset for pod-group scheduling (the fork's
    staging kube-scheduler framework Placement; topology_placement.go
    produces one per topology domain)."""

    name: str
    node_names: List[str]


@dataclass
class PlacementProgress:
    """Mid-simulation group progress handed to PlacementFeasible plugins
    (framework.go:2160; GangScheduling gates on scheduled >= min_count)."""

    scheduled: int = 0
    failed: int = 0
    total: int = 0


@dataclass
class PodGroupAssignments:
    """One successful placement simulation: the proposed member→node
    assignments plus the placement's node views — the input PlacementScore
    plugins score (staging framework PodGroupAssignments)."""

    placement: Placement
    proposed: List[Tuple[Pod, str]] = field(default_factory=list)
    nodes: List[Any] = field(default_factory=list)  # NodeInfo


class Framework:
    """One profile's plugin set + dispatch (frameworkImpl equivalent).

    `plugins` is an ordered list of (plugin_instance, weight). Extension-point
    membership is derived from which methods each plugin defines.
    """

    def __init__(
        self,
        profile_name: str = "default-scheduler",
        plugins: Optional[Sequence[Tuple[Any, int]]] = None,
        snapshot_provider: Optional[Callable[[], Any]] = None,
        rng: Optional[random.Random] = None,
    ):
        self.profile_name = profile_name
        self._plugins: List[Tuple[Any, int]] = list(plugins or [])
        self.snapshot_provider = snapshot_provider
        self.rng = rng or random.Random(0)
        self.pre_enqueue_plugins = self._having("pre_enqueue")
        self.queue_sort_plugins = self._having("less")
        self.pre_filter_plugins = self._having("pre_filter")
        self.filter_plugins = self._having("filter")
        self.post_filter_plugins = self._having("post_filter")
        self.pre_score_plugins = self._having("pre_score")
        self.score_plugins = self._having_weighted("score")
        self.reserve_plugins = self._having("reserve")
        self.unreserve_plugins = self._having("unreserve")
        self.permit_plugins = self._having("permit")
        self.pre_bind_plugins = self._having("pre_bind")
        self.bind_plugins = self._having("bind")
        self.post_bind_plugins = self._having("post_bind")
        self.sign_plugins = self._having("sign")
        # Pod-group / placement extension points (fork additions —
        # runtime/framework.go:1212 RunPodGroupPostFilterPlugins, :2208
        # RunPlacementGeneratePlugins, :2160 RunPlacementFeasiblePlugins,
        # :1625 RunPlacementScorePlugins).
        self.placement_generate_plugins = self._having("generate_placements")
        self.placement_feasible_plugins = self._having("placement_feasible")
        self.placement_score_plugins = self._having_weighted("score_placement")
        self.pod_group_post_filter_plugins = self._having("pod_group_post_filter")
        # Per-plugin QueueingHintFn registrations (EventsToRegister →
        # ClusterEventWithHint, framework/types.go:217): plugin name →
        # {event: [hint fn or None]}. Plugins without events_to_register
        # fall back to the queue's static event map.
        self.queueing_hint_map: Dict[str, Dict[str, List[Any]]] = {}
        for p, _w in self._plugins:
            etr = getattr(p, "events_to_register", None)
            if etr is None:
                continue
            m: Dict[str, List[Any]] = {}
            for event, fn in etr():
                m.setdefault(event, []).append(fn)
            self.queueing_hint_map[p.name] = m
        # Optional dense batch evaluator (the TPU backend) — set by
        # kubernetes_tpu/models pipeline when the device profile is active.
        self.batch_evaluator = None

    def _having(self, method: str) -> List[Any]:
        return [p for p, _ in self._plugins if hasattr(p, method)]

    def _having_weighted(self, method: str) -> List[Tuple[Any, int]]:
        return [(p, w) for p, w in self._plugins if hasattr(p, method)]

    def plugin(self, name: str) -> Optional[Any]:
        for p, _ in self._plugins:
            if p.name == name:
                return p
        return None

    # -- queueing ----------------------------------------------------------

    def run_pre_enqueue_plugins(self, pod: Pod) -> Status:
        for p in self.pre_enqueue_plugins:
            st = p.pre_enqueue(pod)
            if not st.is_success():
                st.plugin = p.name
                return st
        return OK

    def less(self, a, b) -> bool:
        """QueueSort comparison via the (single) queue-sort plugin."""
        if self.queue_sort_plugins:
            return self.queue_sort_plugins[0].less(a, b)
        return a.timestamp < b.timestamp

    @property
    def queue_sort_key(self):
        """Tuple-key form of the queue-sort comparison when the plugin
        provides one (heap entries then compare at C speed)."""
        if self.queue_sort_plugins:
            return getattr(self.queue_sort_plugins[0], "sort_key", None)
        return lambda qpi: (qpi.timestamp,)

    # -- filtering ---------------------------------------------------------

    def run_pre_filter_plugins(
        self, state: CycleState, pod: Pod, nodes: Sequence[NodeInfo]
    ) -> Tuple[Optional[PreFilterResult], Status]:
        """runtime/framework.go:934 RunPreFilterPlugins: merge PreFilterResults,
        collect Skip sets, short-circuit on rejection."""
        result: Optional[PreFilterResult] = None
        skipped = set()
        for p in self.pre_filter_plugins:
            r, st = p.pre_filter(state, pod, nodes)
            if st.is_skip():
                skipped.add(p.name)
                continue
            if not st.is_success():
                st.plugin = p.name
                return None, st
            if r is not None and not r.all_nodes():
                result = r if result is None else result.merge(r)
                if not result.node_names:
                    return result, Status.unresolvable(
                        "node(s) didn't satisfy plugin(s) prefilter result", plugin=p.name
                    )
        state.skip_filter_plugins = skipped
        return result, OK

    def run_filter_plugins(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> Status:
        """runtime/framework.go:1105 RunFilterPlugins (per node)."""
        for p in self.filter_plugins:
            if p.name in state.skip_filter_plugins:
                continue
            st = p.filter(state, pod, node_info)
            if not st.is_success():
                st.plugin = p.name
                return st
        return OK

    def run_filter_plugins_with_nominated_pods(
        self, state: CycleState, pod: Pod, node_info: NodeInfo, nominator=None
    ) -> Status:
        """runtime/framework.go:1275: two-pass filter — first pass simulates
        higher/equal-priority nominated pods as if running on the node."""
        nominated = []
        if nominator is not None and node_info.node is not None:
            nominated = [
                pi for pi in nominator.nominated_pods_for_node(node_info.node.name)
                if pi.pod.uid != pod.uid and pi.pod.priority >= pod.priority
            ]
        if nominated:
            state_with = state.clone()
            ni_with = node_info.snapshot_clone()
            for pi in nominated:
                ni_with.add_pod(pi)
                for p in self.pre_filter_plugins:
                    if p.name in state.skip_filter_plugins:
                        continue
                    add_pod = getattr(p, "add_pod", None)
                    if add_pod is not None:
                        st = add_pod(state_with, pod, pi, ni_with)
                        if not st.is_success():
                            st.plugin = p.name
                            return st
            st = self.run_filter_plugins(state_with, pod, ni_with)
            if not st.is_success():
                return st
        return self.run_filter_plugins(state, pod, node_info)

    def run_post_filter_plugins(self, state: CycleState, pod: Pod, filtered_status_map: Dict[str, Status]):
        """runtime/framework.go:1152 — first non-skip result wins."""
        for p in self.post_filter_plugins:
            result, st = p.post_filter(state, pod, filtered_status_map)
            if st.is_success() or st.code == UNSCHEDULABLE_AND_UNRESOLVABLE:
                # copy before stamping: plugins may return shared singletons
                return result, Status(st.code, st.reasons, p.name)
        return None, Status.unschedulable("no postFilter plugin made progress")

    # -- scoring -----------------------------------------------------------

    # -- placement extension points (fork: framework.go:2208,:2160,:1625,
    # :1212) ---------------------------------------------------------------

    def run_placement_generate_plugins(
        self, state: CycleState, group, members, parent: Placement
    ) -> Tuple[List[Placement], Status]:
        """RunPlacementGeneratePlugins: each plugin refines the previous
        round's placements (the reference chains generators through the
        parent placement; with one generator this is one pass)."""
        placements = [parent]
        for p in self.placement_generate_plugins:
            nxt: List[Placement] = []
            for parent_pl in placements:
                out, st = p.generate_placements(state, group, members, parent_pl)
                if not st.is_success():
                    st.plugin = p.name
                    return [], st
                nxt.extend(out)
            placements = nxt
        return placements, OK

    def run_placement_feasible_plugins(
        self, state: CycleState, group, progress: PlacementProgress
    ) -> Status:
        """RunPlacementFeasiblePlugins: group-level gate on the simulation
        outcome (GangScheduling: scheduled >= min_count)."""
        for p in self.placement_feasible_plugins:
            st = p.placement_feasible(state, group, progress)
            if not st.is_success():
                st.plugin = p.name
                return st
        return OK

    def run_placement_score_plugins(
        self, state: CycleState, group, assignments: List[PodGroupAssignments]
    ) -> List[int]:
        """RunPlacementScorePlugins: per-plugin score each candidate
        placement's assignments, normalize, weight, sum — one total per
        placement (deterministic ties: the caller picks the first max)."""
        totals = [0] * len(assignments)
        for p, weight in self.placement_score_plugins:
            scores = []
            for pga in assignments:
                s, st = p.score_placement(state, group, pga)
                if not st.is_success():
                    raise RuntimeError(
                        f"placement score {p.name} failed: {st.message()}")
                scores.append(s)
            norm = getattr(p, "normalize_placement_score", None)
            if norm is not None:
                scores = norm(group, scores)
            for i, s in enumerate(scores):
                totals[i] += weight * s
        return totals

    def run_pod_group_post_filter_plugins(self, state: CycleState, group, members, diagnosis):
        """RunPodGroupPostFilterPlugins (framework.go:1212): give plugins a
        chance to make room for the whole group (pod-group preemption)."""
        for p in self.pod_group_post_filter_plugins:
            result, st = p.pod_group_post_filter(state, group, members, diagnosis)
            if st.is_success() or st.code not in (UNSCHEDULABLE, UNSCHEDULABLE_AND_UNRESOLVABLE):
                st.plugin = p.name
                return result, st
        return None, Status.unschedulable("no pod-group post filter made room")

    def run_pre_score_plugins(self, state: CycleState, pod: Pod, nodes: Sequence[NodeInfo]) -> Status:
        skipped = set()
        for p in self.pre_score_plugins:
            st = p.pre_score(state, pod, nodes)
            if st.is_skip():
                skipped.add(p.name)
                continue
            if not st.is_success():
                st.plugin = p.name
                return st
        state.skip_score_plugins = skipped
        return OK

    def run_score_plugins(
        self, state: CycleState, pod: Pod, nodes: Sequence[NodeInfo]
    ) -> Dict[str, List[NodeScore]]:
        """runtime/framework.go:1405 RunScorePlugins: per-plugin score each
        node, run NormalizeScore, then apply plugin weight."""
        all_scores: Dict[str, List[NodeScore]] = {}
        for p, weight in self.score_plugins:
            if p.name in state.skip_score_plugins:
                continue
            scores = [NodeScore(ni.name, 0) for ni in nodes]
            for i, ni in enumerate(nodes):
                s, st = p.score(state, pod, ni)
                if not st.is_success():
                    raise RuntimeError(f"score plugin {p.name} failed: {st.message()}")
                scores[i].score = s
            normalize = getattr(p, "normalize_score", None)
            if normalize is not None:
                normalize(state, pod, scores)
            for ns in scores:
                if ns.score > MAX_NODE_SCORE or ns.score < MIN_NODE_SCORE:
                    raise RuntimeError(
                        f"plugin {p.name} returns an invalid score {ns.score} for node {ns.name}"
                    )
                ns.score *= weight
            all_scores[p.name] = scores
        return all_scores

    # -- reserve / permit / bind ------------------------------------------

    def run_reserve_plugins_reserve(self, state: CycleState, pod: Pod, node_name: str) -> Status:
        for p in self.reserve_plugins:
            st = p.reserve(state, pod, node_name)
            if not st.is_success():
                st.plugin = p.name
                return st
        return OK

    def run_reserve_plugins_unreserve(self, state: CycleState, pod: Pod, node_name: str) -> None:
        for p in reversed(self.unreserve_plugins):
            p.unreserve(state, pod, node_name)

    def run_permit_plugins(self, state: CycleState, pod: Pod, node_name: str) -> Status:
        for p in self.permit_plugins:
            st = p.permit(state, pod, node_name)
            if st.is_rejected():
                st.plugin = p.name
                return st
            if st.code == WAIT:
                st.plugin = p.name
                return st
            if not st.is_success():
                st.plugin = p.name
                return st
        return OK

    def run_pre_bind_pre_flight(self, state: CycleState, pod: Pod,
                                node_name: str) -> Status:
        """PreBindPreFlight (staging kube-scheduler framework
        interface.go:688-694, runtime/framework.go:1875): ask each PreBind
        plugin whether it intends to do any work for this pod. Plugins
        answering Skip are recorded in state.skip_pre_bind_plugins; returns
        Skip when EVERY PreBind plugin skips (the binding cycle may then
        bypass the PreBind phase entirely — the async-binding enabler)."""
        all_skip = True
        for p in self.pre_bind_plugins:
            flight = getattr(p, "pre_bind_pre_flight", None)
            if flight is None:
                all_skip = False
                continue
            st = flight(state, pod, node_name)
            if st.is_skip():
                state.skip_pre_bind_plugins.add(p.name)
            elif not st.is_success():
                st.plugin = p.name
                return st
            else:
                all_skip = False
        return Status.skip() if all_skip else OK

    def run_pre_bind_plugins(self, state: CycleState, pod: Pod, node_name: str) -> Status:
        for p in self.pre_bind_plugins:
            if p.name in state.skip_pre_bind_plugins:
                continue
            st = p.pre_bind(state, pod, node_name)
            if not st.is_success():
                st.plugin = p.name
                return st
        return OK

    def run_bind_plugins(self, state: CycleState, pod: Pod, node_name: str) -> Status:
        if not self.bind_plugins:
            return Status.error("no bind plugin configured")
        for p in self.bind_plugins:
            st = p.bind(state, pod, node_name)
            if st.is_skip():
                continue
            if st.is_success():
                return st
            # copy before stamping: plugins may return the shared OK/Status
            # singletons, which must never be mutated. `conflict` must ride
            # along — it routes the unwind to the backoffQ requeue.
            return Status(st.code, st.reasons, p.name, conflict=st.conflict)
        return Status.error("all bind plugins skipped")

    def run_post_bind_plugins(self, state: CycleState, pod: Pod, node_name: str) -> None:
        for p in self.post_bind_plugins:
            p.post_bind(state, pod, node_name)

    # -- signatures (OpportunisticBatching / kernel row-block batching) ----

    def sign_pod(self, pod: Pod) -> Optional[tuple]:
        """Pod signature for batch reuse (staging framework/signers.go /
        interface.go:774 SignPlugin). None => unsignable (never batched).

        Memoized two ways:
        - per pod object, keyed by (framework, node_name): pod SPEC objects
          are immutable in place (updates replace the pod object through the
          watch path), and node_name is the only signed field the scheduler
          mutates in place (assume/unwind);
        - per TEMPLATE, when the pod carries a `_sig_shared` holder
          (Pod.clone_from_template): all clones share one memo, so a
          workload of N identical pods signs once, not N times.
        """
        key = (id(self), pod.node_name)
        shared = getattr(pod, "_sig_shared", None)
        if shared is not None:
            hit = shared.get(key, _SIG_MISS)
            if hit is not _SIG_MISS:
                return hit
        else:
            cached = getattr(pod, "_sig_cache", None)
            if cached is not None and cached[0] == key:
                return cached[1]
        sig = []
        out: Optional[tuple] = None
        for p in self.sign_plugins:
            part = p.sign(pod)
            if part is None:
                break
            sig.append((p.name, part))
        else:
            out = tuple(sig) if sig else None
        if shared is not None:
            shared[key] = out
        else:
            pod._sig_cache = (key, out)
        return out
