"""Cross-process pod-lifecycle spans + the crash-safe flight recorder.

A dependency-free, OpenTelemetry-shaped span subsystem: one causal trace
per pod, stitched across every hop of the scheduling pipeline — queue
admission → queue wait → plan build (delta vs full) → device dispatch →
device wait → host commit → bind POST → apiserver WAL append → BOUND
fanout → foreign-shard observation. The reference measures the in-process
half of this with ``framework_extension_point_duration_seconds`` and
utiltrace (schedule_one.go:574); the cross-process half is Dapper-style
context propagation (PAPERS [Dapper]) over the repo's existing wire
surfaces: an ``X-Trace-Context`` header on the binding subresource, a
``tctx`` field on bulk-bind items and slim BOUND events.

Design constraints (this rides paths benchmarked at >10k pods/s):

- **Deterministic head sampling.** A pod's trace id is a keyed hash of its
  uid, and the 1-in-N sampling decision is a pure function of that id — so
  every process (N schedulers + the apiserver) independently agrees which
  pods are sampled with NO coordination, and the wire context only needs
  to carry the force-sample override (conflict/requeue/fallback/adoption
  paths record at 100%).
- **Lock-free recording.** Completed spans append to a per-process ring
  buffer (``collections.deque(maxlen=…)`` — append is GIL-atomic), so the
  reflector thread, the dispatcher worker, apiserver handler threads, and
  the scheduling loop all record without a lock. Unsampled pods pay one
  memoized dict lookup.
- **Record-complete spans.** Almost every span is recorded retroactively
  with a known duration (``record``); live spans exist only as ``with``
  blocks (``span``) or the explicit ``start_span``/``end`` pair that the
  ``span-discipline`` analyzer checker polices (every started span must be
  ended on all paths, and neither spans nor metrics may appear inside
  jit-reachable code).

The flight recorder dumps the span ring plus the last-K events/errors per
process to ``<dir>/flightrec-<pid>.jsonl`` on SIGUSR2, on a StepTrace
slow-step breach, on unhandled crash (excepthook + atexit, with
``faulthandler`` covering native faults), and optionally on a periodic
timer — so a chaos ``kill -9`` (which no handler can observe) still leaves
a recent forensic artifact on disk instead of nothing.

Stage-name taxonomy (the stable contract bench/analyzer share) is pinned
in ``STAGES``/``CORE_CHAIN``; docs/OBSERVABILITY.md is the prose spec.
"""

from __future__ import annotations

import hashlib
import itertools
import json
import os
import threading
import time
from collections import deque
from typing import Dict, Iterable, List, Optional

TRACE_HEADER = "X-Trace-Context"

# The pinned stage names (docs/OBSERVABILITY.md). bench.py --trace and the
# trace analyzer CLI key on these strings; renames are contract breaks.
STAGES = (
    "queue.admission",   # pod entered this scheduler's queue (event)
    "queue.wait",        # admission → pop
    "plan.build",        # session plan acquisition (attrs: kind=full|delta|resume)
    "device.dispatch",   # kernel dispatch enqueue
    "device.wait",       # blocked on the device result fetch
    "host.commit",       # assume/reserve/permit/bind host tail
    "bind.post",         # binding POST leaves the scheduler (attrs: bulk)
    "api.bind",          # apiserver binding subresource commit
    "wal.append",        # durable WAL append of the BOUND event
    "bound.fanout",      # BOUND event fanout to watch streams
    "bound.observe",     # a watcher process decoded the BOUND event
    "pod.e2e",           # admission → bound (feeds the e2e histogram)
)
# A bound pod's minimal complete chain. Device stages are optional (host-
# path pods legitimately skip them); observe spans prove the fanout landed.
CORE_CHAIN = ("queue.wait", "host.commit", "bind.post", "api.bind",
              "wal.append", "bound.fanout")
# Always-sampled forensic stages (recorded with force=True contexts).
FORCED_STAGES = ("bind.conflict", "device.fallback", "shard.adopt",
                 "trace.slow_step", "replication.promote")

_SAMPLE_ENV = "TPU_SCHED_TRACE_SAMPLE"
_ENABLE_ENV = "TPU_SCHED_TRACE"
DEFAULT_SAMPLE_N = 16


class SpanContext:
    """Trace identity + the sampling verdict. ``trace_id`` is 16 hex chars,
    derived from the pod uid, identical in every process."""

    __slots__ = ("trace_id", "sampled")

    def __init__(self, trace_id: str, sampled: bool):
        self.trace_id = trace_id
        self.sampled = sampled


def trace_id_for(uid: str) -> str:
    """Deterministic 64-bit trace id (blake2b, not Python hash() — which is
    per-process seeded and would break cross-process agreement)."""
    return hashlib.blake2b(uid.encode(), digest_size=8).hexdigest()


def format_ctx(ctx: SpanContext) -> str:
    """Wire form for X-Trace-Context / tctx fields: ``<trace_id>-<flags>``
    (flags 01 = sampled, the W3C traceparent flag octet)."""
    return f"{ctx.trace_id}-{'01' if ctx.sampled else '00'}"


def parse_ctx(wire: str) -> Optional[SpanContext]:
    tid, _, flags = wire.partition("-")
    if len(tid) != 16:
        return None
    return SpanContext(tid, flags != "00")


class Span:
    """A live span (``start_span``/``end``). Prefer ``record``/``span`` —
    this exists for non-lexical lifetimes, and the span-discipline checker
    requires every start to be ended under with/try coverage."""

    __slots__ = ("name", "ctx", "attrs", "_t0", "_wall")

    def __init__(self, name: str, ctx: SpanContext, attrs: dict):
        self.name = name
        self.ctx = ctx
        self.attrs = attrs
        self._t0 = time.perf_counter()
        self._wall = time.time()


class _ScopedSpan:
    """``with tracer.span(...)`` — records on exit, error status on raise."""

    __slots__ = ("_tracer", "_span")

    def __init__(self, tracer: "SpanRecorder", span: Optional[Span]):
        self._tracer = tracer
        self._span = span

    def __enter__(self):
        return self._span

    def __exit__(self, exc_type, _exc, _tb):
        if self._span is not None:
            if exc_type is not None:
                self._span.attrs["error"] = exc_type.__name__
            self._tracer.end(self._span)
        return False


class SpanRecorder:
    """The per-process tracer: head-sampled, ring-buffered, lock-free."""

    def __init__(self, capacity: int = 8192, sample_n: Optional[int] = None,
                 proc: str = "", enabled: Optional[bool] = None):
        if sample_n is None:
            try:
                sample_n = int(os.environ.get(_SAMPLE_ENV,
                                              str(DEFAULT_SAMPLE_N)))
            except ValueError:
                sample_n = DEFAULT_SAMPLE_N
        self.sample_n = max(1, sample_n)
        if enabled is None:
            enabled = os.environ.get(_ENABLE_ENV, "1") not in ("0", "false")
        self.enabled = enabled
        self.proc = proc or f"pid{os.getpid()}"
        self.ring: "deque" = deque(maxlen=capacity)
        self.recorded = 0  # total spans accepted (ring may have evicted)
        self._ids = itertools.count(1)
        # uid → base SpanContext memo (bounded; cleared wholesale on cap).
        self._ctx_memo: Dict[str, SpanContext] = {}
        self._ctx_cap = 8192
        self._proc_ctx: Optional[SpanContext] = None

    # -- contexts ----------------------------------------------------------

    def context_for(self, uid: str, force: bool = False) -> SpanContext:
        ctx = self._ctx_memo.get(uid)
        if ctx is None:
            tid = trace_id_for(uid)
            ctx = SpanContext(tid, int(tid, 16) % self.sample_n == 0)
            if len(self._ctx_memo) >= self._ctx_cap:
                self._ctx_memo.clear()
            self._ctx_memo[uid] = ctx
        if force and not ctx.sampled:
            return SpanContext(ctx.trace_id, True)
        return ctx

    def proc_ctx(self) -> SpanContext:
        """Force-sampled process-scoped context for non-pod forensic spans
        (breaker trips, shard adoptions)."""
        if self._proc_ctx is None:
            self._proc_ctx = SpanContext(
                trace_id_for(f"proc:{self.proc}:{os.getpid()}"), True)
        return self._proc_ctx

    def wants(self, ctx: Optional[SpanContext]) -> bool:
        return self.enabled and ctx is not None and ctx.sampled

    # -- recording ---------------------------------------------------------

    def record(self, name: str, ctx: SpanContext, duration: float = 0.0,
               start: Optional[float] = None, parent: str = "",
               **attrs) -> None:
        """Append one COMPLETED span. ``start`` is wall-clock seconds
        (time.time()); None means it ended just now."""
        if not self.wants(ctx):
            return
        if start is None:
            start = time.time() - duration
        self.recorded += 1
        self.ring.append({
            "trace": ctx.trace_id,
            "span": f"{os.getpid():x}.{next(self._ids):x}",
            "parent": parent,
            "name": name,
            "proc": self.proc,
            "pid": os.getpid(),
            "ts": start,
            "dur": duration,
            "attrs": attrs,
        })

    def event(self, name: str, ctx: SpanContext, **attrs) -> None:
        self.record(name, ctx, 0.0, **attrs)

    def span(self, name: str, ctx: SpanContext, **attrs) -> _ScopedSpan:
        """Scoped live span: ``with tracer.span("api.bind", ctx): ...``."""
        live = Span(name, ctx, attrs) if self.wants(ctx) else None
        return _ScopedSpan(self, live)

    def start_span(self, name: str, ctx: SpanContext,
                   **attrs) -> Optional[Span]:
        """Open a live span for a non-lexical lifetime. The span-discipline
        checker requires a matching ``end`` reached on all paths."""
        if not self.wants(ctx):
            return None
        return Span(name, ctx, attrs)

    def end(self, span: Optional[Span]) -> None:
        if span is None:
            return
        self.record(span.name, span.ctx,
                    time.perf_counter() - span._t0, start=span._wall,
                    **span.attrs)

    # -- export ------------------------------------------------------------

    def snapshot(self) -> List[dict]:
        for _ in range(4):
            try:
                return list(self.ring)
            except RuntimeError:
                continue  # concurrent append mid-copy: retry on fresh state
        return []

    def clear(self) -> None:
        self.ring.clear()

    def dump_jsonl(self, path: str) -> str:
        """Write the ring as one span per line (atomic tmp+replace)."""
        write_jsonl(path, self.snapshot())
        return path


def write_jsonl(path: str, rows: Iterable[dict]) -> None:
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        for row in rows:
            f.write(json.dumps(row) + "\n")
    os.replace(tmp, path)


def chrome_trace(spans: Iterable[dict]) -> dict:
    """Convert span rows to the Chrome trace_event format (Perfetto/
    chrome://tracing). Processes map to integer pids with process_name
    metadata; spans are complete ('X') events in microseconds."""
    procs: Dict[str, int] = {}
    events: List[dict] = []
    for s in spans:
        proc = s.get("proc", "?")
        pid = procs.setdefault(proc, len(procs) + 1)
        events.append({
            "name": s["name"], "cat": "sched", "ph": "X",
            "ts": s["ts"] * 1e6, "dur": max(s.get("dur", 0.0), 0.0) * 1e6,
            "pid": pid, "tid": 1,
            "args": dict(s.get("attrs", {}), trace=s["trace"]),
        })
    for proc, pid in procs.items():
        events.append({"name": "process_name", "ph": "M", "pid": pid,
                       "args": {"name": proc}})
    return {"traceEvents": events, "displayTimeUnit": "ms"}


# ---------------------------------------------------------------------------
# process-global default tracer
# ---------------------------------------------------------------------------

_DEFAULT: Optional[SpanRecorder] = None


def default_tracer() -> SpanRecorder:
    global _DEFAULT
    if _DEFAULT is None:
        _DEFAULT = SpanRecorder()
    return _DEFAULT


def set_default_tracer(tracer: Optional[SpanRecorder]) -> None:
    """Swap the process tracer (tests; binaries label ``proc`` instead)."""
    global _DEFAULT
    _DEFAULT = tracer


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------

_FLIGHT: Optional["FlightRecorder"] = None


def request_dump(reason: str) -> Optional[str]:
    """Dump through the installed flight recorder (rate-limited); no-op
    when none is installed. The seam StepTrace/ShardMember call so they
    need no direct dependency on recorder wiring."""
    if _FLIGHT is None:
        return None
    return _FLIGHT.dump(reason, rate_limited=True)


class FlightRecorder:
    """Crash-safe forensic dumps: span ring + last-K events/errors/counters
    per process, written to ``<dir>/flightrec-<pid>.jsonl``.

    Triggers: SIGUSR2, StepTrace slow-step breach (via ``request_dump``),
    unhandled crash (sys.excepthook chain + atexit; ``faulthandler`` covers
    native faults into ``flightrec-<pid>.crash``), an optional periodic
    timer — the only trigger that survives SIGKILL chaos (``kill -9``
    leaves the last periodic artifact on disk) — and process exit when
    ``at_exit`` is set. Dumps are atomic (tmp+``os.replace``), so a crash
    mid-dump leaves the previous artifact intact."""

    MIN_DUMP_INTERVAL = 2.0  # rate limit for breach-triggered dumps

    def __init__(self, directory: str, tracer: Optional[SpanRecorder] = None,
                 recorder=None, scheduler=None, apiserver=None,
                 keep_events: int = 256):
        self.directory = directory
        self.tracer = tracer or default_tracer()
        self.recorder = recorder      # tracing.EventRecorder (optional)
        self.scheduler = scheduler    # core.Scheduler (optional)
        self.apiserver = apiserver    # core.apiserver.APIServer (optional)
        self.keep_events = keep_events
        self.path = os.path.join(directory, f"flightrec-{os.getpid()}.jsonl")
        self.dumps = 0
        self._last_dump = 0.0
        self._crashed = False
        self._prev_excepthook = None
        self._stop = threading.Event()
        self._timer: Optional[threading.Thread] = None
        # Serializes dumps across the autodump thread, request_dump callers,
        # the SIGUSR2 handler, and shutdown. Non-blocking acquire: a dump
        # already in flight makes a concurrent one redundant, and a SIGNAL
        # handler interrupting a main-thread dump must skip, not deadlock.
        self._dump_lock = threading.Lock()

    # -- triggers ----------------------------------------------------------

    def install(self, sigusr2: bool = True, on_crash: bool = True,
                at_exit: bool = False,
                autodump_interval: float = 0.0) -> "FlightRecorder":
        global _FLIGHT
        _FLIGHT = self
        os.makedirs(self.directory, exist_ok=True)
        if sigusr2:
            self._install_sigusr2()
        if on_crash:
            self._install_crash_hooks(at_exit)
        if autodump_interval > 0:
            self._timer = threading.Thread(
                target=self._autodump_loop, args=(autodump_interval,),
                name="flightrec-autodump", daemon=True)
            self._timer.start()
        return self

    def _install_sigusr2(self) -> None:
        import signal
        prev = signal.getsignal(signal.SIGUSR2)

        def handler(signum, frame):
            self.dump("sigusr2")
            if callable(prev):  # chain (the cache debugger may also listen)
                prev(signum, frame)

        try:
            signal.signal(signal.SIGUSR2, handler)
        except ValueError:
            pass  # not the main thread: signal triggers unavailable

    def _install_crash_hooks(self, at_exit: bool) -> None:
        import atexit
        import faulthandler
        import sys
        try:
            # Native faults (segfault/abort) can't run Python hooks; leave
            # the interpreter-level dump beside the JSONL artifact.
            self._crash_file = open(  # noqa: SIM115 - must outlive install
                os.path.join(self.directory,
                             f"flightrec-{os.getpid()}.crash"), "w")
            faulthandler.enable(self._crash_file)
        except (OSError, RuntimeError):
            pass
        self._prev_excepthook = sys.excepthook

        def hook(exc_type, exc, tb):
            self._crashed = True
            try:
                self.dump("crash", error=f"{exc_type.__name__}: {exc}")
            except Exception:  # noqa: BLE001 - never mask the real crash
                pass
            (self._prev_excepthook or sys.__excepthook__)(exc_type, exc, tb)

        sys.excepthook = hook
        atexit.register(self._atexit_dump, at_exit)

    def _atexit_dump(self, always: bool) -> None:
        if always or self._crashed:
            try:
                self.dump("exit" if not self._crashed else "crash-exit")
            except Exception:  # noqa: BLE001 - exiting anyway
                pass

    def _autodump_loop(self, interval: float) -> None:
        last_recorded = -1
        while not self._stop.wait(interval):
            try:
                # Skip unchanged rings: serializing an 8k-span ring costs
                # tens of ms of GIL — pointless when nothing new happened
                # (idle shard, quiet apiserver).
                if self.tracer.recorded == last_recorded:
                    continue
                last_recorded = self.tracer.recorded
                self.dump("periodic")
            except Exception:  # noqa: BLE001 - keep the timer alive
                pass

    def close(self) -> None:
        global _FLIGHT
        self._stop.set()
        if self._timer is not None:
            self._timer.join(timeout=2)
            self._timer = None
        if _FLIGHT is self:
            _FLIGHT = None

    # -- the dump ----------------------------------------------------------

    def dump(self, reason: str, rate_limited: bool = False,
             error: str = "") -> Optional[str]:
        now = time.monotonic()
        if rate_limited and now - self._last_dump < self.MIN_DUMP_INTERVAL:
            return None
        if not self._dump_lock.acquire(blocking=False):
            return None  # a dump is already being produced
        try:
            return self._dump_locked(reason, now, error)
        finally:
            self._dump_lock.release()

    def _dump_locked(self, reason: str, now: float, error: str) -> str:
        self._last_dump = now
        rows: List[dict] = [{
            "kind": "meta", "reason": reason, "pid": os.getpid(),
            "proc": self.tracer.proc, "time": time.time(),
            "dump_seq": self.dumps, "error": error,
        }]
        for span in self.tracer.snapshot():
            rows.append(dict(span, kind="span"))
        if self.recorder is not None:
            for ev in self.recorder.recent(limit=self.keep_events):
                rows.append({
                    "kind": "event", "object": ev.object_key,
                    "reason": ev.reason, "type": ev.type,
                    "message": ev.message, "count": ev.count,
                    "ts": ev.timestamp})
        rows.extend(self._scheduler_rows())
        rows.extend(self._apiserver_rows())
        os.makedirs(self.directory, exist_ok=True)
        write_jsonl(self.path, rows)
        self.dumps += 1
        return self.path

    def _scheduler_rows(self) -> List[dict]:
        s = self.scheduler
        if s is None:
            return []
        rows = [{"kind": "counters",
                 "attempts": s.attempts, "scheduled": s.scheduled,
                 "failures": s.failures,
                 "bind_conflicts": s.bind_conflicts,
                 "conflict_requeues": s.conflict_requeues,
                 "state_unwinds": s.state_unwinds,
                 "device_scheduled": getattr(s, "device_scheduled", 0),
                 "host_path_pods": getattr(s, "host_path_pods", 0)}]
        for line in list(s.error_log)[-self.keep_events:]:
            rows.append({"kind": "error", "message": line})
        member = getattr(s, "shard_member", None)
        if member is not None:
            rows.append({"kind": "shard",
                         "owned": sorted(member.owned),
                         "adoptions": member.adoptions,
                         "handbacks": member.handbacks,
                         "renewals": member.renewals})
        return rows

    def _apiserver_rows(self) -> List[dict]:
        a = self.apiserver
        if a is None:
            return []
        return [{"kind": "counters",
                 "bind_conflicts": a.bind_conflicts,
                 "capacity_conflicts": a.capacity_conflicts,
                 "lease_conflicts": a.lease_conflicts,
                 "lease_transitions": a.lease_transitions,
                 "resumed_watches": a.resumed_watches,
                 "relisted_watches": a.relisted_watches,
                 "pods": len(a.store.pods), "nodes": len(a.store.nodes)}]
