"""PersistentVolume controller analogue: the control loop that binds claims
to volumes OUTSIDE the scheduler.

Re-expresses the kube-controller-manager persistentvolume controller surface
the scheduler's VolumeBinding plugin interlocks with
(pkg/controller/volume/persistentvolume/pv_controller.go semantics, reduced
to the scheduler-relevant contract):

- IMMEDIATE-mode unbound claims bind to the smallest matching available PV
  as soon as both exist (syncUnboundClaim → findBestMatchForClaim); the
  scheduler refuses pods whose immediate claims are still unbound
  (volume_binding.go PreFilter ERR_UNBOUND_IMMEDIATE).
- WAIT_FOR_FIRST_CONSUMER claims wait until the scheduler selects a node and
  writes the `volume.kubernetes.io/selected-node` annotation (the PreBind
  side of binder.go BindPodVolumes); the controller then provisions a PV
  with node affinity for that node and binds it.

The controller subscribes to the clientset's storage events, so newly
created claims/volumes reconcile immediately — the informer-driven shape of
the reference collapsed to synchronous callbacks (SURVEY.md §4.2 fake
control-plane layer).
"""

from __future__ import annotations

from typing import Optional

from ..api.storage import (
    IMMEDIATE,
    WAIT_FOR_FIRST_CONSUMER,
    PersistentVolume,
    PersistentVolumeClaim,
)
from ..api.types import NodeSelector, NodeSelectorTerm
from ..api.labels import IN, Requirement

BIND_COMPLETED = "pv.kubernetes.io/bind-completed"
SELECTED_NODE = "volume.kubernetes.io/selected-node"


class PVController:
    """Bind/provision loop. Attach to a FakeClientset; every storage write
    (and every explicit sync()) reconciles all unbound claims."""

    def __init__(self, clientset):
        self.cs = clientset
        self.binds = 0
        self.provisions = 0
        clientset.attach_pv_controller(self)
        clientset.on_storage_event(self._on_storage_event)

    # -- reconcile ---------------------------------------------------------

    def _on_storage_event(self, kind: str, obj) -> None:
        if kind in ("pv", "pvc", "storage_class"):
            self.sync()

    def sync(self) -> int:
        """One reconcile pass; returns the number of claims progressed."""
        n = 0
        for pvc in list(self.cs.pvcs.values()):
            if pvc.volume_name:
                continue
            mode = self._binding_mode(pvc)
            if mode == WAIT_FOR_FIRST_CONSUMER:
                node = pvc.annotations.get(SELECTED_NODE, "")
                if node:
                    self.provision(pvc, node)
                    n += 1
                continue
            pv = self._find_best_match(pvc)
            if pv is not None:
                self._bind(pvc, pv)
                n += 1
        return n

    def _binding_mode(self, pvc: PersistentVolumeClaim) -> str:
        sc = self.cs.storage_classes.get(pvc.storage_class)
        return sc.volume_binding_mode if sc is not None else IMMEDIATE

    def _find_best_match(self, pvc: PersistentVolumeClaim) -> Optional[PersistentVolume]:
        """findBestMatchForClaim: smallest available PV satisfying
        class/modes/capacity (node affinity is the scheduler's concern for
        delayed claims; immediate claims bind regardless of topology, which
        is exactly the historical immediate-mode pitfall the reference
        preserves)."""
        best = None
        for pv in self.cs.pvs.values():
            if pv.claim_ref:
                continue
            if pv.storage_class != pvc.storage_class:
                continue
            if not set(pvc.access_modes) <= set(pv.access_modes):
                continue
            if pv.capacity < pvc.request:
                continue
            if best is None or pv.capacity < best.capacity:
                best = pv
        return best

    # -- bind / provision --------------------------------------------------

    def _bind(self, pvc: PersistentVolumeClaim, pv: PersistentVolume) -> None:
        pv.claim_ref = pvc.key
        pvc.volume_name = pv.name
        pvc.annotations[BIND_COMPLETED] = "true"
        self.binds += 1

    def provision(self, pvc: PersistentVolumeClaim, node_name: str) -> PersistentVolume:
        """Dynamic provisioning for a WaitForFirstConsumer claim whose
        consumer landed on `node_name`: create a PV pinned to that node
        (the external-provisioner contract) and bind it."""
        sc = self.cs.storage_classes.get(pvc.storage_class)
        pv = PersistentVolume(
            name=f"pvc-{pvc.uid}",
            capacity=pvc.request,
            access_modes=pvc.access_modes,
            storage_class=pvc.storage_class,
            csi_driver=(sc.provisioner if sc is not None else ""),
            node_affinity=NodeSelector(terms=(NodeSelectorTerm(
                match_fields=(Requirement("metadata.name", IN, (node_name,)),)),)),
        )
        self.cs.pvs[pv.name] = pv
        self._bind(pvc, pv)
        self.provisions += 1
        return pv
