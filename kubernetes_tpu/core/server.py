"""The scheduler server: health/readiness/metrics endpoints + the run loop
wiring.

Re-expresses cmd/kube-scheduler/app/server.go (Run :183 — /healthz,/readyz
:208-229, leader election :310-342, /metrics :376) over http.server. The
SchedulerServer owns a scheduler, a leader elector, and the cache debugger;
serve() exposes the endpoints, run_forever() drives the scheduling loop while
leading.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from .debugger import CacheDebugger
from .leaderelection import LeaderElector, LeaseStore


class SchedulerServer:
    def __init__(self, scheduler, identity: str = "scheduler-0",
                 lease_store: Optional[LeaseStore] = None,
                 leader_elect: bool = False):
        self.scheduler = scheduler
        self.debugger = CacheDebugger(scheduler)
        self.elector: Optional[LeaderElector] = None
        if leader_elect:
            self.elector = LeaderElector(
                lease_store or LeaseStore(), identity=identity)
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._ready = False

    # -- health (server.go:208-229) ----------------------------------------

    def healthz(self) -> bool:
        return True

    def readyz(self) -> bool:
        # informer-sync analogue: the fake clientset fans out synchronously,
        # so readiness = event handlers wired + (when electing) leadership
        # watchdog alive.
        return self._ready

    def mark_ready(self) -> None:
        self._ready = True

    # -- http --------------------------------------------------------------

    def serve(self, port: int = 0) -> int:
        """Start the HTTP endpoints on `port` (0 = ephemeral); returns the
        bound port."""
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def do_GET(self):
                if self.path == "/healthz":
                    self._respond(200 if server.healthz() else 500, "ok")
                elif self.path == "/readyz":
                    self._respond(200 if server.readyz() else 503,
                                  "ok" if server.readyz() else "not ready")
                elif self.path == "/metrics":
                    self._respond(200, server.scheduler.expose_metrics(),
                                  "text/plain; version=0.0.4")
                elif self.path == "/metrics/resources":
                    self._respond(200, server.expose_resource_metrics(),
                                  "text/plain; version=0.0.4")
                elif self.path == "/debug/cache":
                    self._respond(200, server.debugger.dump())
                elif self.path == "/debug/comparer":
                    self._respond(200, json.dumps(server.debugger.compare()))
                elif self.path.startswith("/debug/events"):
                    # /debug/events[?object=<ns>/<name>]: the scheduler's
                    # EventRecorder buffer NEWEST-FIRST (recorder.recent()
                    # re-sorts by live timestamp — aggregated events mutate
                    # count/timestamp in place, so insertion order lies).
                    self._respond(200, server.expose_events(self.path),
                                  "application/json")
                else:
                    self._respond(404, "not found")

            def _respond(self, code, body, ctype="text/plain"):
                data = body.encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

        self._httpd = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        t = threading.Thread(target=self._httpd.serve_forever, daemon=True)
        t.start()
        self.mark_ready()
        return self._httpd.server_address[1]

    def expose_events(self, path: str) -> str:
        """/debug/events?object=<key> — the recorder buffer newest-first
        (client-go event read surface, collapsed to the debug plane)."""
        _, _, query = path.partition("?")
        object_key = None
        for part in query.split("&"):
            if part.startswith("object="):
                from urllib.parse import unquote
                object_key = unquote(part.split("=", 1)[1])
        events = self.scheduler.recorder.recent(object_key)
        return json.dumps([
            {"object": e.object_key, "type": e.type, "reason": e.reason,
             "message": e.message, "count": e.count,
             "timestamp": e.timestamp}
            for e in events])

    def expose_resource_metrics(self) -> str:
        """/metrics/resources (app/server.go:376-379 →
        pkg/scheduler/metrics/resources): per-pod resource requests as
        kube_pod_resource_request series, by namespace/pod/node/phase —
        one shared renderer with the apiserver's watch-cache endpoint
        (core/watchcache.py), so the two expositions cannot drift."""
        from .watchcache import RESOURCE_METRICS_HEADER, resource_request_lines
        cs = self.scheduler.clientset
        lines = list(RESOURCE_METRICS_HEADER)
        bindings = getattr(cs, "bindings", {})
        for pod in cs.pods.values():
            req = pod.resource_request()
            # Pending pods get an EMPTY node label (the reference's
            # kube_pod_resource_request convention) — `or ""` keeps a None
            # node_name from rendering as the literal string "None".
            node = bindings.get(pod.uid) or pod.node_name or ""
            lines.extend(resource_request_lines(
                pod.namespace, pod.name, node,
                req.milli_cpu, float(req.memory), req.scalar_resources))
        return "\n".join(lines) + "\n"

    def shutdown(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd = None

    # -- run loop ----------------------------------------------------------

    def run_cycles(self, max_cycles: int = 1_000_000) -> int:
        """Drive scheduling while holding leadership (or unconditionally when
        leader election is off)."""
        if self.elector is not None and not self.elector.tick():
            return 0
        return self.scheduler.run_until_idle(max_cycles)
