"""WAL-shipping apiserver replication (docs/RESILIENCE.md § replication).

The reference survives control-plane node loss because its state plane is
split into a replicated log (etcd3) and read-serving watch caches; this
package rebuilds that split natively over the repo's own WAL
(core/wal.py): a **follower** apiserver tails the leader's committed WAL
frames over ``GET /replication/wal``, replays them into its own store +
on-disk WAL (``APIServer.apply_frame``), and serves the full read plane
(list / watch / RESUME / metrics) to its local shard schedulers, while
every mutating verb answers ``421 NotLeader`` with a redirect the client
follows to the leader. Leader death promotes the lowest-ranked live
follower (``ReplicationTail`` election -> ``APIServer.promote``), fenced
by a monotonic replication epoch stamped on every shipped frame.
"""

from .follower import (REPL_LEASE, LeaderLease, ReplicationTail)

__all__ = ["ReplicationTail", "LeaderLease", "REPL_LEASE"]
