"""Follower-side replication: WAL tail, snapshot bootstrap, election.

A follower is a full :class:`~..core.apiserver.APIServer` in
``role="follower"`` — same store, same watch plane, same WAL — plus a
:class:`ReplicationTail` thread that keeps it converged with the leader:

- **tail**: long-lived ``GET /replication/wal?from=<seq>&epoch=<E>``
  stream; every received frame replays through ``APIServer.apply_frame``
  (local WAL append first, then store upsert, then local watch fanout —
  the leader's own write ordering). The fanout is the shared
  ``_fan_event`` path, so the follower's watch-cache read plane
  (core/watchcache.py: LIST/summary/``/metrics/resources``/RESUME ring)
  and its shard-FILTERED watch streams stay converged in the shared rv
  space — clients keep slim-filtered streams across replica switches and
  across this replica's own promotion, with zero re-lists. Heartbeats
  (``HB``) carry the leader's head seq, which feeds the
  ``apiserver_replication_lag_records`` gauge.
- **bootstrap**: a cold follower (or one the ship window no longer
  covers — 410 ``ResyncRequired``) installs ``GET /replication/snapshot``
  and re-tails from the snapshot's seq. Local WAL recovery
  (``APIServer(data_dir=...)``) already happened before the tail starts,
  so a restarted follower resumes from its own disk, not a snapshot.
- **election**: when nothing (frame, HB, reconnect) has been heard for a
  full lease period, probe the peer set: follow an already-promoted
  leader of a newer epoch; defer to a live lower-ranked follower; else —
  this IS the lowest-ranked live follower — ``promote()``. The fencing
  epoch bump rejects any straggler frames from the deposed generation.

Failure-mode contract (docs/RESILIENCE.md): shard schedulers keep
scheduling from follower reads throughout a failover; their writes fail
fast (connection refused / 421 against a stale redirect) and ride the
client retry layers until the promotion lands — degraded, never a crash.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional
from urllib import request as urlrequest

from ..core import wire

REPL_LEASE = "repl-leader"


class LeaderLease:
    """Maintains the durable ``repl-leader`` lease record — the same
    PUT-CAS + server-side-expiry shape as the shard slots
    (shard/leases.py), WAL'd and therefore SHIPPED, so every follower's
    replicated lease table shows who leads and for how long it has been
    silent. The renewer runs in every replica and simply no-ops while the
    replica is not the leader, so a promotion needs no extra wiring: the
    next tick after ``promote()`` CAS-takes the (by then expired) lease."""

    def __init__(self, api, identity: str, duration: float = 2.0):
        self.api = api
        self.identity = identity
        self.duration = duration
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.renewals = 0

    def renew_once(self) -> bool:
        if self.api.role != "leader":
            return False
        try:
            got = self.api.upsert_lease(REPL_LEASE, self.identity,
                                        self.duration)
        except Exception:  # noqa: BLE001 - the lease simply ages
            return False
        # upsert_lease answers None (CAS loss) or the NOT_LEADER sentinel
        # (we raced a deposition) — only a real lease record counts.
        if isinstance(got, dict):
            self.renewals += 1
            return True
        return False

    def start(self) -> "LeaderLease":
        if self._thread is not None:
            return self

        def loop():
            while not self._stop.wait(self.duration / 3.0):
                self.renew_once()

        self.renew_once()
        self._thread = threading.Thread(target=loop, name="repl-leader-lease",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


class ReplicationTail:
    """The follower's replication client + election state machine."""

    def __init__(self, api, leader_url: str, rank: int,
                 peers: Optional[Dict[int, str]] = None,
                 lease_duration: float = 2.0,
                 hb_interval: Optional[float] = None,
                 page_limit: int = 512):
        api.role = "follower"
        api.leader_url = leader_url
        api.replica_rank = rank
        api.repl_tail = self  # surfaced via /replication/status: election
        # deferral only honors peers whose tail is ALIVE (can promote)
        if peers:
            api.repl_peers.update(peers)
        self.api = api
        self.leader_url = leader_url
        self.lease_duration = lease_duration
        # Streaming paged bootstrap (docs/SCALE.md): objects arrive as
        # json lines in pages of this size — a 50k-node snapshot never
        # rides one response body on either side.
        self.page_limit = max(1, int(page_limit))
        # Heartbeats several times per lease period: one lost HB must not
        # look like a dead leader.
        self.hb = hb_interval if hb_interval is not None \
            else max(0.1, lease_duration / 4.0)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._conn = None
        # The generation the CURRENT leader_url is known to claim (from
        # the election probe, the promotion announcement, or stream HBs).
        # Passed to apply_frame so a lagging survivor that already adopted
        # the winner's epoch still accepts the winner's PRE-promotion
        # frames (stamped with the old epoch). 0 = unknown: frames are
        # judged on their own stamps only.
        self.leader_epoch = 0
        self.last_contact = time.monotonic()
        self.reconnects = 0
        self.bootstraps = 0
        self.elections = 0
        self.deferrals = 0
        self.fenced_streams = 0
        # Shipped DELTA frames whose base rv didn't match our cache —
        # each one forced a full snapshot resync (the fallback contract).
        self.delta_resyncs = 0

    # -- bootstrap ----------------------------------------------------------

    def _get_json(self, url: str, timeout: float):
        # Status probes stay JSON (no Accept offer): the election path is
        # the debug plane, and a probe must parse against ANY peer.
        req = urlrequest.Request(url)
        with urlrequest.urlopen(req, timeout=timeout) as resp:
            return wire.jloads(resp.read())

    def bootstrap(self, timeout: float = 30.0) -> None:
        """Synchronous initial sync for a COLD follower (empty local WAL):
        install the leader's snapshot before serving reads, so the first
        client list/watch never sees an empty store that then re-fills.
        A follower with local WAL state skips this — its own recovery is
        authoritative and the tail replays the delta."""
        if self.api._repl_seq > 0:
            return
        deadline = time.monotonic() + timeout
        delay = 0.05
        while True:
            try:
                self._bootstrap_snapshot()
                return
            except Exception:  # noqa: BLE001 - leader may still be starting
                if time.monotonic() >= deadline:
                    raise
                time.sleep(delay)
                delay = min(delay * 2, 1.0)

    def _bootstrap_snapshot(self) -> None:
        # Verify the source IS the current leader first: installing a
        # snapshot from a demoted/stale peer would REGRESS this replica's
        # store and seq to a forked, older history (and sentinel-close its
        # clients' watch streams into a re-list against it).
        st = self._probe(self.leader_url)
        if (st is None or st.get("role") != "leader"
                or int(st.get("replEpoch", 0)) < self.api.repl_epoch):
            raise RuntimeError(
                f"snapshot source {self.leader_url} is not the current "
                f"leader: {st}")
        self.api.install_snapshot(self._fetch_snapshot_stream())
        self.bootstraps += 1
        self.last_contact = time.monotonic()

    def _fetch_snapshot_stream(self) -> dict:
        """Consume the STREAMING paged bootstrap
        (`GET /replication/snapshot?limit=N`, docs/SCALE.md): SNAP_META,
        then one json line per object, then SNAP_END. Lines are parsed as
        they arrive (bounded buffering — the snapshot never exists as one
        response body or one parse on either side); a torn stream (no
        SNAP_END: the leader died mid-bootstrap) raises and is NEVER
        installed. The meta's role is re-checked — pages may have been
        served across a demotion."""
        import http.client as _hc

        host = self.leader_url.split("//", 1)[1]
        conn = _hc.HTTPConnection(
            host, timeout=max(10.0, self.lease_duration * 4))
        try:
            conn.request(
                "GET", f"/replication/snapshot?limit={self.page_limit}",
                headers=wire.client_headers())
            resp = conn.getresponse()
            if resp.status != 200:
                resp.read()
                raise RuntimeError(
                    f"snapshot stream: HTTP {resp.status}")
            snap: Optional[dict] = None
            objs: Dict[str, list] = {
                "pods": [], "nodes": [], "podgroups": [],
                "replicasets": [], "deployments": [], "pdbs": []}
            complete = False
            while True:
                got = wire.read_event(resp)
                if got is None:
                    break
                d, _nbytes, _codec = got
                typ = d.get("type")
                if typ == "SNAP_META":
                    if d.get("role") != "leader":
                        raise RuntimeError(
                            "snapshot source demoted mid-stream")
                    snap = {k: d[k] for k in
                            ("epoch", "seq", "repl", "leases", "evictions")
                            if k in d}
                elif typ == "SNAP_END":
                    complete = True
                    break
                elif d.get("kind") in objs:
                    objs[d["kind"]].append(d["object"])
            if snap is None or not complete:
                raise RuntimeError("snapshot stream torn before SNAP_END")
            for kind, got_objs in objs.items():
                snap[kind] = got_objs
            return snap
        finally:
            conn.close()

    # -- the tail loop ------------------------------------------------------

    def start(self) -> "ReplicationTail":
        if self._thread is not None:
            return self
        self._thread = threading.Thread(
            target=self._run, name=f"repl-tail-{self.api.replica_rank}",
            daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        conn = self._conn
        if conn is not None:
            try:
                import socket
                if conn.sock is not None:
                    conn.sock.shutdown(socket.SHUT_RDWR)
                conn.close()
            except Exception:  # noqa: BLE001
                pass
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def _run(self) -> None:
        backoff = 0.05
        while not self._stop.is_set():
            # A promotion ANNOUNCEMENT (POST /replication/leader ->
            # note_leader) may have moved api.leader_url while this thread
            # was tailing or backing off: adopt it before (re)connecting —
            # the fastest convergence path, no silence detection needed.
            api_leader = self.api.leader_url
            if (api_leader and api_leader != self.leader_url
                    and self.api.role == "follower"):
                self.leader_url = api_leader
                # note_leader adopted the announced generation already.
                self.leader_epoch = self.api.repl_epoch
            try:
                progressed = self._tail_once()
            except Exception:  # noqa: BLE001 - transport failure = dead tail
                progressed = False
            if self._stop.is_set() or self.api.role == "leader":
                return  # promoted (or shutting down): the tail's job is done
            if progressed:
                backoff = 0.05
                continue
            if (time.monotonic() - self.last_contact) > self.lease_duration:
                self._election()
                if self.api.role == "leader":
                    return
            if self._stop.wait(backoff):
                return
            backoff = min(backoff * 2, max(0.25, self.lease_duration / 4.0))

    def _tail_once(self) -> bool:
        """One ship-stream attachment: True when the stream made contact
        (connected and delivered at least a heartbeat) before dying."""
        import http.client as _hc
        from urllib.parse import quote

        api = self.api
        host = self.leader_url.split("//", 1)[1]
        conn = _hc.HTTPConnection(host, timeout=max(
            2.0, self.lease_duration * 2))
        path = (f"/replication/wal?from={api._repl_seq}"
                f"&epoch={api.repl_epoch}&hb={self.hb}"
                f"&leader={quote(self.leader_url, safe='')}")
        try:
            # stream_headers adds the session offer: the leader replying
            # with the session MIME ships DELTA twins this follower
            # materializes against its own watch-cache base.
            conn.request("GET", path, headers=wire.stream_headers())
            resp = conn.getresponse()
        except Exception:  # noqa: BLE001 - leader unreachable
            conn.close()
            return False
        if resp.status == 410:
            # Ship window no longer covers our seq (leader compacted past
            # us, or our history diverged): full snapshot resync.
            try:
                resp.read()
            finally:
                conn.close()
            self._bootstrap_snapshot()
            return True
        if resp.status != 200:
            try:
                resp.read()
            finally:
                conn.close()
            return False
        self._conn = conn
        self.reconnects += 1
        made_contact = False
        session = (wire.SessionDecoder()
                   if wire.session_of_mime(resp.getheader("Content-Type"))
                   else None)
        try:
            while not self._stop.is_set():
                # Sniff-decoded per frame (core/wire.py): a binary
                # follower keeps tailing through a JSON peer's frames —
                # codec continuity is NOT part of the stream contract,
                # which is what lets mixed fleets promote across planes.
                got = wire.read_event(resp, session=session)
                if got is None:
                    return made_contact  # EOF: leader went away
                rec, _nbytes, _codec = got
                if rec.get("type") == "HB":
                    ep = int(rec.get("epoch", 0))
                    if (ep < api.repl_epoch
                            or rec.get("role", "leader") != "leader"):
                        # Deposed-generation or NON-LEADER stream: fence
                        # it off WITHOUT refreshing last_contact — a
                        # demoted peer's heartbeats must not hold off the
                        # election that finds the real leader.
                        self.fenced_streams += 1
                        return made_contact
                    self.last_contact = time.monotonic()
                    made_contact = True
                    self.leader_epoch = max(self.leader_epoch, ep)
                    api.repl_lag = max(
                        0, int(rec.get("seq", 0)) - api._repl_seq)
                    continue
                self.last_contact = time.monotonic()
                made_contact = True
                try:
                    applied = api.apply_frame(
                        rec, stream_epoch=self.leader_epoch)
                except wire.DeltaBaseMismatch:
                    # A shipped DELTA didn't match our watch-cache base
                    # (diverged history, promotion gap): the contract is
                    # full-object resync, never a silent patch. Snapshot
                    # bootstrap re-tails from the installed cut.
                    self.delta_resyncs += 1
                    self._bootstrap_snapshot()
                    return True
                if not applied:
                    # Stale-epoch frame (a deposed leader's append): drop
                    # the stream; the election will find the real leader.
                    self.fenced_streams += 1
                    return made_contact
            return made_contact
        finally:
            self._conn = None
            try:
                conn.close()
            except Exception:  # noqa: BLE001
                pass

    # -- election -----------------------------------------------------------

    def _probe(self, url: str) -> Optional[dict]:
        try:
            return self._get_json(url + "/replication/status",
                                  timeout=max(0.2, self.lease_duration / 4.0))
        except Exception:  # noqa: BLE001 - peer dead/unreachable
            return None

    def _election(self) -> None:
        """A full lease period of silence: decide between following a new
        leader, deferring to a lower-ranked live follower, or promoting."""
        api = self.api
        self.elections += 1
        statuses: Dict[int, dict] = {}
        for rank, url in sorted(api.repl_peers.items()):
            if url == api.advertise_url:
                continue
            st = self._probe(url)
            if st is not None:
                statuses[rank] = st
        # 1) Someone already leads (>= our generation): follow the claim
        # with the HIGHEST fencing epoch — a stale leader that has not yet
        # learned it was deposed may still claim the role. This also
        # covers the ORIGINAL leader coming back after a restart.
        claims = [(int(st.get("replEpoch", 0)), rank) for rank, st
                  in statuses.items()
                  if st.get("role") == "leader"
                  and int(st.get("replEpoch", 0)) >= api.repl_epoch]
        if claims:
            ep, rank = max(claims)
            st = statuses[rank]
            url = st.get("leader") or api.repl_peers.get(rank, "")
            if url:
                self.leader_url = url
                self.leader_epoch = ep
                api.note_leader(url, ep)
                self.last_contact = time.monotonic()
            return
        # 2) A live follower with a lower rank AND a live tail exists: it
        # promotes, we defer — but only for half a lease period, so a
        # candidate that dies mid-election doesn't wedge the plane. A
        # tail-less "follower" (a demoted seed leader, or a deposed
        # ex-promotee whose tail thread exited) can never promote — do
        # NOT defer to it, or the plane livelocks leaderless.
        if any(st.get("role") == "follower" and rank < api.replica_rank
               and (st.get("tail") or {}).get("alive")
               for rank, st in statuses.items()):
            self.deferrals += 1
            self.last_contact = time.monotonic() - self.lease_duration / 2.0
            return
        # 3) This is the lowest-ranked live follower: take over. Everything
        # readable from the dead leader's stream has been applied (the tail
        # drains to EOF before landing here) — the WAL tail IS replayed.
        api.promote(reason="leader_lost")
        self.leader_url = api.advertise_url
        self._announce_leadership()

    def _announce_leadership(self) -> None:
        """Push the new generation to every peer (POST /replication/leader):
        surviving followers re-tail to us immediately, and a stale
        co-claimant demotes itself even though no follower tails it. Best
        effort — a peer that misses it converges via its own election."""
        api = self.api
        body = wire.jdumps({"leader": api.advertise_url,
                            "epoch": api.repl_epoch,
                            "rank": api.replica_rank}).encode()
        for rank, url in sorted(api.repl_peers.items()):
            if url == api.advertise_url:
                continue
            try:
                req = urlrequest.Request(
                    url + "/replication/leader", data=body, method="POST",
                    headers={"Content-Type": "application/json"})
                with urlrequest.urlopen(
                        req, timeout=max(0.2, self.lease_duration / 4.0)):
                    pass
            except Exception:  # noqa: BLE001 - dead peer: nothing to tell
                pass
