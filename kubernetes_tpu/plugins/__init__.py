from .basic import (
    DefaultBinder,
    ImageLocality,
    NodeAffinity,
    NodeName,
    NodePorts,
    NodeUnschedulable,
    PrioritySort,
    SchedulingGates,
    TaintToleration,
)
from .interpodaffinity import InterPodAffinity
from .noderesources import BalancedAllocation, Fit
from .podtopologyspread import PodTopologySpread

__all__ = [
    "DefaultBinder",
    "ImageLocality",
    "NodeAffinity",
    "NodeName",
    "NodePorts",
    "NodeUnschedulable",
    "PrioritySort",
    "SchedulingGates",
    "TaintToleration",
    "InterPodAffinity",
    "BalancedAllocation",
    "Fit",
    "PodTopologySpread",
]
