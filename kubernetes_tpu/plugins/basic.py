"""The "cheap" in-tree plugins: NodeName, NodeUnschedulable, NodePorts,
SchedulingGates, PrioritySort, DefaultBinder, ImageLocality, TaintToleration,
NodeAffinity.

Each class mirrors one reference plugin package under
pkg/scheduler/framework/plugins/ (anchor cited per class). Methods follow the
duck-typed extension-point protocol in kubernetes_tpu/core/framework.py.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

from ..api.types import (
    NO_EXECUTE,
    NO_SCHEDULE,
    PREFER_NO_SCHEDULE,
    Node,
    Pod,
    Toleration,
    find_matching_untolerated_taint,
)
from ..core.framework import (
    MAX_NODE_SCORE,
    OK,
    CycleState,
    NodeScore,
    PreFilterResult,
    Status,
    default_normalize_score,
)
from ..core.node_info import NodeInfo

# ---------------------------------------------------------------------------


class NodeName:
    """plugins/nodename: pod.spec.nodeName exact match."""

    name = "NodeName"

    def filter(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> Status:
        if pod.node_name and pod.node_name != node_info.name:
            return Status.unresolvable("node(s) didn't match the requested node name")
        return OK

    def sign(self, pod: Pod):
        return pod.node_name


class NodeUnschedulable:
    """plugins/nodeunschedulable: gate on node.spec.unschedulable, tolerable
    via the unschedulable taint toleration."""

    name = "NodeUnschedulable"
    TAINT_KEY = "node.kubernetes.io/unschedulable"

    def filter(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> Status:
        node = node_info.node
        if node is not None and node.unschedulable:
            if not any(t.tolerates(_UNSCHED_TAINT) for t in pod.tolerations):
                return Status.unresolvable("node(s) were unschedulable")
        return OK

    def sign(self, pod: Pod):
        return tuple((t.key, t.operator, t.value, t.effect) for t in pod.tolerations)


from ..api.types import Taint as _Taint  # noqa: E402

_UNSCHED_TAINT = _Taint(key=NodeUnschedulable.TAINT_KEY, effect=NO_SCHEDULE)


def host_ports_conflict(ports, used_ports) -> bool:
    """nodeports.go Fits → fitsPorts, incl. the 0.0.0.0 wildcard semantics.
    The single source of truth for host AND device paths (the device path
    evaluates this host-side into a static per-node mask — ops/features.py)."""
    for p in ports:
        for (proto, ip, port) in used_ports:
            if port != p.host_port or proto != p.protocol:
                continue
            if ip in ("", "0.0.0.0") or p.host_ip in ("", "0.0.0.0") or ip == p.host_ip:
                return True
    return False


class NodePorts:
    """plugins/nodeports: reject nodes with conflicting host ports."""

    name = "NodePorts"
    _KEY = "PreFilterNodePorts"

    def pre_filter(self, state: CycleState, pod: Pod, nodes) -> Tuple[Optional[PreFilterResult], Status]:
        ports = pod.host_ports()
        if not ports:
            return None, Status.skip()
        state.write(self._KEY, ports)
        return None, OK

    def filter(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> Status:
        ports = state.read(self._KEY)
        if ports is None:
            ports = pod.host_ports()
        if host_ports_conflict(ports, node_info.used_ports):
            return Status.unschedulable("node(s) didn't have free ports for the requested pod ports")
        return OK

    def sign(self, pod: Pod):
        return tuple(sorted((p.protocol, p.host_ip, p.host_port) for p in pod.host_ports()))


class SchedulingGates:
    """plugins/schedulinggates: PreEnqueue gate on spec.schedulingGates."""

    name = "SchedulingGates"

    def pre_enqueue(self, pod: Pod) -> Status:
        if pod.scheduling_gates:
            return Status.unresolvable(
                "waiting for scheduling gates: " + ",".join(pod.scheduling_gates)
            )
        return OK


class PrioritySort:
    """plugins/queuesort: priority desc, then enqueue timestamp asc."""

    name = "PrioritySort"

    def less(self, a, b) -> bool:
        pa = a.pod.priority
        pb = b.pod.priority
        if pa != pb:
            return pa > pb
        return a.timestamp < b.timestamp

    @staticmethod
    def sort_key(qpi) -> tuple:
        """Tuple equivalent of less() for C-speed heap comparisons."""
        return (-qpi.pod.priority, qpi.timestamp)


class DefaultBinder:
    """plugins/defaultbinder: POST /binding — routed through the async API
    dispatcher when available (framework/api_calls/pod_binding.go:32
    PodBindingCall via APIDispatcher; inline mode executes immediately)."""

    name = "DefaultBinder"

    def __init__(self, handle=None):
        self.handle = handle

    def bind(self, state: CycleState, pod: Pod, node_name: str) -> Status:
        dispatcher = getattr(self.handle, "api_dispatcher", None)
        try:
            if dispatcher is None or dispatcher.mode == "inline":
                # Inline mode executes immediately anyway — skip the APICall
                # allocation and go straight to the API (this runs once per
                # scheduled pod on a >10k pods/s path). Counter/error
                # accounting matches APIDispatcher._execute.
                try:
                    self.handle.clientset.bind(pod, node_name)
                except Exception as e:  # noqa: BLE001
                    if getattr(e, "code", None) == 429:
                        # Flow-control shed (core/flowcontrol.py): the bind
                        # never ran. Tagged so the binding cycle requeues
                        # through the backoffQ with the admission stamp
                        # intact — the retry layers already honored
                        # Retry-After before this surfaced.
                        return Status.bind_shed(str(e))
                    if getattr(e, "code", None) == 409:
                        # Optimistic-binding loss (AlreadyBound /
                        # OutOfCapacity): another scheduler committed first.
                        # Tagged so the binding cycle requeues through the
                        # backoffQ instead of parking the pod as an error.
                        reason = ""
                        try:  # the 409 body names which conflict it was
                            import json as _json
                            reason = _json.loads(e.read()).get("error", "")
                        except Exception:  # noqa: BLE001
                            pass
                        return Status.bind_conflict(reason or str(e))
                    if dispatcher is not None:
                        from ..core.api_dispatcher import CALL_BINDING
                        dispatcher.errors.append(f"{CALL_BINDING}/{pod.uid}: {e!r}")
                    return Status.error(str(e))
                if dispatcher is not None:
                    dispatcher.executed += 1
                return OK
            from ..core.api_dispatcher import APICall, CALL_BINDING
            from ..core import spans as _spans
            on_error = getattr(self.handle, "on_async_bind_error", None)
            _tr = _spans.default_tracer()
            _ctx = _tr.context_for(pod.uid)
            dispatcher.add(APICall(
                call_type=CALL_BINDING, object_uid=pod.uid,
                trace_ctx=_spans.format_ctx(_ctx) if _tr.wants(_ctx) else None,
                execute=lambda: self.handle.clientset.bind(pod, node_name),
                bind_args=(pod, node_name),
                # Stable bound method: the dispatcher batches consecutive
                # binding calls whose bulk_execute is the SAME callable.
                bulk_execute=self._bulk_bind,
                on_error=(lambda e, _p=pod: on_error(_p, e))
                if on_error is not None else None))
        except Exception as e:  # noqa: BLE001
            return Status.error(str(e))
        return OK

    def _bulk_bind(self, calls) -> list:
        """Commit a run of queued binding calls as ONE bulk request
        (dispatcher thread worker → clientset.bind_many). Per-bind POSTs
        cap the async worker far below the server's bind capacity: each
        round-trip costs a GIL wakeup in a process whose reflector/
        scheduler threads are busy, so amortizing N binds per wakeup is
        worth ~an order of magnitude in drain rate. Falls back to per-call
        binds for clientsets without a bulk verb (FakeClientset)."""
        cs = self.handle.clientset
        bind_many = getattr(cs, "bind_many", None)
        if bind_many is not None:
            return bind_many([c.bind_args for c in calls])
        out = []
        for c in calls:
            try:
                cs.bind(*c.bind_args)
                out.append(None)
            except Exception as e:  # noqa: BLE001
                out.append(e)
        return out


class ImageLocality:
    """plugins/imagelocality: score nodes by bytes of the pod's images already
    present, scaled into [23Mi, 1000Mi] and spread-discounted by the fraction
    of nodes that already have the image (imagelocality.go scaledImageScore)."""

    name = "ImageLocality"
    MIN_THRESHOLD = 23 * 1024 * 1024
    MAX_CONTAINER_THRESHOLD = 1000 * 1024 * 1024

    def __init__(self, handle=None):
        self.handle = handle

    @classmethod
    def scaled_score(cls, pod: Pod, node_info: NodeInfo, image_nodes, total_nodes: int) -> int:
        """Pure scoring math (imagelocality.go scaledImageScore + thresholds):
        the single source of truth for host AND device paths — the device path
        precomputes this per node into a static score vector (ops/features.py)."""
        sum_scores = 0
        for c in pod.containers:
            size = node_info.image_states.get(c.image)
            if size is None:
                continue
            spread = 1.0
            if image_nodes is not None:
                spread = image_nodes.get(c.image, 1) / total_nodes
            sum_scores += int(size * spread)
        max_threshold = cls.MAX_CONTAINER_THRESHOLD * max(1, len(pod.containers))
        if sum_scores < cls.MIN_THRESHOLD:
            return 0
        if sum_scores > max_threshold:
            return MAX_NODE_SCORE
        return int(MAX_NODE_SCORE * (sum_scores - cls.MIN_THRESHOLD) / (max_threshold - cls.MIN_THRESHOLD))

    def score(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> Tuple[int, Status]:
        total_nodes = 1
        image_nodes = None
        if self.handle is not None and getattr(self.handle, "snapshot", None) is not None:
            snap = self.handle.snapshot() if callable(self.handle.snapshot) else self.handle.snapshot
            total_nodes = max(1, len(snap.node_info_list))
            image_nodes = getattr(snap, "image_num_nodes", None)
        return self.scaled_score(pod, node_info, image_nodes, total_nodes), OK

    def sign(self, pod: Pod):
        return tuple(sorted(c.image for c in pod.containers))


class TaintToleration:
    """plugins/tainttoleration (taint_toleration.go).

    Filter: first NoSchedule/NoExecute taint not tolerated =>
    UnschedulableAndUnresolvable (:133). Score: count of PreferNoSchedule
    taints intolerable by the pod (:182-194); NormalizeScore reversed (:212).
    """

    name = "TaintToleration"
    _KEY = "PreScoreTaintToleration"

    def events_to_register(self):
        """taint_toleration.go EventsToRegister: node add/update with a
        toleration check (isSchedulableAfterNodeChange)."""
        from ..core.queue import EVENT_NODE_ADD, EVENT_NODE_UPDATE
        return [(EVENT_NODE_ADD, self._hint_node),
                (EVENT_NODE_UPDATE, self._hint_node)]

    @staticmethod
    def _hint_node(pod: Pod, old, new) -> bool:
        if new is None:
            return True
        return find_matching_untolerated_taint(new.taints, pod.tolerations) is None

    def filter(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> Status:
        node = node_info.node
        if node is None:
            return Status.error("node not found")
        taint = find_matching_untolerated_taint(node.taints, pod.tolerations)
        if taint is not None:
            return Status.unresolvable(
                f"node(s) had untolerated taint {{{taint.key}: {taint.value}}}"
            )
        return OK

    def pre_score(self, state: CycleState, pod: Pod, nodes) -> Status:
        tolerations = [
            t for t in pod.tolerations
            if not t.effect or t.effect == PREFER_NO_SCHEDULE
        ]
        state.write(self._KEY, tolerations)
        return OK

    def score(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> Tuple[int, Status]:
        tolerations = state.read(self._KEY) or []
        count = 0
        for taint in node_info.node.taints:
            if taint.effect != PREFER_NO_SCHEDULE:
                continue
            if not any(t.tolerates(taint) for t in tolerations):
                count += 1
        return count, OK

    def normalize_score(self, state: CycleState, pod: Pod, scores: List[NodeScore]) -> None:
        default_normalize_score(MAX_NODE_SCORE, True, scores)

    def sign(self, pod: Pod):
        return tuple((t.key, t.operator, t.value, t.effect) for t in pod.tolerations)


class NodeAffinity:
    """plugins/nodeaffinity (node_affinity.go).

    Filter: nodeSelector AND required node affinity terms. PreFilter narrows
    to specific nodes when terms pin metadata.name (node_affinity.go PreFilter),
    and Skips when the pod expresses no node affinity. Score: sum of matching
    preferred term weights, default-normalized.
    """

    name = "NodeAffinity"

    def events_to_register(self):
        """node_affinity.go EventsToRegister / isSchedulableAfterNodeChange:
        a node event helps only if the new node matches the pod's required
        selector/affinity."""
        from ..core.queue import EVENT_NODE_ADD, EVENT_NODE_UPDATE
        return [(EVENT_NODE_ADD, self._hint_node),
                (EVENT_NODE_UPDATE, self._hint_node)]

    @staticmethod
    def _hint_node(pod: Pod, old, new) -> bool:
        if new is None:
            return True
        return pod.required_node_selector_matches(new)

    def pre_filter(self, state: CycleState, pod: Pod, nodes) -> Tuple[Optional[PreFilterResult], Status]:
        na = pod.affinity.node_affinity if pod.affinity else None
        if not pod.node_selector and (na is None or na.required is None):
            return None, Status.skip()
        # Narrow to named nodes when every term pins metadata.name via In.
        if na is not None and na.required is not None and na.required.terms:
            node_names: Optional[set] = set()
            for term in na.required.terms:
                term_names = None
                for req in term.match_fields:
                    if req.key == "metadata.name" and req.operator == "In":
                        term_names = set(req.values)
                if term_names is None:
                    node_names = None
                    break
                node_names |= term_names
            if node_names is not None:
                return PreFilterResult(node_names), OK
        return None, OK

    def filter(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> Status:
        if not pod.required_node_selector_matches(node_info.node):
            return Status.unresolvable("node(s) didn't match Pod's node affinity/selector")
        return OK

    def pre_score(self, state: CycleState, pod: Pod, nodes) -> Status:
        na = pod.affinity.node_affinity if pod.affinity else None
        if na is None or not na.preferred:
            return Status.skip()
        return OK

    def score(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> Tuple[int, Status]:
        na = pod.affinity.node_affinity if pod.affinity else None
        if na is None:
            return 0, OK
        total = 0
        for pref in na.preferred:
            if pref.preference.matches(node_info.node):
                total += pref.weight
        return total, OK

    def normalize_score(self, state: CycleState, pod: Pod, scores: List[NodeScore]) -> None:
        default_normalize_score(MAX_NODE_SCORE, False, scores)

    def sign(self, pod: Pod):
        na = pod.affinity.node_affinity if pod.affinity else None
        return (
            tuple(sorted(pod.node_selector.items())),
            repr(na) if na else "",
        )
