"""DynamicResources plugin: DRA claim allocation during scheduling.

Reference anchors: plugins/dynamicresources/ (dynamicresources.go 2152 LoC,
dra_manager.go 512): PreFilter fetches the pod's claims (missing ⇒
unresolvable; allocated ⇒ node pinned to the allocation), Filter runs a
per-node allocation attempt over the node's ResourceSlices (structured
parameters), Reserve assumes the winning allocation in the shared assume
cache, Unreserve reverts, PreBind writes claim status + reservedFor.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from ..api.dra import AllocatedDevice, DeviceRequest, ResourceClaim
from ..api.types import Pod
from ..core.framework import OK, CycleState, PreFilterResult, Status
from ..core.node_info import NodeInfo

ERR_CLAIM_NOT_FOUND = 'resourceclaim "%s" not found'
ERR_ALLOCATED_ELSEWHERE = "resourceclaim was allocated for a different node"
ERR_NO_DEVICES = "node(s) didn't have enough free devices for the claims"


class DynamicResources:
    name = "DynamicResources"
    # Reserve/PreBind act only on CycleState written in PreFilter (no-ops on
    # a fresh state) — device commit fast-path eligible.
    state_driven_tail = True
    _KEY = "PreFilterDynamicResources"

    # extendeddynamicresources.go specialClaimInMemName: the in-memory
    # claim tracking extended-resource-backed allocations until PreBind
    # creates the real object.
    SPECIAL_CLAIM_NAME = "<extended-resources>"

    def __init__(self, handle=None):
        self.handle = handle
        # Assume cache (dra_manager.go / assumecache): device keys held by
        # in-flight reservations, per claim.
        self.assumed: Dict[str, List[AllocatedDevice]] = {}  # claim key -> devices
        self.assumed_nodes: Dict[str, str] = {}              # claim key -> node
        # Revision-cached in-use device set: rebuilt when the clientset's
        # claim revision moves, updated INCREMENTALLY by our own
        # reserve/unreserve (the O(all claims) rebuild per cycle made the
        # claim-template workload quadratic).
        self._iu_cache: Optional[Set[Tuple[str, str, str]]] = None
        self._iu_rv = -1

    def _gate(self, name: str) -> bool:
        gates = getattr(self.handle, "gates", None)
        if gates is None:
            return True
        try:
            return gates.enabled(name)
        except ValueError:
            return True

    def _extended_claim_for(self, pod: Pod) -> Optional[ResourceClaim]:
        """Extended Resources Backed by DRA (extendeddynamicresources.go
        preFilterExtendedResources): a pod requesting an extended resource
        mapped by some DeviceClass.extended_resource_name gets an IN-MEMORY
        claim requesting count=quantity devices of that class; the real
        object is created in PreBind."""
        from ..core.features import DRA_EXTENDED_RESOURCE
        if not self._gate(DRA_EXTENDED_RESOURCE):
            return None
        req = pod.resource_request()
        if not req.scalar_resources:
            return None
        by_ext = {dc.extended_resource_name: dc
                  for dc in self.handle.device_classes.values()
                  if dc.extended_resource_name}
        if not by_ext:
            return None
        requests = []
        for rname, amount in req.scalar_resources.items():
            dc = by_ext.get(rname)
            if dc is not None and amount > 0:
                requests.append(DeviceRequest(
                    name=rname, device_class=dc.name, count=int(amount)))
        if not requests:
            return None
        # Named for its pod from the start: the assume cache keys on
        # claim.key, and a shared in-memory name would let two in-flight
        # extended-resource pods clobber each other's reservations.
        return ResourceClaim(name=f"{pod.name}-extended-resources",
                             namespace=pod.namespace, requests=requests)

    # -- listers -----------------------------------------------------------

    def _claims_for(self, pod: Pod) -> List[Optional[ResourceClaim]]:
        return [self.handle.resource_claims.get(f"{pod.namespace}/{name}")
                for name in getattr(pod, "resource_claims", ())]

    def _in_use(self) -> Set[Tuple[str, str, str]]:
        """(node, driver, device) triples already allocated or assumed.
        Cached against the clientset's claim revision; our own
        reserve/unreserve/pre_bind keep it consistent in between (their
        net effect on the set is exactly the triples they add/remove)."""
        rv = getattr(self.handle.clientset, "resource_claims_rv", 0)
        if self._iu_cache is not None and self._iu_rv == rv:
            return self._iu_cache
        used: Set[Tuple[str, str, str]] = set()
        for claim in self.handle.resource_claims.values():
            if claim.allocated:
                for d in claim.allocations:
                    used.add((claim.allocated_node, d.driver, d.device))
        for key, devices in self.assumed.items():
            node = self.assumed_nodes.get(key, "")
            for d in devices:
                used.add((node, d.driver, d.device))
        self._iu_cache = used
        self._iu_rv = rv
        return used

    # -- PreFilter ---------------------------------------------------------

    @dataclass
    class _State:
        claims: List[ResourceClaim] = field(default_factory=list)
        pinned_node: str = ""  # allocation already fixes the node
        # node -> [(claim, devices)]
        node_allocations: Dict[str, List[Tuple[ResourceClaim, List[AllocatedDevice]]]] = field(default_factory=dict)
        # (node, driver, device) triples taken by existing allocations +
        # assumptions, computed ONCE per cycle in PreFilter — the per-node
        # Filter must not rescan every claim in the cluster (O(claims) per
        # node turned the 500-node DRA workload O(claims x nodes x pods)).
        in_use: Optional[Set[Tuple[str, str, str]]] = None
        # Extended-resources-backed special claim (in-memory until PreBind);
        # nodes where the device plugin satisfied everything keep an empty
        # allocation list (extendeddynamicresources.go filterExtendedResources).
        special: Optional[ResourceClaim] = None

        def clone(self) -> "DynamicResources._State":
            return DynamicResources._State(
                claims=list(self.claims),
                pinned_node=self.pinned_node,
                node_allocations={k: list(v) for k, v in self.node_allocations.items()},
                in_use=set(self.in_use) if self.in_use is not None else None,
                special=self.special,
            )

    def pre_filter(self, state: CycleState, pod: Pod, nodes) -> Tuple[Optional[PreFilterResult], Status]:
        names = getattr(pod, "resource_claims", ())
        special = self._extended_claim_for(pod) if not names else None
        if not names and special is None:
            return None, Status.skip()
        s = self._State()
        if special is not None:
            s.claims.append(special)
            s.special = special
            s.in_use = self._in_use()
            state.write(self._KEY, s)
            return None, OK
        pinned: Optional[str] = None
        for name in names:
            claim = self.handle.resource_claims.get(f"{pod.namespace}/{name}")
            if claim is None:
                return None, Status.unresolvable(ERR_CLAIM_NOT_FOUND % name)
            s.claims.append(claim)
            if claim.allocated:
                if pinned is not None and claim.allocated_node != pinned:
                    return None, Status.unresolvable(ERR_ALLOCATED_ELSEWHERE)
                pinned = claim.allocated_node
        state.write(self._KEY, s)
        if pinned is not None and all(c.allocated for c in s.claims):
            # Every claim pre-allocated: scheduling reduces to validating
            # the pinned node — the O(all claims) in-use scan is dead
            # weight (it only feeds fresh allocation attempts), and the
            # ResourceClaimTemplate workload pays it once per pod.
            s.pinned_node = pinned
            return PreFilterResult({pinned}), OK
        s.in_use = self._in_use()
        if pinned is not None:
            s.pinned_node = pinned
            return PreFilterResult({pinned}), OK
        return None, OK

    # -- Filter: per-node allocation attempt -------------------------------

    def _resolve_selectors(self, req) -> Dict[str, str]:
        sel = dict(req.selectors)
        if req.device_class:
            dc = self.handle.device_classes.get(req.device_class)
            if dc is not None:
                sel.update(dc.selectors)
        return sel

    @staticmethod
    def _matcher_for(req):
        """Compiled expression selector, memoized on the request object
        (dynamic-resource-allocation/cel compiles each CEL program once)."""
        expr = getattr(req, "expression", "")
        if not expr:
            return None
        cached = getattr(req, "_compiled_expr", None)
        if cached is None:
            from ..api.dra import compile_device_expression
            cached = compile_device_expression(expr)
            req._compiled_expr = cached
        return cached

    def filter(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> Status:
        s: Optional[DynamicResources._State] = state.read(self._KEY)
        if s is None:
            return OK
        node_name = node_info.name
        if s.pinned_node:
            return OK if node_name == s.pinned_node else Status.unschedulable(
                ERR_ALLOCATED_ELSEWHERE)
        in_use = s.in_use if s.in_use is not None else self._in_use()
        taken: Set[Tuple[str, str]] = set()
        allocations: List[Tuple[ResourceClaim, List[AllocatedDevice]]] = []
        slices = self.handle.resource_slices.get(node_name, [])
        for claim in s.claims:
            if claim.allocated:
                continue
            devices: List[AllocatedDevice] = []
            for req in claim.requests:
                count = req.count
                if claim is s.special:
                    # Extended resource: the node's device plugin satisfies
                    # it outright when it advertises enough capacity; DRA
                    # devices only back the remainder-less case
                    # (filterExtendedResources: device-plugin vs DRA split).
                    free = (node_info.allocatable.scalar_resources.get(req.name, 0)
                            - node_info.requested.scalar_resources.get(req.name, 0))
                    if free >= count:
                        continue
                sel = self._resolve_selectors(req)
                found = 0
                for sl in slices:
                    for dev in sl.devices:
                        if found >= count:
                            break
                        key = (sl.driver, dev.name)
                        if key in taken or (node_name, sl.driver, dev.name) in in_use:
                            continue
                        if not all(dev.attributes.get(k) == v for k, v in sel.items()):
                            continue
                        matcher = self._matcher_for(req)
                        if matcher is not None and not matcher(dev, sl.driver):
                            continue
                        devices.append(AllocatedDevice(sl.driver, dev.name))
                        taken.add(key)
                        found += 1
                if found < count:
                    return Status.unschedulable(ERR_NO_DEVICES)
            allocations.append((claim, devices))
        st = self._check_node_allocatable(pod, node_info, allocations, slices,
                                          in_use)
        if st is not None:
            return st
        s.node_allocations[node_name] = allocations
        return OK

    def _check_node_allocatable(self, pod: Pod, node_info: NodeInfo,
                                allocations, slices,
                                in_use=None) -> Optional[Status]:
        """DRA allocations that consume node-allocatable resources
        (nodeallocatabledynamicresources.go
        calculateAndCheckNodeAllocatableResources): the pod's container
        requests PLUS its chosen devices' declared consumption must fit the
        node's remaining allocatable."""
        from ..core.features import DRA_NODE_ALLOCATABLE_RESOURCES
        if not self._gate(DRA_NODE_ALLOCATABLE_RESOURCES):
            return None
        dev_objs = {}
        for sl in slices:
            for dev in sl.devices:
                if dev.consumes:
                    dev_objs[(sl.driver, dev.name)] = dev
        if not dev_objs:
            return None
        from ..api.resource import cpu_to_milli, to_int
        extra_cpu = extra_mem = 0
        for _claim, devices in allocations:
            for ad in devices:
                dev = dev_objs.get((ad.driver, ad.device))
                if dev is None:
                    continue
                if "cpu" in dev.consumes:
                    extra_cpu += cpu_to_milli(dev.consumes["cpu"])
                if "memory" in dev.consumes:
                    extra_mem += to_int(dev.consumes["memory"])
        # Devices ALREADY allocated on this node consume allocatable that
        # NodeInfo.requested doesn't know about (their pods' requests only
        # cover containers) — charge them too
        # (nodeallocatabledynamicresources.go counts existing allocations).
        node_name = node_info.name
        if in_use:
            for (driver, name), dev in dev_objs.items():
                if (node_name, driver, name) in in_use:
                    if "cpu" in dev.consumes:
                        extra_cpu += cpu_to_milli(dev.consumes["cpu"])
                    if "memory" in dev.consumes:
                        extra_mem += to_int(dev.consumes["memory"])
        if not extra_cpu and not extra_mem:
            return None
        req = pod.resource_request()
        alloc = node_info.allocatable
        used = node_info.requested
        if (req.milli_cpu + extra_cpu > alloc.milli_cpu - used.milli_cpu
                or req.memory + extra_mem > alloc.memory - used.memory):
            return Status.unschedulable(
                "node(s) lack allocatable for DRA device consumption")
        return None

    # -- Reserve / Unreserve / PreBind -------------------------------------

    def reserve(self, state: CycleState, pod: Pod, node_name: str) -> Status:
        s: Optional[DynamicResources._State] = state.read(self._KEY)
        if s is None:
            return OK
        for claim, devices in s.node_allocations.get(node_name, ()):
            self.assumed[claim.key] = devices
            self.assumed_nodes[claim.key] = node_name
            if self._iu_cache is not None:
                for d in devices:
                    self._iu_cache.add((node_name, d.driver, d.device))
        return OK

    def unreserve(self, state: CycleState, pod: Pod, node_name: str) -> None:
        s: Optional[DynamicResources._State] = state.read(self._KEY)
        if s is None:
            return
        for claim, devices in s.node_allocations.get(node_name, ()):
            self.assumed.pop(claim.key, None)
            self.assumed_nodes.pop(claim.key, None)
            if self._iu_cache is not None:
                for d in devices:
                    self._iu_cache.discard((node_name, d.driver, d.device))

    def pre_bind_pre_flight(self, state: CycleState, pod: Pod,
                            node_name: str) -> Status:
        """PreBindPreFlight (dynamicresources.go PreBindPreFlight): Skip
        when the pod references no resource claims AND no in-memory
        extended-resources claim was built for it this cycle."""
        if getattr(pod, "resource_claims", None):
            return OK
        s = state.read(self._KEY)
        if s is not None and s.special is not None:
            return OK
        return Status.skip()

    def pre_bind(self, state: CycleState, pod: Pod, node_name: str) -> Status:
        s: Optional[DynamicResources._State] = state.read(self._KEY)
        if s is None:
            return OK
        if s.special is not None and any(
                devices for claim, devices in s.node_allocations.get(node_name, ())
                if claim is s.special):
            # bindClaim (extendeddynamicresources.go): the in-memory claim
            # becomes a real API object; the pod records the mapping in
            # extended_resource_claim_status. When the node's device plugin
            # satisfied every request, no claim is created at all.
            self.handle.clientset.create_resource_claim(s.special)
            pod.extended_resource_claim_status = {
                "claim": s.special.key,
                "requests": [r.name for r in s.special.requests],
            }
        for claim, devices in s.node_allocations.get(node_name, ()):
            claim.allocated_node = node_name
            claim.allocations = list(devices)
            if pod.uid not in claim.reserved_for:
                claim.reserved_for.append(pod.uid)
            self.assumed.pop(claim.key, None)
            self.assumed_nodes.pop(claim.key, None)
        for claim in s.claims:
            if claim.allocated and pod.uid not in claim.reserved_for:
                claim.reserved_for.append(pod.uid)
        return OK


def allocate_pending_claims(clientset) -> int:
    """allocResourceClaims opcode (scheduler_perf dra configs): allocate every
    pending claim greedily against the cluster's ResourceSlices — the harness
    analogue of the DRA controller pre-allocating claims so measured pods only
    validate the pinned node. Returns the number of claims allocated."""
    used: Set[Tuple[str, str, str]] = set()
    for claim in clientset.resource_claims.values():
        if claim.allocated:
            for d in claim.allocations:
                used.add((claim.allocated_node, d.driver, d.device))
    n_alloc = 0
    for claim in clientset.resource_claims.values():
        if claim.allocated:
            continue
        for node_name, slices in clientset.resource_slices.items():
            taken: Set[Tuple[str, str]] = set()
            devices: List[AllocatedDevice] = []
            ok = True
            for req in claim.requests:
                sel = dict(req.selectors)
                if req.device_class:
                    dc = clientset.device_classes.get(req.device_class)
                    if dc is not None:
                        sel.update(dc.selectors)
                matcher = DynamicResources._matcher_for(req)  # compiled once
                found = 0
                for sl in slices:
                    for dev in sl.devices:
                        if found >= req.count:
                            break
                        key = (sl.driver, dev.name)
                        if key in taken or (node_name, sl.driver, dev.name) in used:
                            continue
                        if not all(dev.attributes.get(k) == v for k, v in sel.items()):
                            continue
                        if matcher is not None and not matcher(dev, sl.driver):
                            continue
                        devices.append(AllocatedDevice(sl.driver, dev.name))
                        taken.add(key)
                        found += 1
                if found < req.count:
                    ok = False
                    break
            if ok:
                claim.allocated_node = node_name
                claim.allocations = devices
                for d in devices:
                    used.add((node_name, d.driver, d.device))
                n_alloc += 1
                break
    if n_alloc and hasattr(clientset, "bump_resource_claims_rv"):
        clientset.bump_resource_claims_rv()
    return n_alloc
