"""DynamicResources plugin: DRA claim allocation during scheduling.

Reference anchors: plugins/dynamicresources/ (dynamicresources.go 2152 LoC,
dra_manager.go 512): PreFilter fetches the pod's claims (missing ⇒
unresolvable; allocated ⇒ node pinned to the allocation), Filter runs a
per-node allocation attempt over the node's ResourceSlices (structured
parameters), Reserve assumes the winning allocation in the shared assume
cache, Unreserve reverts, PreBind writes claim status + reservedFor.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from ..api.dra import AllocatedDevice, ResourceClaim
from ..api.types import Pod
from ..core.framework import OK, CycleState, PreFilterResult, Status
from ..core.node_info import NodeInfo

ERR_CLAIM_NOT_FOUND = 'resourceclaim "%s" not found'
ERR_ALLOCATED_ELSEWHERE = "resourceclaim was allocated for a different node"
ERR_NO_DEVICES = "node(s) didn't have enough free devices for the claims"


class DynamicResources:
    name = "DynamicResources"
    # Reserve/PreBind act only on CycleState written in PreFilter (no-ops on
    # a fresh state) — device commit fast-path eligible.
    state_driven_tail = True
    _KEY = "PreFilterDynamicResources"

    def __init__(self, handle=None):
        self.handle = handle
        # Assume cache (dra_manager.go / assumecache): device keys held by
        # in-flight reservations, per claim.
        self.assumed: Dict[str, List[AllocatedDevice]] = {}  # claim key -> devices
        self.assumed_nodes: Dict[str, str] = {}              # claim key -> node

    # -- listers -----------------------------------------------------------

    def _claims_for(self, pod: Pod) -> List[Optional[ResourceClaim]]:
        return [self.handle.resource_claims.get(f"{pod.namespace}/{name}")
                for name in getattr(pod, "resource_claims", ())]

    def _in_use(self) -> Set[Tuple[str, str, str]]:
        """(node, driver, device) triples already allocated or assumed."""
        used: Set[Tuple[str, str, str]] = set()
        for claim in self.handle.resource_claims.values():
            if claim.allocated:
                for d in claim.allocations:
                    used.add((claim.allocated_node, d.driver, d.device))
        for key, devices in self.assumed.items():
            node = self.assumed_nodes.get(key, "")
            for d in devices:
                used.add((node, d.driver, d.device))
        return used

    # -- PreFilter ---------------------------------------------------------

    @dataclass
    class _State:
        claims: List[ResourceClaim] = field(default_factory=list)
        pinned_node: str = ""  # allocation already fixes the node
        # node -> [(claim, devices)]
        node_allocations: Dict[str, List[Tuple[ResourceClaim, List[AllocatedDevice]]]] = field(default_factory=dict)
        # (node, driver, device) triples taken by existing allocations +
        # assumptions, computed ONCE per cycle in PreFilter — the per-node
        # Filter must not rescan every claim in the cluster (O(claims) per
        # node turned the 500-node DRA workload O(claims x nodes x pods)).
        in_use: Optional[Set[Tuple[str, str, str]]] = None

        def clone(self) -> "DynamicResources._State":
            return DynamicResources._State(
                claims=list(self.claims),
                pinned_node=self.pinned_node,
                node_allocations={k: list(v) for k, v in self.node_allocations.items()},
                in_use=set(self.in_use) if self.in_use is not None else None,
            )

    def pre_filter(self, state: CycleState, pod: Pod, nodes) -> Tuple[Optional[PreFilterResult], Status]:
        names = getattr(pod, "resource_claims", ())
        if not names:
            return None, Status.skip()
        s = self._State()
        pinned: Optional[str] = None
        for name in names:
            claim = self.handle.resource_claims.get(f"{pod.namespace}/{name}")
            if claim is None:
                return None, Status.unresolvable(ERR_CLAIM_NOT_FOUND % name)
            s.claims.append(claim)
            if claim.allocated:
                if pinned is not None and claim.allocated_node != pinned:
                    return None, Status.unresolvable(ERR_ALLOCATED_ELSEWHERE)
                pinned = claim.allocated_node
        s.in_use = self._in_use()
        state.write(self._KEY, s)
        if pinned is not None:
            s.pinned_node = pinned
            return PreFilterResult({pinned}), OK
        return None, OK

    # -- Filter: per-node allocation attempt -------------------------------

    def _resolve_selectors(self, req) -> Dict[str, str]:
        sel = dict(req.selectors)
        if req.device_class:
            dc = self.handle.device_classes.get(req.device_class)
            if dc is not None:
                sel.update(dc.selectors)
        return sel

    @staticmethod
    def _matcher_for(req):
        """Compiled expression selector, memoized on the request object
        (dynamic-resource-allocation/cel compiles each CEL program once)."""
        expr = getattr(req, "expression", "")
        if not expr:
            return None
        cached = getattr(req, "_compiled_expr", None)
        if cached is None:
            from ..api.dra import compile_device_expression
            cached = compile_device_expression(expr)
            req._compiled_expr = cached
        return cached

    def filter(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> Status:
        s: Optional[DynamicResources._State] = state.read(self._KEY)
        if s is None:
            return OK
        node_name = node_info.name
        if s.pinned_node:
            return OK if node_name == s.pinned_node else Status.unschedulable(
                ERR_ALLOCATED_ELSEWHERE)
        in_use = s.in_use if s.in_use is not None else self._in_use()
        taken: Set[Tuple[str, str]] = set()
        allocations: List[Tuple[ResourceClaim, List[AllocatedDevice]]] = []
        slices = self.handle.resource_slices.get(node_name, [])
        for claim in s.claims:
            if claim.allocated:
                continue
            devices: List[AllocatedDevice] = []
            for req in claim.requests:
                sel = self._resolve_selectors(req)
                found = 0
                for sl in slices:
                    for dev in sl.devices:
                        if found >= req.count:
                            break
                        key = (sl.driver, dev.name)
                        if key in taken or (node_name, sl.driver, dev.name) in in_use:
                            continue
                        if not all(dev.attributes.get(k) == v for k, v in sel.items()):
                            continue
                        matcher = self._matcher_for(req)
                        if matcher is not None and not matcher(dev, sl.driver):
                            continue
                        devices.append(AllocatedDevice(sl.driver, dev.name))
                        taken.add(key)
                        found += 1
                if found < req.count:
                    return Status.unschedulable(ERR_NO_DEVICES)
            allocations.append((claim, devices))
        s.node_allocations[node_name] = allocations
        return OK

    # -- Reserve / Unreserve / PreBind -------------------------------------

    def reserve(self, state: CycleState, pod: Pod, node_name: str) -> Status:
        s: Optional[DynamicResources._State] = state.read(self._KEY)
        if s is None:
            return OK
        for claim, devices in s.node_allocations.get(node_name, ()):
            self.assumed[claim.key] = devices
            self.assumed_nodes[claim.key] = node_name
        return OK

    def unreserve(self, state: CycleState, pod: Pod, node_name: str) -> None:
        s: Optional[DynamicResources._State] = state.read(self._KEY)
        if s is None:
            return
        for claim, _ in s.node_allocations.get(node_name, ()):
            self.assumed.pop(claim.key, None)
            self.assumed_nodes.pop(claim.key, None)

    def pre_bind_pre_flight(self, state: CycleState, pod: Pod,
                            node_name: str) -> Status:
        """PreBindPreFlight (dynamicresources.go PreBindPreFlight): Skip
        when the pod references no resource claims."""
        if not getattr(pod, "resource_claims", None):
            return Status.skip()
        return OK

    def pre_bind(self, state: CycleState, pod: Pod, node_name: str) -> Status:
        s: Optional[DynamicResources._State] = state.read(self._KEY)
        if s is None:
            return OK
        for claim, devices in s.node_allocations.get(node_name, ()):
            claim.allocated_node = node_name
            claim.allocations = list(devices)
            if pod.uid not in claim.reserved_for:
                claim.reserved_for.append(pod.uid)
            self.assumed.pop(claim.key, None)
            self.assumed_nodes.pop(claim.key, None)
        for claim in s.claims:
            if claim.allocated and pod.uid not in claim.reserved_for:
                claim.reserved_for.append(pod.uid)
        return OK


def allocate_pending_claims(clientset) -> int:
    """allocResourceClaims opcode (scheduler_perf dra configs): allocate every
    pending claim greedily against the cluster's ResourceSlices — the harness
    analogue of the DRA controller pre-allocating claims so measured pods only
    validate the pinned node. Returns the number of claims allocated."""
    used: Set[Tuple[str, str, str]] = set()
    for claim in clientset.resource_claims.values():
        if claim.allocated:
            for d in claim.allocations:
                used.add((claim.allocated_node, d.driver, d.device))
    n_alloc = 0
    for claim in clientset.resource_claims.values():
        if claim.allocated:
            continue
        for node_name, slices in clientset.resource_slices.items():
            taken: Set[Tuple[str, str]] = set()
            devices: List[AllocatedDevice] = []
            ok = True
            for req in claim.requests:
                sel = dict(req.selectors)
                if req.device_class:
                    dc = clientset.device_classes.get(req.device_class)
                    if dc is not None:
                        sel.update(dc.selectors)
                matcher = DynamicResources._matcher_for(req)  # compiled once
                found = 0
                for sl in slices:
                    for dev in sl.devices:
                        if found >= req.count:
                            break
                        key = (sl.driver, dev.name)
                        if key in taken or (node_name, sl.driver, dev.name) in used:
                            continue
                        if not all(dev.attributes.get(k) == v for k, v in sel.items()):
                            continue
                        if matcher is not None and not matcher(dev, sl.driver):
                            continue
                        devices.append(AllocatedDevice(sl.driver, dev.name))
                        taken.add(key)
                        found += 1
                if found < req.count:
                    ok = False
                    break
            if ok:
                claim.allocated_node = node_name
                claim.allocations = devices
                for d in devices:
                    used.add((node_name, d.driver, d.device))
                n_alloc += 1
                break
    return n_alloc
