"""Fork-addition plugins: NodeDeclaredFeatures, DeferredPodScheduling,
GangScheduling (Permit barrier).

Reference anchors:
- nodedeclaredfeatures/ (215 LoC): match pod feature requirements against
  NodeInfo.DeclaredFeatures.
- deferredpodscheduling/: KEP-style deferred scheduling — pods annotated for
  deferral are gated until the deferral window passes / annotation clears.
- gangscheduling/gangscheduling.go:45-47 (521 LoC): all-or-nothing
  enforcement via a Permit-based co-scheduling barrier for pods scheduled
  individually (the group-cycle path in core/scheduler.py covers entities
  that pop as one unit; this plugin covers the feature-gated per-pod mode).
"""

from __future__ import annotations

import time
from typing import Dict, Optional, Tuple

from ..api.types import Pod
from ..core.framework import OK, CycleState, Status, WAIT
from ..core.node_info import NodeInfo

DEFER_ANNOTATION = "scheduling.k8s.io/defer-until"


class NodeDeclaredFeatures:
    """Filter: every feature the pod requires must be declared true by the
    node (pod annotation `features.k8s.io/required: f1,f2`)."""

    name = "NodeDeclaredFeatures"
    REQUIRED_ANNOTATION = "features.k8s.io/required"

    def filter(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> Status:
        required = pod.annotations.get(self.REQUIRED_ANNOTATION, "")
        if not required:
            return OK
        declared = node_info.node.declared_features if node_info.node else {}
        for feat in required.split(","):
            feat = feat.strip()
            if feat and not declared.get(feat, False):
                return Status.unschedulable(
                    "node(s) didn't declare required feature " + feat)
        return OK

    def sign(self, pod: Pod):
        return pod.annotations.get(self.REQUIRED_ANNOTATION, "")


class DeferredPodScheduling:
    """PreEnqueue gate: pods carrying a defer-until timestamp stay gated
    until the deadline passes (fork's deferred scheduling addition)."""

    name = "DeferredPodScheduling"

    def __init__(self, now=time.time):
        self.now = now

    def pre_enqueue(self, pod: Pod) -> Status:
        raw = pod.annotations.get(DEFER_ANNOTATION, "")
        if not raw:
            return OK
        try:
            deadline = float(raw)
        except ValueError:
            return OK
        if self.now() < deadline:
            return Status.unresolvable(
                f"pod scheduling deferred until {deadline}")
        return OK


class GangScheduling:
    """Permit-based co-scheduling barrier (gangscheduling.go): a gang member
    scheduled individually WAITs at Permit until min_count peers hold
    reservations; the barrier rejects (unwinding all waiters) on timeout."""

    name = "GangScheduling"
    # Permit acts only on gang members (pod.pod_group); plain pods pass —
    # the device commit fast path checks pod_group itself.
    gang_only = True

    def __init__(self, handle=None, timeout_seconds: float = 60.0, now=time.monotonic):
        self.handle = handle
        self.timeout = timeout_seconds
        self.now = now
        # group key -> {pod uid: deadline}
        self.waiting: Dict[Tuple[str, str], Dict[str, float]] = {}

    def _group(self, pod: Pod):
        if not pod.pod_group:
            return None
        groups = getattr(self.handle.clientset, "pod_groups", {})
        return groups.get(f"{pod.namespace}/{pod.pod_group}")

    def permit(self, state: CycleState, pod: Pod, node_name: str) -> Status:
        group = self._group(pod)
        if group is None:
            return OK
        key = (pod.namespace, pod.pod_group)
        waiters = self.waiting.setdefault(key, {})
        waiters[pod.uid] = self.now() + self.timeout
        if len(waiters) >= max(1, group.min_count):
            # Barrier satisfied: Allow() every parked peer (waitingPod.Allow,
            # gangscheduling.go); the current pod proceeds synchronously.
            released = self.waiting.pop(key)
            allow = getattr(self.handle, "allow_waiting_pod", None)
            if allow is not None:
                for uid in released:
                    if uid != pod.uid:
                        allow(uid)
            return OK
        return Status(WAIT, (f"waiting for {group.min_count} gang members",),
                      self.name)

    def placement_feasible(self, state: CycleState, group, progress) -> Status:
        """PlacementFeasible gate (gangscheduling.go via framework.go:2160):
        a candidate placement stands only if it schedules at least min_count
        members of the group."""
        need = max(1, getattr(group, "min_count", 1))
        if progress.scheduled >= need:
            return OK
        return Status.unschedulable(
            f"placement schedules {progress.scheduled}/{progress.total} "
            f"members, need {need}")

    def unreserve(self, state: CycleState, pod: Pod, node_name: str) -> None:
        key = (pod.namespace, pod.pod_group)
        waiters = self.waiting.get(key)
        if waiters is not None:
            waiters.pop(pod.uid, None)
            if not waiters:
                self.waiting.pop(key, None)
