"""NodeResourcesFit and NodeResourcesBalancedAllocation.

Reference anchors:
- Filter semantics:  plugins/noderesources/fit.go (fitsRequest :710 — per-
  resource `request > allocatable − requested` rejection, Unresolvable when
  request > allocatable).
- LeastAllocated:    least_allocated.go:30-62
  score = Σ_r weight_r * (allocatable_r − requested_r) * 100 / allocatable_r / Σ weight.
- MostAllocated:     most_allocated.go (requested * 100 / allocatable).
- RequestedToCapacityRatio: requested_to_capacity_ratio.go (piecewise-linear
  interpolation over utilization shape points).
- BalancedAllocation: balanced_allocation.go:204-253
  score = (1 − std(fractions)) * 100, two-resource fast path |f1−f2|/2.
- Non-zero defaults: framework/types.go GetNonzeroRequests (100 mCPU / 200Mi)
  feed scoring (not filtering), via NodeInfo.non_zero_requested.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence, Tuple

from ..api import resource as res
from ..api.resource import Resource
from ..api.types import Pod
from ..core.framework import (
    MAX_NODE_SCORE,
    OK,
    CycleState,
    NodeScore,
    PreFilterResult,
    Status,
)
from ..core.node_info import NodeInfo

LEAST_ALLOCATED = "LeastAllocated"
MOST_ALLOCATED = "MostAllocated"
REQUESTED_TO_CAPACITY_RATIO = "RequestedToCapacityRatio"

DEFAULT_RESOURCES = ({"name": res.CPU, "weight": 1}, {"name": res.MEMORY, "weight": 1})


class InsufficientResource:
    __slots__ = ("resource_name", "requested", "used", "capacity", "unresolvable")

    def __init__(self, resource_name, requested, used, capacity, unresolvable=False):
        self.resource_name = resource_name
        self.requested = requested
        self.used = used
        self.capacity = capacity
        self.unresolvable = unresolvable


def fits_request(pod_request: Resource, node_info: NodeInfo, num_new_pods: int = 1) -> List[InsufficientResource]:
    """fit.go:710 fitsRequest."""
    out: List[InsufficientResource] = []
    alloc = node_info.allocatable
    used = node_info.requested
    if len(node_info.pods) + num_new_pods > alloc.allowed_pod_number:
        out.append(InsufficientResource(res.PODS, num_new_pods, len(node_info.pods), alloc.allowed_pod_number))
    if (
        pod_request.milli_cpu == 0
        and pod_request.memory == 0
        and pod_request.ephemeral_storage == 0
        and not pod_request.scalar_resources
    ):
        return out
    if pod_request.milli_cpu > 0 and pod_request.milli_cpu > alloc.milli_cpu - used.milli_cpu:
        out.append(InsufficientResource(
            res.CPU, pod_request.milli_cpu, used.milli_cpu, alloc.milli_cpu,
            unresolvable=pod_request.milli_cpu > alloc.milli_cpu))
    if pod_request.memory > 0 and pod_request.memory > alloc.memory - used.memory:
        out.append(InsufficientResource(
            res.MEMORY, pod_request.memory, used.memory, alloc.memory,
            unresolvable=pod_request.memory > alloc.memory))
    if (
        pod_request.ephemeral_storage > 0
        and pod_request.ephemeral_storage > alloc.ephemeral_storage - used.ephemeral_storage
    ):
        out.append(InsufficientResource(
            res.EPHEMERAL_STORAGE, pod_request.ephemeral_storage,
            used.ephemeral_storage, alloc.ephemeral_storage,
            unresolvable=pod_request.ephemeral_storage > alloc.ephemeral_storage))
    for name, amount in pod_request.scalar_resources.items():
        if amount == 0:
            continue
        a = alloc.scalar_resources.get(name, 0)
        u = used.scalar_resources.get(name, 0)
        if amount > a - u:
            out.append(InsufficientResource(name, amount, u, a, unresolvable=amount > a))
    return out


# ---------------------------------------------------------------------------
# Scoring strategies (resource_allocation.go scorer shapes)
# ---------------------------------------------------------------------------


def least_requested_score(requested: int, capacity: int) -> int:
    if capacity == 0 or requested > capacity:
        return 0
    return (capacity - requested) * MAX_NODE_SCORE // capacity


def most_requested_score(requested: int, capacity: int) -> int:
    if capacity == 0:
        return 0
    if requested > capacity:
        requested = capacity
    return requested * MAX_NODE_SCORE // capacity


def requested_to_capacity_ratio_score(requested: int, capacity: int, shape: Sequence[Tuple[int, int]]) -> int:
    """Piecewise-linear over utilization (0-100) -> score (0-10 scaled to 0-100).
    shape: sorted (utilization, score 0-10) points (requested_to_capacity_ratio.go
    buildRequestedToCapacityRatioScorerFunction)."""
    if capacity == 0:
        utilization = 100
    else:
        utilization = min(100, requested * 100 // capacity)
    if not shape:
        return 0
    if utilization <= shape[0][0]:
        raw = shape[0][1]
    elif utilization >= shape[-1][0]:
        raw = shape[-1][1]
    else:
        raw = shape[-1][1]
        for i in range(1, len(shape)):
            if utilization < shape[i][0]:
                u0, s0 = shape[i - 1]
                u1, s1 = shape[i]
                raw = s0 + (s1 - s0) * (utilization - u0) // (u1 - u0)
                break
    return raw * (MAX_NODE_SCORE // 10)


class Fit:
    """NodeResourcesFit (fit.go)."""

    name = "NodeResourcesFit"
    _KEY = "PreFilterNodeResourcesFit"

    def __init__(self, scoring_strategy: str = LEAST_ALLOCATED,
                 resources: Sequence[Dict] = DEFAULT_RESOURCES,
                 shape: Sequence[Tuple[int, int]] = ((0, 10), (100, 0)),
                 handle=None):
        self.scoring_strategy = scoring_strategy
        self.resources = tuple(resources)
        self.shape = tuple(shape)
        self.handle = handle
        self._has_dra = False  # set_framework: profile runs DynamicResources

    def set_framework(self, fw) -> None:
        self._has_dra = fw.plugin("DynamicResources") is not None

    def _effective_request(self, pod: Pod):
        """fit.go + extendeddynamicresources.go: extended resources mapped
        to a DeviceClass (DRAExtendedResource) are the DynamicResources
        plugin's to satisfy — strip them from the fit request so nodes
        without device-plugin capacity remain candidates."""
        req = pod.resource_request()
        handle = self.handle
        if handle is None or not self._has_dra or not req.scalar_resources:
            # Without the DynamicResources plugin in the profile, nothing
            # would ever satisfy the stripped resource — keep it in the fit.
            return req
        gates = getattr(handle, "gates", None)
        try:
            if gates is None or not gates.enabled("DRAExtendedResource"):
                return req
        except ValueError:
            return req
        classes = getattr(handle, "device_classes", None)
        if not classes:
            return req
        mapped = {dc.extended_resource_name for dc in classes.values()
                  if dc.extended_resource_name}
        strip = mapped & set(req.scalar_resources)
        if not strip:
            return req
        eff = req.clone()
        for name in strip:
            # The device plugin may still satisfy part of it; zeroing here
            # is exact because DynamicResources.filter re-checks the
            # device-plugin-vs-DRA split per node.
            eff.scalar_resources.pop(name, None)
        return eff

    # -- QueueingHints (fit.go EventsToRegister / isSchedulableAfterNodeChange
    # / isSchedulableAfterPodEvent) -----------------------------------------

    def events_to_register(self):
        from ..core.queue import (EVENT_ASSIGNED_POD_DELETE, EVENT_NODE_ADD,
                                  EVENT_NODE_UPDATE, EVENT_POD_DELETE)
        return [
            (EVENT_NODE_ADD, self._hint_node_change),
            (EVENT_NODE_UPDATE, self._hint_node_change),
            # Deletes always queue: every pod delete frees a pod slot, and
            # a Fit rejection may be pod-count-bound regardless of the
            # pending pod's resource requests (fits() "Too many pods") —
            # a freed-resource overlap test would strand such pods until
            # the unschedulable timeout.
            (EVENT_ASSIGNED_POD_DELETE, None),
            (EVENT_POD_DELETE, None),
        ]

    @staticmethod
    def _hint_node_change(pod: Pod, old, new) -> bool:
        """Queue only when the (new/updated) node could satisfy the
        request outright (fit.go isSchedulableAfterNodeChange)."""
        if new is None:
            return True
        req = pod.resource_request()
        alloc = new.allocatable
        if req.milli_cpu > alloc.milli_cpu or req.memory > alloc.memory:
            return False
        if req.ephemeral_storage > alloc.ephemeral_storage:
            return False
        for name, amount in req.scalar_resources.items():
            if amount > alloc.scalar_resources.get(name, 0):
                return False
        return True

    # -- filter -----------------------------------------------------------

    def pre_filter(self, state: CycleState, pod: Pod, nodes) -> Tuple[Optional[PreFilterResult], Status]:
        state.write(self._KEY, self._effective_request(pod))
        return None, OK

    def filter(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> Status:
        req = state.read(self._KEY)
        if req is None:
            req = pod.resource_request()
        insufficient = fits_request(req, node_info)
        if insufficient:
            reasons = tuple(f"Insufficient {r.resource_name}" for r in insufficient)
            if any(r.unresolvable for r in insufficient):
                return Status.unresolvable(*reasons)
            return Status.unschedulable(*reasons)
        return OK

    # AddPod/RemovePod PreFilter extensions are implicit: fits_request reads
    # live node_info aggregates, so preemption simulation just mutates the
    # cloned NodeInfo (cheaper than the reference's state delta tracking).

    # -- score ------------------------------------------------------------

    def _requested_on_node(self, name: str, node_info: NodeInfo, pod_request: Resource) -> Tuple[int, int]:
        alloc = node_info.allocatable.get(name)
        if name == res.CPU:
            used = node_info.non_zero_requested.milli_cpu + (pod_request.milli_cpu or NodeInfo.DEFAULT_MILLI_CPU)
        elif name == res.MEMORY:
            used = node_info.non_zero_requested.memory + (pod_request.memory or NodeInfo.DEFAULT_MEMORY)
        else:
            used = node_info.requested.get(name) + pod_request.get(name)
        return used, alloc

    def score(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> Tuple[int, Status]:
        req = state.read(self._KEY)
        if req is None:
            req = pod.resource_request()
        node_score = 0
        weight_sum = 0
        for spec in self.resources:
            name, weight = spec["name"], spec.get("weight", 1)
            used, alloc = self._requested_on_node(name, node_info, req)
            if alloc == 0:
                continue
            if self.scoring_strategy == LEAST_ALLOCATED:
                rscore = least_requested_score(used, alloc)
            elif self.scoring_strategy == MOST_ALLOCATED:
                rscore = most_requested_score(used, alloc)
            else:
                rscore = requested_to_capacity_ratio_score(used, alloc, self.shape)
            node_score += rscore * weight
            weight_sum += weight
        if weight_sum == 0:
            return 0, OK
        return node_score // weight_sum, OK

    def sign(self, pod: Pod):
        r = pod.resource_request()
        return (
            r.milli_cpu, r.memory, r.ephemeral_storage,
            tuple(sorted(r.scalar_resources.items())),
        )

    # -- placement scoring (resource_allocation.go:505 scorePlacement) ------

    def score_placement(self, state, group, pga) -> Tuple[int, "Status"]:
        """Score a whole candidate placement: the strategy formula over the
        placement-AGGREGATE requested/allocatable, where requested includes
        both the nodes' existing pods and the proposed group assignments
        (fit.go:873 ScorePlacement)."""
        node_score = 0
        weight_sum = 0
        for spec in self.resources:
            name, weight = spec["name"], spec.get("weight", 1)
            used = 0
            for pod, _node in pga.proposed:
                req = pod.resource_request()
                if name == res.CPU:
                    used += req.milli_cpu or NodeInfo.DEFAULT_MILLI_CPU
                elif name == res.MEMORY:
                    used += req.memory or NodeInfo.DEFAULT_MEMORY
                else:
                    used += req.get(name)
            alloc = 0
            for ni in pga.nodes:
                alloc += ni.allocatable.get(name)
                if name == res.CPU:
                    used += ni.non_zero_requested.milli_cpu
                elif name == res.MEMORY:
                    used += ni.non_zero_requested.memory
                else:
                    used += ni.requested.get(name)
            if alloc == 0:
                continue
            if self.scoring_strategy == LEAST_ALLOCATED:
                rscore = least_requested_score(used, alloc)
            elif self.scoring_strategy == MOST_ALLOCATED:
                rscore = most_requested_score(used, alloc)
            else:
                rscore = requested_to_capacity_ratio_score(used, alloc, self.shape)
            node_score += rscore * weight
            weight_sum += weight
        if weight_sum == 0:
            return 0, OK
        return node_score // weight_sum, OK


class BalancedAllocation:
    """NodeResourcesBalancedAllocation (balanced_allocation.go)."""

    name = "NodeResourcesBalancedAllocation"
    _KEY = "PreScoreNodeResourcesBalancedAllocation"

    def __init__(self, resources: Sequence[Dict] = DEFAULT_RESOURCES):
        self.resources = tuple(resources)

    def pre_score(self, state: CycleState, pod: Pod, nodes) -> Status:
        req = pod.resource_request()
        # Best-effort pods skip BalancedAllocation (balanced_allocation.go
        # PreScore Skip, kubernetes#129138).
        if all(req.get(spec["name"]) == 0 for spec in self.resources):
            return Status.skip()
        state.write(self._KEY, req)
        return OK

    # Utilization fractions are quantized to millionths (integer math) so the
    # host oracle and the device kernel (int64 tensors, ops/kernel.py) agree
    # bit-for-bit; the reference's float64 std (balanced_allocation.go:204-253)
    # differs from this by < 1e-4 score units.
    FRACTION_SCALE = 1_000_000

    def score(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> Tuple[int, Status]:
        req = state.read(self._KEY)
        if req is None:
            req = pod.resource_request()
        qs: List[int] = []
        for spec in self.resources:
            name = spec["name"]
            alloc = node_info.allocatable.get(name)
            if alloc == 0:
                continue
            if name == res.CPU:
                used = node_info.non_zero_requested.milli_cpu + (req.milli_cpu or NodeInfo.DEFAULT_MILLI_CPU)
            elif name == res.MEMORY:
                used = node_info.non_zero_requested.memory + (req.memory or NodeInfo.DEFAULT_MEMORY)
            else:
                used = node_info.requested.get(name) + req.get(name)
            qs.append(min(used * self.FRACTION_SCALE // alloc, self.FRACTION_SCALE))
        if len(qs) < 2:
            return MAX_NODE_SCORE, OK
        if len(qs) == 2:
            # floor(100 - 50*|f1-f2|) in exact integer arithmetic.
            return (MAX_NODE_SCORE * self.FRACTION_SCALE - 50 * abs(qs[0] - qs[1])) // self.FRACTION_SCALE, OK
        fractions = [q / self.FRACTION_SCALE for q in qs]
        mean = sum(fractions) / len(fractions)
        std = math.sqrt(sum((f - mean) ** 2 for f in fractions) / len(fractions))
        return int((1 - std) * MAX_NODE_SCORE), OK

    def sign(self, pod: Pod):
        r = pod.resource_request()
        return (r.milli_cpu, r.memory, tuple(sorted(r.scalar_resources.items())))
