"""Preemption: the DefaultPreemption PostFilter plugin + the dry-run
Evaluator.

Reference anchors:
- pkg/scheduler/framework/preemption/preemption.go — Evaluator.Preempt :181,
  findCandidates :201, DryRunPreemption :425 (per-node victim simulation),
  SelectCandidate / pickOneNodeForPreemption :286;
- plugins/defaultpreemption/default_preemption.go — PostFilter → Evaluator,
  victim ordering (lower priority first, then earlier start later),
  PodEligibleToPreemptOthers;
- async victim deletion (executor.go:171) is synchronous here; the
  APIDispatcher integration arrives with the async-writes subsystem.

The dry run is the host-side "what-if" path; its device-batched analogue
(DryRunPreemption as a second kernel, SURVEY.md §7.7) can replace the inner
loop later without changing this control flow.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from ..api.types import Pod
from ..core.framework import (
    OK,
    CycleState,
    Status,
    UNSCHEDULABLE,
    UNSCHEDULABLE_AND_UNRESOLVABLE,
)
from ..core.node_info import NodeInfo, PodInfo


@dataclass
class Candidate:
    """One feasible preemption plan (preemption.go candidate)."""

    node_name: str
    victims: List[PodInfo] = field(default_factory=list)
    num_pdb_violations: int = 0


@dataclass
class PostFilterResult:
    nominating_info: Optional[str] = None  # nominated node name


class Evaluator:
    """Preemption dry-run machinery (preemption.go Evaluator)."""

    MIN_CANDIDATE_NODES_PERCENTAGE = 10   # preemption.go minCandidateNodesPercentage
    MIN_CANDIDATE_NODES_ABSOLUTE = 100    # preemption.go minCandidateNodesAbsolute

    def __init__(self, handle, framework):
        self.handle = handle
        self.fw = framework
        self._offset = 0  # rotating start, GetOffsetAndNumCandidates
        self._last_start = None  # start used by the most recent dry run
        self.last_from_device = False  # candidates came from the kernel

    # -- eligibility (default_preemption.go PodEligibleToPreemptOthers) ----

    def pod_eligible(self, pod: Pod, snapshot) -> Tuple[bool, str]:
        if pod.preemption_policy == "Never":
            return False, "not eligible due to preemptionPolicy=Never"
        if pod.nominated_node_name:
            ni = snapshot.get(pod.nominated_node_name)
            if ni is not None:
                # A lower-priority pod already terminating on the nominated
                # node means preemption is in flight: don't preempt again.
                for pi in ni.pods:
                    if pi.pod.priority < pod.priority and pi.pod.deletion_ts is not None:
                        return False, "a terminating victim already exists on the nominated node"
        return True, ""

    # -- per-node dry run (preemption.go DryRunPreemption / SimulatePreemption)

    def dry_run_on_node(
        self, state: CycleState, pod: Pod, node_info: NodeInfo
    ) -> Optional[Candidate]:
        """Can `pod` fit on this node after evicting some lower-priority pods?
        Returns the minimal victim set (reprieve pass), or None."""
        ni = node_info.snapshot_clone()
        sim_state = state.clone()
        potential = [pi for pi in ni.pods if pi.pod.priority < pod.priority]
        if not potential:
            return None

        def remove_pod(pi: PodInfo) -> bool:
            if not ni.remove_pod(pi.pod):
                return False
            for p in self.fw.pre_filter_plugins:
                fn = getattr(p, "remove_pod", None)
                if fn is not None and not fn(sim_state, pod, pi, ni).is_success():
                    return False
            return True

        def add_pod(pi: PodInfo) -> bool:
            ni.add_pod(pi)
            for p in self.fw.pre_filter_plugins:
                fn = getattr(p, "add_pod", None)
                if fn is not None and not fn(sim_state, pod, pi, ni).is_success():
                    return False
            return True

        for pi in potential:
            if not remove_pod(pi):
                return None
        st = self.fw.run_filter_plugins(sim_state, pod, ni)
        if not st.is_success():
            return None

        # Reprieve: re-add victims most-important first — higher priority,
        # then EARLIER start time (MoreImportantPod; preemption.go:480-520) —
        # keeping those that still fit.
        potential.sort(key=lambda pi: (-pi.pod.priority, pi.pod.creation_ts))
        victims: List[PodInfo] = []
        for pi in potential:
            if not add_pod(pi):
                return None
            st = self.fw.run_filter_plugins(sim_state, pod, ni)
            if not st.is_success():
                # can't keep it: evict for real
                if not remove_pod(pi):
                    return None
                victims.append(pi)
        if not victims:
            return None  # pod fit without evicting anyone — not a preemption
        return Candidate(node_name=ni.name, victims=victims)

    def find_candidates(
        self, state: CycleState, pod: Pod, node_to_status: Dict[str, Status],
        force_host: bool = False,
    ) -> List[Candidate]:
        """DryRunPreemption over candidate nodes, capped at ~10% of the
        cluster (floor 100) from a rotating offset — the reference's
        GetOffsetAndNumCandidates (preemption.go:201,425). When the handle
        exposes a device backend, the per-node victim simulation runs as ONE
        batched kernel call (same rotation, same cap, same skip of
        unresolvable nodes); the caller host-verifies the selected
        candidate and passes force_host=True to recompute on divergence."""
        snapshot = self.handle.snapshot() if callable(self.handle.snapshot) else self.handle.snapshot
        nodes = snapshot.node_info_list
        n = len(nodes)
        if n == 0:
            return []
        num_candidates = max(
            n * self.MIN_CANDIDATE_NODES_PERCENTAGE // 100,
            self.MIN_CANDIDATE_NODES_ABSOLUTE)
        if force_host and self._last_start is not None:
            # Host recompute after a device-verify divergence: scan the SAME
            # rotation window the device pass used, and do NOT advance the
            # offset again — a pure-host run would have consumed exactly one
            # offset for this attempt.
            start = self._last_start
        else:
            start = self._offset % n
            self._offset += 1
            self._last_start = start
        self.last_from_device = False
        if not force_host:
            device_fn = getattr(self.handle, "device_dry_run_preemption", None)
            if device_fn is not None:
                cands = device_fn(self.fw, state, pod, node_to_status,
                                  num_candidates, start)
                if cands is not None:
                    self.last_from_device = True
                    return cands
        candidates: List[Candidate] = []
        for i in range(n):
            ni = nodes[(start + i) % n]
            st = node_to_status.get(ni.name)
            # Unresolvable rejections can't be fixed by evicting pods
            # (preemption.go nodesWherePreemptionMightHelp).
            if st is not None and st.code == UNSCHEDULABLE_AND_UNRESOLVABLE:
                continue
            cand = self.dry_run_on_node(state, pod, ni)
            if cand is not None:
                candidates.append(cand)
                if len(candidates) >= num_candidates:
                    break
        return candidates

    # -- selection (preemption.go pickOneNodeForPreemption) ----------------

    @staticmethod
    def select_candidate(candidates: List[Candidate]) -> Optional[Candidate]:
        if not candidates:
            return None
        if len(candidates) == 1:
            return candidates[0]

        def key(c: Candidate):
            highest = max(pi.pod.priority for pi in c.victims)
            prio_sum = sum(pi.pod.priority for pi in c.victims)
            latest_start = max(pi.pod.creation_ts for pi in c.victims)
            return (
                c.num_pdb_violations,   # fewest PDB violations
                highest,                # lowest highest-victim priority
                prio_sum,               # lowest priority sum
                len(c.victims),         # fewest victims
                -latest_start,          # latest victim start time survives
            )

        return min(candidates, key=key)

    # -- commit (preemption.go prepareCandidate / executor.go:171
    # prepareCandidateAsync) ------------------------------------------------

    def prepare_candidate(self, cand: Candidate, pod: Pod) -> None:
        """Evict the victims. Deletions route through the APIDispatcher
        (executor.go:171 prepareCandidateAsync: the scheduling cycle moves on
        while the API calls drain; in thread mode they physically run off the
        loop, in inline mode they complete immediately with identical
        semantics)."""
        cs = self.handle.clientset
        dispatcher = getattr(self.handle, "api_dispatcher", None)
        gates = getattr(self.handle, "gates", None)
        async_ok = True
        if gates is not None:
            try:
                async_ok = gates.enabled("SchedulerAsyncPreemption")
            except ValueError:
                pass
        metrics = getattr(self.handle, "metrics", None)

        def _delete(p):
            # preemption_goroutines_* (executor.go:171 prepareCandidateAsync
            # analogue): each victim deletion is one unit of async work.
            import time as _time
            _t0 = _time.perf_counter()
            try:
                cs.delete_pod(p)
            except Exception:
                if metrics is not None:
                    metrics.preemption_goroutines_execution_total.inc("error")
                raise
            if metrics is not None:
                metrics.preemption_goroutines_execution_total.inc("success")
                metrics.preemption_goroutines_duration.observe(
                    _time.perf_counter() - _t0)

        for pi in cand.victims:
            if dispatcher is not None and async_ok:
                from ..core.api_dispatcher import APICall, CALL_DELETE
                dispatcher.add(APICall(
                    call_type=CALL_DELETE, object_uid=pi.pod.uid,
                    execute=lambda p=pi.pod: _delete(p)))
            else:
                # SchedulerAsyncPreemption off: victims delete synchronously
                # inside the scheduling cycle (pre-gate behavior).
                _delete(pi.pod)
        # Lower-priority pods nominated to this node lose their nomination
        # (preemption.go prepareCandidate → ClearNominatedNodeName).
        nominator = getattr(self.handle, "nominator", None)
        if nominator is not None:
            for pi in list(nominator.nominated_pods_for_node(cand.node_name)):
                if pi.pod.priority < pod.priority:
                    nominator.delete_nominated_pod(pi.pod)
                    pi.pod.nominated_node_name = ""


class PodGroupEvaluator:
    """Pod-group preemption (preemption/podgrouppreemption.go:42
    PodGroupEvaluator): the preemptor is a whole group and the domain is the
    whole cluster. Remove every preemptible lower-priority pod, check the
    group schedules, then reprieve victims most-important-first while the
    group still fits (:139 selectVictimsOnDomain)."""

    def __init__(self, handle):
        self.handle = handle

    def preempt(self, group, members, simulate_fn) -> Tuple[List[PodInfo], Status]:
        """Returns (victims, status). `simulate_fn()` must attempt the whole
        group against the live snapshot and return True on feasibility,
        leaving the snapshot unchanged. NodeInfos are mutated during
        evaluation and ALWAYS restored before returning."""
        snapshot = self.handle.snapshot() if callable(self.handle.snapshot) else self.handle.snapshot
        preemptor_prio = max((m.pod.priority for m in members), default=0)
        potential: List[Tuple[NodeInfo, PodInfo]] = []
        for ni in snapshot.node_info_list:
            for pi in ni.pods:
                if (pi.pod.priority < preemptor_prio
                        and pi.pod.deletion_ts is None):
                    potential.append((ni, pi))
        if not potential:
            return [], Status.unresolvable(
                "pod-group preemption: no lower-priority pods")

        removed: List[Tuple[NodeInfo, PodInfo]] = []
        try:
            for ni, pi in potential:
                if ni.remove_pod(pi.pod):
                    removed.append((ni, pi))
            if not simulate_fn():
                return [], Status.unschedulable(
                    "pod-group preemption: group does not fit even after "
                    "removing all lower-priority pods")
            # Reprieve most-important-first (MoreImportantPod ordering).
            removed.sort(key=lambda t: (-t[1].pod.priority, t[1].pod.creation_ts))
            victims: List[PodInfo] = []
            for ni, pi in list(removed):
                ni.add_pod(pi)
                if simulate_fn():
                    removed.remove((ni, pi))  # reprieved: stays restored
                else:
                    ni.remove_pod(pi.pod)
                    victims.append(pi)
            return victims, OK
        finally:
            for ni, pi in removed:  # restore every still-removed victim
                ni.add_pod(pi)


class DefaultPreemption:
    """plugins/defaultpreemption — PostFilter extension point."""

    name = "DefaultPreemption"

    def __init__(self, handle=None, framework=None):
        self.handle = handle
        self._evaluator: Optional[Evaluator] = None
        self._framework = framework

    def set_framework(self, fw) -> None:
        self._framework = fw
        self._evaluator = None

    @property
    def evaluator(self) -> Evaluator:
        if self._evaluator is None:
            self._evaluator = Evaluator(self.handle, self._framework)
        return self._evaluator

    def post_filter(
        self, state: CycleState, pod: Pod, filtered_status_map: Dict[str, Status]
    ) -> Tuple[Optional[PostFilterResult], Status]:
        snapshot = self.handle.snapshot() if callable(self.handle.snapshot) else self.handle.snapshot
        ok, msg = self.evaluator.pod_eligible(pod, snapshot)
        if not ok:
            return None, Status.unresolvable(f"preemption: {msg}")
        metrics = getattr(self.handle, "metrics", None)
        if metrics is not None:
            metrics.preemption_attempts.inc()
        import time as _time
        _t_eval = _time.perf_counter()
        candidates = self.evaluator.find_candidates(state, pod, filtered_status_map)
        if metrics is not None:
            metrics.preemption_evaluation_duration.observe(
                _time.perf_counter() - _t_eval)
        if not candidates:
            return None, Status.unresolvable(
                "preemption: 0/%d nodes are available" % max(1, snapshot.num_nodes()))
        # Extender preempt verb (preemption.go callExtenders /
        # extender.go:46-49 ProcessPreemption): preempt-capable extenders
        # narrow the candidate victim map before selection.
        extenders = getattr(self.handle, "extenders", None) or ()
        if any(e.supports_preemption() for e in extenders):
            # Extender-trimmed victim sets are extender-authoritative: the
            # host dry run can't reproduce them, so skip device verification.
            self.evaluator.last_from_device = False
            from ..core.extender import run_extender_preemption
            victim_map = {c.node_name: c.victims for c in candidates}
            victim_map, err = run_extender_preemption(extenders, pod, victim_map)
            if err is not None:
                # Retryable failure (preemption.go callExtenders → AsStatus):
                # the attempt errors; it must NOT park the pod unresolvable.
                return None, Status.error(f"extender preemption: {err}")
            candidates = [
                # num_pdb_violations carries over only because no PDB API
                # exists yet (always 0); with PDBs it must be recomputed
                # from the trimmed victim list.
                Candidate(node_name=c.node_name,
                          victims=victim_map[c.node_name],
                          num_pdb_violations=c.num_pdb_violations)
                for c in candidates
                if c.node_name in victim_map and victim_map[c.node_name]]
            if not candidates:
                return None, Status.unresolvable(
                    "preemption: extenders rejected all candidates")
        best = self.evaluator.select_candidate(candidates)
        if self.evaluator.last_from_device and best is not None:
            # Host-verify the device-selected candidate: the exact per-node
            # dry run must reproduce the victim set. On divergence (a kernel
            # coverage bug), the host loop is authoritative.
            ni = snapshot.get(best.node_name)
            verified = (self.evaluator.dry_run_on_node(state, pod, ni)
                        if ni is not None else None)
            if verified is None or (
                    {pi.pod.uid for pi in verified.victims}
                    != {pi.pod.uid for pi in best.victims}):
                candidates = self.evaluator.find_candidates(
                    state, pod, filtered_status_map, force_host=True)
                if not candidates:
                    return None, Status.unresolvable(
                        "preemption: 0/%d nodes are available"
                        % max(1, snapshot.num_nodes()))
                best = self.evaluator.select_candidate(candidates)
            else:
                best = Candidate(node_name=best.node_name,
                                 victims=verified.victims,
                                 num_pdb_violations=best.num_pdb_violations)
        _t_exec = _time.perf_counter()
        self.evaluator.prepare_candidate(best, pod)
        if metrics is not None:
            metrics.preemption_execution_duration.observe(
                _time.perf_counter() - _t_exec)
            if best.num_pdb_violations:
                metrics.preemption_pdb_violations.inc(
                    value=best.num_pdb_violations)
        if metrics is not None:
            metrics.preemption_victims.observe(len(best.victims))
        # Success: the scheduler records the nomination and requeues
        # (preemption.go Preempt returns Success + nominated node).
        return PostFilterResult(nominating_info=best.node_name), OK

    # -- pod-group preemption (PodGroupPostFilter; podgrouppreemption.go) ---

    def pod_group_post_filter(
        self, state: CycleState, group, members, diagnosis
    ) -> Tuple[Optional[PostFilterResult], Status]:
        simulate = getattr(self.handle, "simulate_pod_group", None)
        if simulate is None or not members:
            return None, Status.unschedulable("pod-group preemption unavailable")
        ev = PodGroupEvaluator(self.handle)
        metrics = getattr(self.handle, "metrics", None)
        victims, st = ev.preempt(group, members, lambda: simulate(group, members))
        if not st.is_success() or not victims:
            if metrics is not None:
                metrics.workload_preemption_attempts.inc("no_victims")
            return None, st if not st.is_success() else Status.unschedulable(
                "pod-group preemption found no victim set")
        if metrics is not None:
            metrics.preemption_attempts.inc()
            metrics.preemption_victims.observe(len(victims))
            metrics.workload_preemption_attempts.inc("preempted")
            metrics.workload_preemption_victims.observe(len(victims))
            disrupted = {(pi.pod.namespace, pi.pod.pod_group)
                         for pi in victims if pi.pod.pod_group}
            if disrupted:
                metrics.preemption_workload_disruptions.inc(
                    value=len(disrupted))
        cs = self.handle.clientset
        dispatcher = getattr(self.handle, "api_dispatcher", None)
        for pi in victims:
            if dispatcher is not None:
                from ..core.api_dispatcher import APICall, CALL_DELETE
                dispatcher.add(APICall(
                    call_type=CALL_DELETE, object_uid=pi.pod.uid,
                    execute=lambda p=pi.pod: cs.delete_pod(p)))
            else:
                cs.delete_pod(pi.pod)
        return PostFilterResult(), OK
