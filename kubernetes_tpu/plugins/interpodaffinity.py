"""InterPodAffinity (plugins/interpodaffinity/: plugin.go, filtering.go,
scoring.go).

PreFilter (filtering.go:287) builds three topology-pair count maps:
  1. existingAntiAffinityCounts — existing pods' REQUIRED anti-affinity terms
     that match the incoming pod, keyed by (topologyKey, node's topologyValue)
     (filtering.go:217-241);
  2. affinityCounts — incoming pod's required affinity terms vs existing pods;
  3. antiAffinityCounts — incoming pod's required anti-affinity terms vs
     existing pods (filtering.go:247-284).
Filter (filtering.go:428) is then O(constraints) per node via the maps.

PreScore/Score (scoring.go): weighted preferred-term matches accumulated per
(topologyKey, topologyValue); existing pods' required affinity terms count with
hardPodAffinityWeight. Normalize maps [min,max] -> [0,100] (scoring.go:258-289).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from ..api.types import Pod
from ..core.framework import (
    MAX_NODE_SCORE,
    OK,
    CycleState,
    NodeScore,
    PreFilterResult,
    Status,
)
from ..core.node_info import NodeInfo, PodInfo
from .helpers import AffinityTerm, compile_terms

ERR_EXISTING_ANTI = "node(s) didn't satisfy existing pods anti-affinity rules"
ERR_ANTI = "node(s) didn't match pod anti-affinity rules"
ERR_AFFINITY = "node(s) didn't match pod affinity rules"


@dataclass
class _PreFilterState:
    affinity_terms: tuple = ()
    anti_affinity_terms: tuple = ()
    # (topology_key, topology_value) -> count
    existing_anti_counts: Dict[Tuple[str, str], int] = field(default_factory=dict)
    affinity_counts: List[Dict[str, int]] = field(default_factory=list)  # per-term: tpVal->count
    anti_counts: Dict[Tuple[str, str], int] = field(default_factory=dict)

    def clone(self) -> "_PreFilterState":
        """Deep-clone for CycleState.clone() (what-if simulations)."""
        return _PreFilterState(
            affinity_terms=self.affinity_terms,
            anti_affinity_terms=self.anti_affinity_terms,
            existing_anti_counts=dict(self.existing_anti_counts),
            affinity_counts=[dict(m) for m in self.affinity_counts],
            anti_counts=dict(self.anti_counts),
        )


class InterPodAffinity:
    name = "InterPodAffinity"
    _FKEY = "PreFilterInterPodAffinity"
    _SKEY = "PreScoreInterPodAffinity"

    def __init__(self, handle=None, hard_pod_affinity_weight: int = 1,
                 ignore_preferred_terms_of_existing_pods: bool = False):
        self.handle = handle
        self.hard_pod_affinity_weight = hard_pod_affinity_weight
        self.ignore_preferred_terms_of_existing_pods = ignore_preferred_terms_of_existing_pods

    def _ns_labels(self, ns: str):
        if self.handle is not None:
            fn = getattr(self.handle, "namespace_labels", None)
            if fn is not None:
                return fn(ns)
        return None

    # -- QueueingHints (interpodaffinity EventsToRegister /
    # isSchedulableAfterPodChange) ------------------------------------------

    def events_to_register(self):
        from ..core.queue import (EVENT_ASSIGNED_POD_ADD,
                                  EVENT_ASSIGNED_POD_DELETE, EVENT_NODE_ADD,
                                  EVENT_NODE_UPDATE, EVENT_POD_DELETE)
        return [
            (EVENT_ASSIGNED_POD_ADD, self._hint_pod),
            (EVENT_ASSIGNED_POD_DELETE, self._hint_pod),
            (EVENT_POD_DELETE, self._hint_pod),
            (EVENT_NODE_ADD, None),     # topology domains may appear
            (EVENT_NODE_UPDATE, None),  # (label changes) — always queue
        ]

    @staticmethod
    def _hint_terms(pod: Pod):
        """Per-pod memo of compiled required terms: hint fns run once per
        parked pod per cluster event (O(events x pods)), and the compiled
        terms are constant per pod spec."""
        cached = pod.__dict__.get("_ipa_hint_terms")
        if cached is None:
            pi = PodInfo.of(pod)
            cached = pod._ipa_hint_terms = (
                compile_terms(pi.required_affinity_terms, pod),
                compile_terms(pi.required_anti_affinity_terms, pod),
            )
        return cached

    def _hint_pod(self, pod: Pod, old, new) -> bool:
        """A pod add can satisfy a required affinity term; a pod delete can
        clear an anti-affinity conflict (in either direction). Queue only
        when the other pod matches one of this pod's required terms, or this
        pod matches the other's anti terms (isSchedulableAfterPodChange)."""
        other = new if new is not None else old
        if other is None:
            return True
        aff_terms, anti_terms = self._hint_terms(pod)
        for term in aff_terms:
            if term.matches(other, self._ns_labels):
                return True
        for term in anti_terms:
            if term.matches(other, self._ns_labels):
                return True
        o_aff, o_anti = self._hint_terms(other)
        for term in o_anti:
            if term.matches(pod, self._ns_labels):
                return True
        return False

    # -- PreFilter ---------------------------------------------------------

    def pre_filter(self, state: CycleState, pod: Pod, nodes: Sequence[NodeInfo]) -> Tuple[Optional[PreFilterResult], Status]:
        pi = PodInfo.of(pod)
        aff_terms = compile_terms(pi.required_affinity_terms, pod)
        anti_terms = compile_terms(pi.required_anti_affinity_terms, pod)
        s = _PreFilterState(affinity_terms=aff_terms, anti_affinity_terms=anti_terms)
        s.affinity_counts = [dict() for _ in aff_terms]

        # 1. existing pods' required anti-affinity vs incoming pod — only
        #    nodes that host such pods need scanning (filtering.go uses the
        #    HavePodsWithRequiredAntiAffinityList sublist). When `nodes` IS
        #    the snapshot's full list, use its maintained sublist instead of
        #    an O(all nodes) scan per pod — at 15k nodes with zero
        #    anti-affinity pods the scan alone dominated the daemonset
        #    workload's cycle time.
        anti_nodes = nodes
        if self.handle is not None:
            snap_fn = getattr(self.handle, "snapshot", None)
            if snap_fn is not None:
                snap = snap_fn()
                if snap.node_info_list is nodes:
                    anti_nodes = snap.have_pods_with_required_anti_affinity_list
        for ni in anti_nodes:
            if not ni.pods_with_required_anti_affinity:
                continue
            node = ni.node
            if node is None:
                continue
            for epi in ni.pods_with_required_anti_affinity:
                for term in compile_terms(epi.required_anti_affinity_terms, epi.pod):
                    tp_val = node.labels.get(term.topology_key)
                    if tp_val is None:
                        continue
                    if term.matches(pod, self._ns_labels):
                        key = (term.topology_key, tp_val)
                        s.existing_anti_counts[key] = s.existing_anti_counts.get(key, 0) + 1

        # 2+3. incoming pod's required terms vs all existing pods.
        if aff_terms or anti_terms:
            for ni in nodes:
                node = ni.node
                if node is None or not ni.pods:
                    continue
                for epi in ni.pods:
                    ep = epi.pod
                    for i, term in enumerate(aff_terms):
                        tp_val = node.labels.get(term.topology_key)
                        if tp_val is not None and term.matches(ep, self._ns_labels):
                            s.affinity_counts[i][tp_val] = s.affinity_counts[i].get(tp_val, 0) + 1
                    for term in anti_terms:
                        tp_val = node.labels.get(term.topology_key)
                        if tp_val is not None and term.matches(ep, self._ns_labels):
                            key = (term.topology_key, tp_val)
                            s.anti_counts[key] = s.anti_counts.get(key, 0) + 1

        if not aff_terms and not anti_terms and not s.existing_anti_counts:
            state.write(self._FKEY, s)
            return None, Status.skip()
        state.write(self._FKEY, s)
        return None, OK

    # AddPod/RemovePod extensions for preemption dry runs
    # (filtering.go updateWithPod).
    def add_pod(self, state: CycleState, pod: Pod, added: PodInfo, node_info: NodeInfo) -> Status:
        self._update(state, pod, added, node_info, +1)
        return OK

    def remove_pod(self, state: CycleState, pod: Pod, removed: PodInfo, node_info: NodeInfo) -> Status:
        self._update(state, pod, removed, node_info, -1)
        return OK

    def _update(self, state: CycleState, pod: Pod, other: PodInfo, node_info: NodeInfo, delta: int) -> None:
        s: _PreFilterState = state.read(self._FKEY)
        if s is None:
            return
        node = node_info.node
        if node is None:
            return
        for term in compile_terms(other.required_anti_affinity_terms, other.pod):
            tp_val = node.labels.get(term.topology_key)
            if tp_val is not None and term.matches(pod, self._ns_labels):
                key = (term.topology_key, tp_val)
                s.existing_anti_counts[key] = s.existing_anti_counts.get(key, 0) + delta
        for i, term in enumerate(s.affinity_terms):
            tp_val = node.labels.get(term.topology_key)
            if tp_val is not None and term.matches(other.pod, self._ns_labels):
                s.affinity_counts[i][tp_val] = s.affinity_counts[i].get(tp_val, 0) + delta
        for term in s.anti_affinity_terms:
            tp_val = node.labels.get(term.topology_key)
            if tp_val is not None and term.matches(other.pod, self._ns_labels):
                key = (term.topology_key, tp_val)
                s.anti_counts[key] = s.anti_counts.get(key, 0) + delta

    # -- Filter ------------------------------------------------------------

    def filter(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> Status:
        s: _PreFilterState = state.read(self._FKEY)
        if s is None:
            return OK
        node = node_info.node
        # existing pods' anti-affinity (filtering.go:368).
        for (tp_key, tp_val), count in s.existing_anti_counts.items():
            if count > 0 and node.labels.get(tp_key) == tp_val:
                return Status.unschedulable(ERR_EXISTING_ANTI)
        # incoming pod's anti-affinity.
        for term in s.anti_affinity_terms:
            tp_val = node.labels.get(term.topology_key)
            if tp_val is None:
                continue
            if s.anti_counts.get((term.topology_key, tp_val), 0) > 0:
                return Status.unschedulable(ERR_ANTI)
        # incoming pod's affinity (filtering.go:398 satisfyPodAffinity).
        if s.affinity_terms:
            all_matched = True
            has_all_keys = True
            for i, term in enumerate(s.affinity_terms):
                tp_val = node.labels.get(term.topology_key)
                if tp_val is None:
                    # satisfyPodAffinity (interpodaffinity/filtering.go:398):
                    # a node missing any term's topology key can never satisfy
                    # the term — not even via the bootstrap case below. Keep
                    # walking all terms so has_all_keys reflects every key.
                    has_all_keys = False
                    all_matched = False
                elif s.affinity_counts[i].get(tp_val, 0) == 0:
                    all_matched = False
            if not all_matched:
                # Bootstrap special case: no pod anywhere matches any term and
                # the incoming pod matches its own terms => allow (on nodes
                # that carry every requested topology key).
                no_matches_anywhere = all(not c for c in s.affinity_counts)
                if has_all_keys and no_matches_anywhere and all(
                    term.matches(pod, self._ns_labels) for term in s.affinity_terms
                ):
                    return OK
                return Status.unschedulable(ERR_AFFINITY)
        return OK

    # -- PreScore / Score --------------------------------------------------

    def pre_score(self, state: CycleState, pod: Pod, nodes: Sequence[NodeInfo]) -> Status:
        pi = PodInfo.of(pod)
        has_preferred = bool(pi.preferred_affinity_terms or pi.preferred_anti_affinity_terms)
        all_nodes = nodes
        affinity_only = False
        if self.handle is not None:
            snap = self.handle.snapshot() if callable(self.handle.snapshot) else self.handle.snapshot
            if has_preferred:
                all_nodes = snap.node_info_list
            else:
                all_nodes = snap.have_pods_with_affinity_list
                affinity_only = True
        if not has_preferred and not any(ni.pods_with_affinity for ni in all_nodes):
            return Status.skip()

        pref_aff = tuple(
            (w.weight, t) for w, t in
            ((w, compile_terms((w.term,), pod)[0]) for w in pi.preferred_affinity_terms)
        )
        pref_anti = tuple(
            (w.weight, t) for w, t in
            ((w, compile_terms((w.term,), pod)[0]) for w in pi.preferred_anti_affinity_terms)
        )

        topology_score: Dict[str, Dict[str, int]] = {}

        def add(tp_key: str, tp_val: str, w: int) -> None:
            if w == 0:
                return
            topology_score.setdefault(tp_key, {})
            topology_score[tp_key][tp_val] = topology_score[tp_key].get(tp_val, 0) + w

        for ni in all_nodes:
            node = ni.node
            if node is None:
                continue
            pods = ni.pods_with_affinity if affinity_only else ni.pods
            for epi in pods:
                ep = epi.pod
                # incoming pod's preferred terms vs existing pod
                for weight, term in pref_aff:
                    tp_val = node.labels.get(term.topology_key)
                    if tp_val is not None and term.matches(ep, self._ns_labels):
                        add(term.topology_key, tp_val, weight)
                for weight, term in pref_anti:
                    tp_val = node.labels.get(term.topology_key)
                    if tp_val is not None and term.matches(ep, self._ns_labels):
                        add(term.topology_key, tp_val, -weight)
                # existing pod's terms vs incoming pod (symmetry)
                if self.hard_pod_affinity_weight > 0:
                    for term in compile_terms(epi.required_affinity_terms, ep):
                        tp_val = node.labels.get(term.topology_key)
                        if tp_val is not None and term.matches(pod, self._ns_labels):
                            add(term.topology_key, tp_val, self.hard_pod_affinity_weight)
                if not self.ignore_preferred_terms_of_existing_pods:
                    for wt in epi.preferred_affinity_terms:
                        term = compile_terms((wt.term,), ep)[0]
                        tp_val = node.labels.get(term.topology_key)
                        if tp_val is not None and term.matches(pod, self._ns_labels):
                            add(term.topology_key, tp_val, wt.weight)
                    for wt in epi.preferred_anti_affinity_terms:
                        term = compile_terms((wt.term,), ep)[0]
                        tp_val = node.labels.get(term.topology_key)
                        if tp_val is not None and term.matches(pod, self._ns_labels):
                            add(term.topology_key, tp_val, -wt.weight)

        if not topology_score:
            return Status.skip()
        state.write(self._SKEY, topology_score)
        return OK

    def score(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> Tuple[int, Status]:
        topology_score = state.read(self._SKEY)
        if not topology_score:
            return 0, OK
        node = node_info.node
        score = 0
        for tp_key, vals in topology_score.items():
            v = node.labels.get(tp_key)
            if v is not None:
                score += vals.get(v, 0)
        return score, OK

    def normalize_score(self, state: CycleState, pod: Pod, scores: List[NodeScore]) -> None:
        topology_score = state.read(self._SKEY)
        if not topology_score:
            return
        min_count = min(s.score for s in scores)
        max_count = max(s.score for s in scores)
        diff = max_count - min_count
        for s in scores:
            if diff > 0:
                # floor division: identical to the reference's float-then-trunc
                # for the non-negative numerator, and exact on device int64.
                s.score = MAX_NODE_SCORE * (s.score - min_count) // diff
            else:
                s.score = 0

    def sign(self, pod: Pod):
        aff = pod.affinity
        return (
            tuple(sorted(pod.labels.items())),
            pod.namespace,
            repr(aff.pod_affinity) if aff and aff.pod_affinity else "",
            repr(aff.pod_anti_affinity) if aff and aff.pod_anti_affinity else "",
        )
