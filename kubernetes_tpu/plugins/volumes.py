"""Volume plugins: VolumeBinding, NodeVolumeLimits (CSI), VolumeZone,
VolumeRestrictions.

Reference anchors:
- volumebinding/ (binder.go 1131 + volume_binding.go 659): PVC partition in
  PreFilter (bound / unbound-delayed / unbound-immediate), per-node
  FindPodVolumes in Filter (bound-PV node affinity; matching available PVs
  for unbound claims; dynamic provisioning check), AssumePodVolumes in
  Reserve, BindPodVolumes API writes in PreBind, revert in Unreserve.
- nodevolumelimits/csi.go (706): per-driver attach counting vs CSINode
  allocatable limits.
- volumezone/ (415): bound PV zone/region labels must match node labels.
- volumerestrictions/ (432): ReadWriteOncePod conflicts (+ pre-existing
  single-attach rules for legacy in-tree drivers, which are CSI-migrated and
  not re-implemented here).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from ..api.storage import (
    IMMEDIATE,
    RWOP,
    WAIT_FOR_FIRST_CONSUMER,
    PersistentVolume,
    PersistentVolumeClaim,
)
from ..api.types import LABEL_ZONE, LABEL_REGION, Pod
from ..core.framework import OK, CycleState, PreFilterResult, Status
from ..core.node_info import NodeInfo, PodInfo

ERR_UNBOUND_IMMEDIATE = "pod has unbound immediate PersistentVolumeClaims"
ERR_NODE_CONFLICT = "node(s) had volume node affinity conflict"
ERR_NO_MATCH = "node(s) didn't find available persistent volumes to bind"
ERR_ZONE = "node(s) had no available volume zone"
ERR_RWOP = "pod uses a ReadWriteOncePod PVC that is already in use by another pod"
ERR_LIMIT = "node(s) exceed max volume count"


def _pod_pvc_names(pod: Pod) -> List[str]:
    return [v.pvc_name for v in pod.volumes if v.pvc_name]


class VolumeBinding:
    """volumebinding/volume_binding.go."""

    name = "VolumeBinding"
    # Reserve/PreBind read only the CycleState written in PreFilter/Filter:
    # with a fresh empty state they are no-ops, so the device commit fast
    # path may skip them (models/tpu_scheduler.py _commit_fast_eligible).
    state_driven_tail = True
    _KEY = "PreFilterVolumeBinding"

    def __init__(self, handle=None):
        self.handle = handle
        # PV assume layer (binder.go AssumeCache): pv name -> pvc key, held
        # until the PVC's bind is observed or the reservation unwinds.
        self.assumed: Dict[str, str] = {}

    # -- listers -----------------------------------------------------------

    def _pvc(self, ns: str, name: str) -> Optional[PersistentVolumeClaim]:
        return self.handle.pvcs.get(f"{ns}/{name}")

    def _pv(self, name: str) -> Optional[PersistentVolume]:
        return self.handle.pvs.get(name)

    def _class(self, name: str):
        return self.handle.storage_classes.get(name)

    # -- PreFilter ---------------------------------------------------------

    @dataclass
    class _State:
        bound: List[PersistentVolumeClaim] = field(default_factory=list)
        unbound_delayed: List[PersistentVolumeClaim] = field(default_factory=list)
        # node name -> [(pvc, pv_name or "" for provisioning)]
        node_decisions: Dict[str, List[Tuple[PersistentVolumeClaim, str]]] = field(default_factory=dict)

        def clone(self) -> "VolumeBinding._State":
            return VolumeBinding._State(
                bound=list(self.bound),
                unbound_delayed=list(self.unbound_delayed),
                node_decisions={k: list(v) for k, v in self.node_decisions.items()},
            )

    def pre_filter(self, state: CycleState, pod: Pod, nodes) -> Tuple[Optional[PreFilterResult], Status]:
        names = _pod_pvc_names(pod)
        if not names:
            return None, Status.skip()
        s = self._State()
        for name in names:
            pvc = self._pvc(pod.namespace, name)
            if pvc is None:
                return None, Status.unresolvable(
                    f'persistentvolumeclaim "{name}" not found')
            if pvc.volume_name:
                s.bound.append(pvc)
                continue
            sc = self._class(pvc.storage_class)
            if sc is not None and sc.volume_binding_mode == WAIT_FOR_FIRST_CONSUMER:
                s.unbound_delayed.append(pvc)
            else:
                # Immediate-mode claims must be bound by the PV controller
                # before scheduling (volume_binding.go PreFilter).
                return None, Status.unresolvable(ERR_UNBOUND_IMMEDIATE)
        state.write(self._KEY, s)
        return None, OK

    # -- Filter (binder.go FindPodVolumes) ---------------------------------

    def filter(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> Status:
        s: Optional[VolumeBinding._State] = state.read(self._KEY)
        if s is None:
            return OK
        node = node_info.node
        for pvc in s.bound:
            pv = self._pv(pvc.volume_name)
            if pv is None:
                return Status.unresolvable(f'persistentvolume "{pvc.volume_name}" not found')
            if pv.node_affinity is not None and not pv.node_affinity.matches(node):
                return Status.unschedulable(ERR_NODE_CONFLICT)
        if not s.unbound_delayed:
            return OK
        decisions: List[Tuple[PersistentVolumeClaim, str]] = []
        used = set()
        for pvc in s.unbound_delayed:
            pv = self._find_matching_pv(pvc, node, used)
            if pv is not None:
                used.add(pv.name)
                decisions.append((pvc, pv.name))
                continue
            sc = self._class(pvc.storage_class)
            if sc is not None and sc.provisioner:
                # Dynamic provisioning possible; honor allowedTopologies.
                if sc.allowed_topologies is not None and not sc.allowed_topologies.matches(node):
                    return Status.unschedulable(ERR_NO_MATCH)
                decisions.append((pvc, ""))
                continue
            return Status.unschedulable(ERR_NO_MATCH)
        s.node_decisions[node.name] = decisions
        return OK

    def _find_matching_pv(self, pvc: PersistentVolumeClaim, node, used) -> Optional[PersistentVolume]:
        """binder.go findMatchingVolume: smallest available PV satisfying
        class/modes/capacity/affinity."""
        best = None
        for pv in self.handle.pvs.values():
            if pv.name in used or pv.claim_ref or pv.name in self.assumed:
                continue
            if pv.storage_class != pvc.storage_class:
                continue
            if not set(pvc.access_modes) <= set(pv.access_modes):
                continue
            if pv.capacity < pvc.request:
                continue
            if pv.node_affinity is not None and not pv.node_affinity.matches(node):
                continue
            if best is None or pv.capacity < best.capacity:
                best = pv
        return best

    # -- Reserve / Unreserve / PreBind -------------------------------------

    def reserve(self, state: CycleState, pod: Pod, node_name: str) -> Status:
        s: Optional[VolumeBinding._State] = state.read(self._KEY)
        if s is None:
            return OK
        for pvc, pv_name in s.node_decisions.get(node_name, ()):
            if pv_name:
                self.assumed[pv_name] = pvc.key
        return OK

    def unreserve(self, state: CycleState, pod: Pod, node_name: str) -> None:
        s: Optional[VolumeBinding._State] = state.read(self._KEY)
        if s is None:
            return
        for pvc, pv_name in s.node_decisions.get(node_name, ()):
            if pv_name and self.assumed.get(pv_name) == pvc.key:
                del self.assumed[pv_name]

    def pre_bind_pre_flight(self, state: CycleState, pod: Pod,
                            node_name: str) -> Status:
        """PreBindPreFlight (volume_binding.go PreBindPreFlight): Skip when
        the pod carries no PVC-backed volumes — PreBind would be a no-op."""
        if not any(v.pvc_name for v in pod.volumes):
            return Status.skip()
        return OK

    def pre_bind(self, state: CycleState, pod: Pod, node_name: str) -> Status:
        """BindPodVolumes (binder.go): write the PV↔PVC binds (and node
        selection for provisioning) through the API."""
        s: Optional[VolumeBinding._State] = state.read(self._KEY)
        if s is None:
            return OK
        for pvc, pv_name in s.node_decisions.get(node_name, ()):
            try:
                self.handle.clientset.bind_volume(pvc, pv_name, node_name)
            except Exception as e:  # noqa: BLE001
                return Status.error(str(e))
            self.assumed.pop(pv_name, None)
        return OK


class NodeVolumeLimits:
    """nodevolumelimits/csi.go: per-CSI-driver attach limits."""

    name = "NodeVolumeLimits"

    def __init__(self, handle=None):
        self.handle = handle

    def _driver_of(self, pvc: PersistentVolumeClaim) -> str:
        if pvc.volume_name:
            pv = self.handle.pvs.get(pvc.volume_name)
            if pv is not None and pv.csi_driver:
                return pv.csi_driver
        sc = self.handle.storage_classes.get(pvc.storage_class)
        return sc.provisioner if sc is not None else ""

    def filter(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> Status:
        names = _pod_pvc_names(pod)
        if not names:
            return OK
        csinode = self.handle.csi_nodes.get(node_info.name)
        if csinode is None or not csinode.driver_limits:
            return OK
        new_per_driver: Dict[str, int] = {}
        for name in names:
            pvc = self.handle.pvcs.get(f"{pod.namespace}/{name}")
            if pvc is None:
                continue
            d = self._driver_of(pvc)
            if d:
                new_per_driver[d] = new_per_driver.get(d, 0) + 1
        if not new_per_driver:
            return OK
        # Existing attachments: the node's pods' PVC-backed volumes per driver
        # (NodeInfo.pvc_ref_counts holds the per-node claim keys).
        existing: Dict[str, int] = {}
        for key, cnt in node_info.pvc_ref_counts.items():
            pvc = self.handle.pvcs.get(key)
            if pvc is None:
                continue
            d = self._driver_of(pvc)
            if d:
                existing[d] = existing.get(d, 0) + 1
        for d, n_new in new_per_driver.items():
            limit = csinode.driver_limits.get(d)
            if limit is not None and existing.get(d, 0) + n_new > limit:
                return Status.unschedulable(ERR_LIMIT)
        return OK


class VolumeZone:
    """volumezone/: bound PVs' zone/region labels must match the node."""

    name = "VolumeZone"
    TOPOLOGY_KEYS = (LABEL_ZONE, LABEL_REGION,
                     "failure-domain.beta.kubernetes.io/zone",
                     "failure-domain.beta.kubernetes.io/region")

    def __init__(self, handle=None):
        self.handle = handle

    def filter(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> Status:
        node = node_info.node
        for name in _pod_pvc_names(pod):
            pvc = self.handle.pvcs.get(f"{pod.namespace}/{name}")
            if pvc is None or not pvc.volume_name:
                continue
            pv = self.handle.pvs.get(pvc.volume_name)
            if pv is None:
                continue
            for key in self.TOPOLOGY_KEYS:
                pv_val = pv.labels.get(key)
                if pv_val is None:
                    continue
                node_val = node.labels.get(key)
                if node_val is None or node_val != pv_val:
                    return Status.unschedulable(ERR_ZONE)
        return OK


class VolumeRestrictions:
    """volumerestrictions/: ReadWriteOncePod access-mode conflicts."""

    name = "VolumeRestrictions"

    def __init__(self, handle=None):
        self.handle = handle

    _KEY = "PreFilterVolumeRestrictions"

    def pre_filter(self, state: CycleState, pod: Pod, nodes) -> Tuple[Optional[PreFilterResult], Status]:
        names = _pod_pvc_names(pod)
        if not names:
            return None, Status.skip()
        # RWOP: no other pod anywhere may use these claims. The cluster-wide
        # refcount rides cycle state so preemption dry-runs can adjust it via
        # add_pod/remove_pod and discover victims whose eviction clears the
        # conflict (volumerestrictions isRWOPConflict + AddPod/RemovePod).
        rwop_keys = set()
        for name in names:
            pvc = self.handle.pvcs.get(f"{pod.namespace}/{name}")
            if pvc is not None and RWOP in pvc.access_modes:
                rwop_keys.add(f"{pod.namespace}/{name}")
        conflicts = 0
        if rwop_keys:
            snap = self.handle.snapshot() if callable(self.handle.snapshot) else self.handle.snapshot
            for ni in snap.node_info_list:
                for key in rwop_keys:
                    conflicts += ni.pvc_ref_counts.get(key, 0)
        state.write(self._KEY, _RWOPState(rwop_keys, conflicts))
        return None, OK

    def _uses_rwop(self, s: "_RWOPState", pi: PodInfo) -> int:
        n = 0
        for name in _pod_pvc_names(pi.pod):
            if f"{pi.pod.namespace}/{name}" in s.rwop_keys:
                n += 1
        return n

    def add_pod(self, state: CycleState, pod: Pod, added: PodInfo, node_info: NodeInfo) -> Status:
        s = state.read(self._KEY)
        if s is not None and s.rwop_keys:
            s.conflicts += self._uses_rwop(s, added)
        return OK

    def remove_pod(self, state: CycleState, pod: Pod, removed: PodInfo, node_info: NodeInfo) -> Status:
        s = state.read(self._KEY)
        if s is not None and s.rwop_keys:
            s.conflicts -= self._uses_rwop(s, removed)
        return OK

    def filter(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> Status:
        s = state.read(self._KEY)
        if s is not None and s.conflicts > 0:
            return Status.unschedulable(ERR_RWOP)
        return OK


@dataclass
class _RWOPState:
    """RWOP conflict refcount, cloned per what-if simulation."""

    rwop_keys: set
    conflicts: int

    def clone(self) -> "_RWOPState":
        return _RWOPState(self.rwop_keys, self.conflicts)
