"""PodTopologySpread (plugins/podtopologyspread/: plugin.go, filtering.go,
scoring.go, common.go).

Filter semantics (filtering.go:318-362): for each DoNotSchedule constraint,
node must carry the topology key; reject when
    matchNum + selfMatch − minMatchNum > maxSkew
where matchNum counts existing pods in the node's topology domain matching the
constraint selector, and minMatchNum is the global domain minimum tracked by a
two-entry criticalPaths structure (filtering.go:98-137) so that AddPod/
RemovePod preemption updates stay O(1).

Score semantics (scoring.go): per ScheduleAnyway constraint, a node earns
matchCount·log(domains+2) + (maxSkew−1); NormalizeScore inverts via
MaxNodeScore * (maxScore + minScore − s) / maxScore.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from ..api.labels import IN, LabelSelector, Requirement
from ..api.types import (
    DO_NOT_SCHEDULE,
    HONOR,
    LABEL_HOSTNAME,
    SCHEDULE_ANYWAY,
    Pod,
    TopologySpreadConstraint,
    find_matching_untolerated_taint,
)
from ..core.framework import (
    MAX_NODE_SCORE,
    OK,
    CycleState,
    NodeScore,
    PreFilterResult,
    Status,
)
from ..core.node_info import NodeInfo, PodInfo

INVALID_SCORE = -1


@dataclass
class _Constraint:
    max_skew: int
    topology_key: str
    selector: LabelSelector
    min_domains: Optional[int]
    node_affinity_policy: str
    node_taints_policy: str


def _compile_constraints(pod: Pod, when: str) -> List[_Constraint]:
    out = []
    for c in pod.topology_spread_constraints:
        if c.when_unsatisfiable != when:
            continue
        selector = c.label_selector or LabelSelector()
        extra = tuple(
            Requirement(k, IN, (pod.labels[k],))
            for k in c.match_label_keys
            if k in pod.labels
        )
        if extra:
            selector = LabelSelector(selector.match_labels, selector.match_expressions + extra)
        out.append(_Constraint(
            max_skew=c.max_skew,
            topology_key=c.topology_key,
            selector=selector,
            min_domains=c.min_domains,
            node_affinity_policy=c.node_affinity_policy,
            node_taints_policy=c.node_taints_policy,
        ))
    return out


def _count_pods_matching(node_info: NodeInfo, selector: LabelSelector, ns: str) -> int:
    """common.go countPodsMatchSelector: same-namespace, non-terminating pods."""
    n = 0
    for pi in node_info.pods:
        p = pi.pod
        if p.namespace == ns and p.deletion_ts is None and selector.matches(p.labels):
            n += 1
    return n


class _CriticalPaths:
    """filtering.go:98 criticalPaths — two smallest (tpVal, matchNum) entries."""

    __slots__ = ("min1_val", "min1_num", "min2_val", "min2_num")

    def __init__(self):
        self.min1_val: Optional[str] = None
        self.min1_num: int = 1 << 62
        self.min2_val: Optional[str] = None
        self.min2_num: int = 1 << 62

    def clone(self) -> "_CriticalPaths":
        c = _CriticalPaths()
        c.min1_val, c.min1_num = self.min1_val, self.min1_num
        c.min2_val, c.min2_num = self.min2_val, self.min2_num
        return c

    def update(self, tp_val: str, num: int) -> None:
        if tp_val == self.min1_val:
            self.min1_num = num
            if self.min1_num > self.min2_num:
                self.min1_val, self.min2_val = self.min2_val, self.min1_val
                self.min1_num, self.min2_num = self.min2_num, self.min1_num
        elif tp_val == self.min2_val:
            self.min2_num = num
            if self.min1_num > self.min2_num:
                self.min1_val, self.min2_val = self.min2_val, self.min1_val
                self.min1_num, self.min2_num = self.min2_num, self.min1_num
        elif num < self.min1_num:
            self.min2_val, self.min2_num = self.min1_val, self.min1_num
            self.min1_val, self.min1_num = tp_val, num
        elif num < self.min2_num:
            self.min2_val, self.min2_num = tp_val, num


@dataclass
class _PreFilterState:
    constraints: List[_Constraint]
    # per-constraint: topologyValue -> match count
    tp_val_to_match_num: List[Dict[str, int]]
    critical_paths: List[_CriticalPaths]
    tp_domains_num: List[int]

    def clone(self) -> "_PreFilterState":
        """Deep-clone for CycleState.clone() — what-if simulations (nominated
        pods / preemption) must not mutate the real cycle's counts."""
        return _PreFilterState(
            constraints=self.constraints,
            tp_val_to_match_num=[dict(m) for m in self.tp_val_to_match_num],
            critical_paths=[cp.clone() for cp in self.critical_paths],
            tp_domains_num=list(self.tp_domains_num),
        )


class PodTopologySpread:
    name = "PodTopologySpread"
    _FKEY = "PreFilterPodTopologySpread"
    _SKEY = "PreScorePodTopologySpread"

    def __init__(self, handle=None, default_constraints: Sequence[TopologySpreadConstraint] = ()):
        self.handle = handle
        self.default_constraints = tuple(default_constraints)

    # -- QueueingHints (pod_topology_spread.go EventsToRegister /
    # isSchedulableAfterPodChange / isSchedulableAfterNodeChange) -----------

    def events_to_register(self):
        from ..core.queue import (EVENT_ASSIGNED_POD_ADD,
                                  EVENT_ASSIGNED_POD_DELETE, EVENT_NODE_ADD,
                                  EVENT_NODE_UPDATE, EVENT_POD_DELETE)
        return [
            (EVENT_ASSIGNED_POD_ADD, self._hint_pod),
            (EVENT_ASSIGNED_POD_DELETE, self._hint_pod),
            (EVENT_POD_DELETE, self._hint_pod),
            (EVENT_NODE_ADD, self._hint_node),
            (EVENT_NODE_UPDATE, self._hint_node),
        ]

    @staticmethod
    def _hint_constraints(pod: Pod):
        """Per-pod memo of compiled DoNotSchedule constraints (hint fns run
        once per parked pod per cluster event)."""
        cached = pod.__dict__.get("_pts_hint_constraints")
        if cached is None:
            cached = pod._pts_hint_constraints = _compile_constraints(
                pod, DO_NOT_SCHEDULE)
        return cached

    def _hint_pod(self, pod: Pod, old, new) -> bool:
        """A pod change matters only if the other pod matches a constraint
        selector in this pod's namespace (isSchedulableAfterPodChange)."""
        other = new if new is not None else old
        if other is None:
            return True
        if other.namespace != pod.namespace:
            return False
        for c in self._hint_constraints(pod):
            if c.selector.matches(other.labels):
                return True
        return False

    def _hint_node(self, pod: Pod, old, new) -> bool:
        """A node event matters if the node carries every constraint
        topology key — or if an UPDATE changed/removed a topology label
        (a vanishing min-count domain can raise the global min and clear
        the skew rejection) (isSchedulableAfterNodeChange)."""
        if new is None:
            return True
        constraints = self._hint_constraints(pod)
        if old is not None and any(
                old.labels.get(c.topology_key) != new.labels.get(c.topology_key)
                for c in constraints):
            return True
        for c in constraints:
            if c.topology_key not in new.labels:
                return False
        return True

    # -- eligibility -------------------------------------------------------

    @staticmethod
    def _node_eligible(pod: Pod, node_info: NodeInfo, c: _Constraint) -> bool:
        node = node_info.node
        if node is None or c.topology_key not in node.labels:
            return False
        if c.node_affinity_policy == HONOR and not pod.required_node_selector_matches(node):
            return False
        if c.node_taints_policy == HONOR:
            if find_matching_untolerated_taint(node.taints, pod.tolerations) is not None:
                return False
        return True

    # -- PreFilter / Filter ------------------------------------------------

    def pre_filter(self, state: CycleState, pod: Pod, nodes: Sequence[NodeInfo]) -> Tuple[Optional[PreFilterResult], Status]:
        constraints = _compile_constraints(pod, DO_NOT_SCHEDULE)
        if not constraints:
            state.write(self._FKEY, _PreFilterState([], [], [], []))
            return None, Status.skip()
        tp_maps: List[Dict[str, int]] = [dict() for _ in constraints]
        for ni in nodes:
            for i, c in enumerate(constraints):
                if not self._node_eligible(pod, ni, c):
                    continue
                tp_val = ni.node.labels[c.topology_key]
                cnt = _count_pods_matching(ni, c.selector, pod.namespace)
                tp_maps[i][tp_val] = tp_maps[i].get(tp_val, 0) + cnt
        cps = []
        domains = []
        for m in tp_maps:
            cp = _CriticalPaths()
            for v, n in m.items():
                cp.update(v, n)
            cps.append(cp)
            domains.append(len(m))
        state.write(self._FKEY, _PreFilterState(constraints, tp_maps, cps, domains))
        return None, OK

    def add_pod(self, state: CycleState, pod: Pod, added: PodInfo, node_info: NodeInfo) -> Status:
        self._update(state, pod, added.pod, node_info, +1)
        return OK

    def remove_pod(self, state: CycleState, pod: Pod, removed: PodInfo, node_info: NodeInfo) -> Status:
        self._update(state, pod, removed.pod, node_info, -1)
        return OK

    def _update(self, state: CycleState, pod: Pod, other: Pod, node_info: NodeInfo, delta: int) -> None:
        s: _PreFilterState = state.read(self._FKEY)
        if s is None or not s.constraints:
            return
        for i, c in enumerate(s.constraints):
            if not self._node_eligible(pod, node_info, c):
                continue
            if other.namespace != pod.namespace or not c.selector.matches(other.labels):
                continue
            tp_val = node_info.node.labels[c.topology_key]
            n = s.tp_val_to_match_num[i].get(tp_val, 0) + delta
            s.tp_val_to_match_num[i][tp_val] = n
            s.critical_paths[i].update(tp_val, n)

    def filter(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> Status:
        s: _PreFilterState = state.read(self._FKEY)
        if s is None or not s.constraints:
            return OK
        node = node_info.node
        for i, c in enumerate(s.constraints):
            tp_val = node.labels.get(c.topology_key)
            if tp_val is None:
                return Status.unresolvable("node(s) didn't have the requested topology")
            min_match = s.critical_paths[i].min1_num
            if c.min_domains is not None and s.tp_domains_num[i] < c.min_domains:
                min_match = 0
            if min_match >= (1 << 62):
                min_match = 0
            self_match = 1 if c.selector.matches(pod.labels) else 0
            match_num = s.tp_val_to_match_num[i].get(tp_val, 0)
            if match_num + self_match - min_match > c.max_skew:
                return Status.unschedulable("node(s) didn't match pod topology spread constraints")
        return OK

    # -- PreScore / Score --------------------------------------------------

    def pre_score(self, state: CycleState, pod: Pod, nodes: Sequence[NodeInfo]) -> Status:
        constraints = _compile_constraints(pod, SCHEDULE_ANYWAY)
        if not constraints and self.default_constraints and not pod.topology_spread_constraints:
            constraints = [
                _Constraint(
                    max_skew=c.max_skew, topology_key=c.topology_key,
                    selector=c.label_selector or LabelSelector(),
                    min_domains=None, node_affinity_policy=HONOR, node_taints_policy="Ignore",
                )
                for c in self.default_constraints
            ]
        if not constraints:
            return Status.skip()
        all_nodes = nodes
        if self.handle is not None:
            snap = self.handle.snapshot() if callable(self.handle.snapshot) else self.handle.snapshot
            all_nodes = snap.node_info_list
        tp_counts: List[Dict[str, int]] = [dict() for _ in constraints]
        ignored_nodes = set()
        for ni in all_nodes:
            node = ni.node
            if node is None:
                continue
            # scoring.go initPreScoreState: nodes missing any topology key or
            # failing honored node affinity are ignored.
            if not all(c.topology_key in node.labels for c in constraints):
                ignored_nodes.add(node.name)
                continue
            if not pod.required_node_selector_matches(node):
                ignored_nodes.add(node.name)
                continue
            for i, c in enumerate(constraints):
                if c.topology_key == LABEL_HOSTNAME:
                    continue  # counted per node at Score time
                tp_val = node.labels[c.topology_key]
                cnt = _count_pods_matching(ni, c.selector, pod.namespace)
                tp_counts[i][tp_val] = tp_counts[i].get(tp_val, 0) + cnt
        # Domain weights are quantized to 1/1024ths (w_q = round(log(size+2)
        # * 1024)) so scores are exact integers on both the host oracle and the
        # device kernel; the reference keeps float64 (scoring.go scoreForCount).
        weights = []
        for i, c in enumerate(constraints):
            if c.topology_key == LABEL_HOSTNAME:
                size = sum(1 for ni in all_nodes if ni.node is not None and ni.node.name not in ignored_nodes)
            else:
                size = len(tp_counts[i])
            weights.append(int(round(math.log(size + 2) * 1024)))
        state.write(self._SKEY, (constraints, tp_counts, weights, ignored_nodes))
        return OK

    def score(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> Tuple[int, Status]:
        data = state.read(self._SKEY)
        if data is None:
            return 0, OK
        constraints, tp_counts, weights, ignored = data
        node = node_info.node
        if node.name in ignored:
            return 0, OK
        score = 0
        for i, c in enumerate(constraints):
            tp_val = node.labels.get(c.topology_key)
            if tp_val is None:
                continue
            if c.topology_key == LABEL_HOSTNAME:
                cnt = _count_pods_matching(node_info, c.selector, pod.namespace)
            else:
                cnt = tp_counts[i].get(tp_val, 0)
            score += cnt * weights[i] + (c.max_skew - 1) * 1024
        return score, OK

    def normalize_score(self, state: CycleState, pod: Pod, scores: List[NodeScore]) -> None:
        data = state.read(self._SKEY)
        if data is None:
            return
        _, _, _, ignored = data
        min_score = 1 << 62
        max_score = 0
        for s in scores:
            if s.name in ignored:
                s.score = INVALID_SCORE
                continue
            min_score = min(min_score, s.score)
            max_score = max(max_score, s.score)
        for s in scores:
            if s.score == INVALID_SCORE:
                s.score = 0
                continue
            if max_score == 0:
                s.score = MAX_NODE_SCORE
                continue
            s.score = MAX_NODE_SCORE * (max_score + min_score - s.score) // max_score

    def sign(self, pod: Pod):
        return (
            tuple(sorted(pod.labels.items())),
            pod.namespace,
            tuple(
                (c.max_skew, c.topology_key, c.when_unsatisfiable, repr(c.label_selector),
                 c.min_domains, c.node_affinity_policy, c.node_taints_policy, c.match_label_keys)
                for c in pod.topology_spread_constraints
            ),
        )
