"""Placement-based topology-aware gang scheduling plugins (fork additions).

- TopologyPlacementGenerator (framework/plugins/topologyaware/
  topology_placement.go:34-43): PlacementGenerate plugin producing one
  candidate node-subset ("placement") per topology domain of the pod group's
  scheduling constraint key; restricted to the domain of already-scheduled
  group members when any exist.
- PodGroupPodsCount (framework/plugins/podgrouppodscount/
  podgroup_pods_count.go): PlacementScore plugin preferring the placement
  that schedules the most group pods (scheduled + proposed), normalized by
  the max across candidates.
"""

from __future__ import annotations

from typing import List, Tuple

from ..api.types import Pod, PodGroup
from ..core.framework import (
    MAX_NODE_SCORE,
    OK,
    CycleState,
    Placement,
    PodGroupAssignments,
    Status,
)


_SCHEDULED_KEY = "TopologyAwareScheduledGroupPods"


def _scheduled_group_pods(handle, group: PodGroup, state=None) -> List[Pod]:
    """podgroupstate.go ScheduledPods: the persistent per-group index of
    bound members (core/podgroupstate.py), maintained from the watch feed —
    O(group members) per cycle instead of O(all pods). Falls back to the
    clientset scan for handles without the store (bare-framework tests).
    Cycle-invariant, memoized on the shared CycleState."""
    if state is not None:
        cached = state.read(_SCHEDULED_KEY)
        if cached is not None:
            return cached
    store = getattr(handle, "pod_group_state", None)
    if store is not None:
        out = store.scheduled_pods(group.namespace, group.name)
    else:
        out = [p for p in handle.clientset.pods.values()
               if (p.pod_group == group.name and p.namespace == group.namespace
                   and p.node_name)]
    if state is not None:
        state.write(_SCHEDULED_KEY, out)
    return out


class TopologyPlacementGenerator:
    name = "TopologyPlacementGenerator"

    def __init__(self, handle=None):
        self.handle = handle

    def generate_placements(
        self, state: CycleState, group: PodGroup, members, parent: Placement
    ) -> Tuple[List[Placement], Status]:
        keys = getattr(group, "topology_keys", ())
        if not keys:
            # No topology constraints: the parent placement stands
            # (topology_placement.go:61-64).
            return [parent], OK
        key = keys[0]  # single constraint supported, like the reference

        snap = self.handle.snapshot()
        required_domain = None
        scheduled = _scheduled_group_pods(self.handle, group, state)
        if scheduled:
            for p in scheduled:
                ni = snap.get(p.node_name)
                node = ni.node if ni is not None else None
                domain = node.labels.get(key) if node else None
                if domain is None:
                    return [], Status.error(
                        f"no topology domain for scheduled pod {p.name}")
                if required_domain is not None and required_domain != domain:
                    return [], Status.error(
                        "scheduled group pods span multiple domains")
                required_domain = domain

        by_domain = {}
        for name in parent.node_names:
            ni = snap.get(name)
            node = ni.node if ni is not None else None
            if node is None:
                continue
            domain = node.labels.get(key)
            if domain is None:
                continue
            if required_domain is not None and domain != required_domain:
                continue
            by_domain.setdefault(domain, []).append(name)
        # Deterministic candidate order (the reference iterates a Go map;
        # we sort so assignment equivalence is reproducible).
        return [Placement(domain, names)
                for domain, names in sorted(by_domain.items())], OK


class PodGroupPodsCount:
    name = "PodGroupPodsCount"

    def __init__(self, handle=None):
        self.handle = handle

    def score_placement(
        self, state: CycleState, group: PodGroup, pga: PodGroupAssignments
    ) -> Tuple[int, Status]:
        scheduled = len(_scheduled_group_pods(self.handle, group, state))
        return scheduled + len(pga.proposed), OK

    def normalize_placement_score(self, group: PodGroup, scores: List[int]) -> List[int]:
        """podgroup_pods_count.go:73 NormalizePlacementScore: scale by the max
        count (MinCount intentionally ignored to keep score gaps small)."""
        mx = max(scores, default=0)
        if mx == 0:
            return scores
        return [s * MAX_NODE_SCORE // mx for s in scores]
