"""Shared plugin helpers: compiled affinity terms and namespace resolution.

Mirrors pkg/scheduler/framework/types.go AffinityTerm (the precompiled form of
v1.PodAffinityTerm) and util helpers in pkg/scheduler/util.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Mapping, Optional, Sequence

from ..api.labels import IN, LabelSelector, Requirement
from ..api.types import Pod, PodAffinityTerm


@dataclass(frozen=True)
class AffinityTerm:
    """Precompiled affinity term (framework/types.go AffinityTerm):
    namespaces resolved to a set, selector merged with matchLabelKeys."""

    namespaces: frozenset
    selector: Optional[LabelSelector]
    topology_key: str
    namespace_selector: Optional[LabelSelector]

    def matches(self, pod: Pod, ns_labels_fn) -> bool:
        """Does `pod` match this term? ns_labels_fn(ns) -> labels dict or None."""
        in_ns = pod.namespace in self.namespaces
        if not in_ns and self.namespace_selector is not None:
            labels = ns_labels_fn(pod.namespace) if ns_labels_fn else None
            in_ns = labels is not None and self.namespace_selector.matches(labels)
        if not in_ns:
            return False
        if self.selector is None:
            return False
        return self.selector.matches(pod.labels)


def compile_term(term: PodAffinityTerm, owner: Pod) -> AffinityTerm:
    """GetAffinityTerms/newAffinityTerm: default namespaces to the owner pod's
    namespace when neither namespaces nor namespaceSelector is given; merge
    matchLabelKeys/mismatchLabelKeys from the owner's labels into the selector
    (MatchLabelKeysInPodAffinity, reference plugin.go mergeAffinityTermsLabelKeys)."""
    namespaces = frozenset(term.namespaces) if term.namespaces else (
        frozenset() if term.namespace_selector is not None else frozenset((owner.namespace,))
    )
    selector = term.label_selector
    extra_reqs = []
    for key in term.match_label_keys:
        if key in owner.labels:
            extra_reqs.append(Requirement(key, IN, (owner.labels[key],)))
    for key in term.mismatch_label_keys:
        if key in owner.labels:
            extra_reqs.append(Requirement(key, "NotIn", (owner.labels[key],)))
    if extra_reqs and selector is not None:
        selector = LabelSelector(
            match_labels=selector.match_labels,
            match_expressions=selector.match_expressions + tuple(extra_reqs),
        )
    return AffinityTerm(
        namespaces=namespaces,
        selector=selector,
        topology_key=term.topology_key,
        namespace_selector=term.namespace_selector,
    )


def compile_terms(terms: Sequence[PodAffinityTerm], owner: Pod):
    return tuple(compile_term(t, owner) for t in terms)
