"""TPU sidecar: the device scheduling backend behind a Unix-domain-socket
RPC boundary.

The reference's natural out-of-process integration shape is the HTTP
scheduler extender (pkg/scheduler/extender.go:44, verbs filter/prioritize/
bind/preempt :46-49); SURVEY §2.4 rows 9-10 call for the TPU build's
equivalent: a colocated sidecar process that OWNS the accelerator and is fed
cluster state + pod batches over gRPC/UDS, so the control-plane scheduler
process never links JAX/XLA. This module is the working UDS prototype of
that contract (docs/SIDECAR.md is the contract document):

- framing: 4-byte big-endian length prefix + JSON body, both directions;
- objects ride the SAME wire codec as the REST apiserver
  (core/apiserver.py pod_to_wire/node_to_wire — one serialization story
  for both process boundaries);
- verbs (mirroring the extender verb set, batched):
    {"verb": "sync",     "nodes": [...]}                  → {"ok": true}
    {"verb": "schedule", "pods": [...]}                   → {"assignments":
        [nodeName | null, ...], "deviceScheduled": n}
    {"verb": "ping"}                                      → {"ok": true}
    {"verb": "shutdown"}                                  → {"ok": true}
  errors: {"error": "..."} with the connection kept open.

The sidecar applies `sync` node diffs to its owned cluster mirror and runs
`schedule` batches through the full TPUScheduler device path; the caller
binds the returned assignments itself (the bind cycle — like the
reference's bind verb — stays host-side unless delegated).
"""

from __future__ import annotations

import json
import os
import socket
import struct
import threading
from typing import List, Optional

_LEN = struct.Struct(">I")


def _send(sock: socket.socket, obj: dict) -> None:
    data = json.dumps(obj).encode()
    sock.sendall(_LEN.pack(len(data)) + data)


def _recv(sock: socket.socket) -> Optional[dict]:
    hdr = b""
    while len(hdr) < _LEN.size:
        chunk = sock.recv(_LEN.size - len(hdr))
        if not chunk:
            return None
        hdr += chunk
    (n,) = _LEN.unpack(hdr)
    body = b""
    while len(body) < n:
        chunk = sock.recv(min(1 << 20, n - len(body)))
        if not chunk:
            return None
        body += chunk
    return json.loads(body)


class SidecarServer:
    """Owns a TPUScheduler; serves the UDS contract. One request at a time
    per connection; multiple sequential connections supported (the host
    scheduler reconnects after a sidecar restart, like any RPC client)."""

    def __init__(self, socket_path: str):
        self.socket_path = socket_path
        from ..core import FakeClientset
        from ..models import TPUScheduler
        self._cs = FakeClientset()
        self._sched = TPUScheduler(clientset=self._cs)
        self._listener: Optional[socket.socket] = None
        self._stop = threading.Event()

    # -- verbs -------------------------------------------------------------

    def _sync(self, req: dict) -> dict:
        """Full node-set replacement (the prototype's re-list; a production
        sidecar would take generation-keyed diffs exactly like the mirror's
        dirty rows)."""
        from ..core.apiserver import node_from_wire
        wanted = {}
        for w in req.get("nodes", ()):
            node = node_from_wire(w)
            wanted[node.name] = node
        for name in list(self._cs.nodes):
            if name not in wanted:
                self._cs.delete_node(name)
        for name, node in wanted.items():
            if name in self._cs.nodes:
                self._cs.update_node(node)
            else:
                self._cs.create_node(node)
        return {"ok": True}

    def _schedule(self, req: dict) -> dict:
        from ..core.apiserver import pod_from_wire
        pods = [pod_from_wire(w) for w in req.get("pods", ())]
        for p in pods:
            self._cs.create_pod(p)
        self._sched.run_until_idle()
        assignments: List[Optional[str]] = []
        for p in pods:
            assignments.append(self._cs.bindings.get(p.uid) or None)
            # The caller owns the cluster truth; the sidecar's copy of the
            # pod served its purpose once scheduled (bound pods stay in the
            # mirror as load; unschedulable ones leave so the next batch
            # doesn't re-attempt them).
            if p.uid not in self._cs.bindings:
                self._cs.delete_pod(p)
        return {"assignments": assignments,
                "deviceScheduled": self._sched.device_scheduled}

    # -- serving -----------------------------------------------------------

    def serve_forever(self) -> None:
        if os.path.exists(self.socket_path):
            os.unlink(self.socket_path)
        self._listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._listener.bind(self.socket_path)
        self._listener.listen(4)
        print(f"kubernetes-tpu-sidecar: serving on {self.socket_path}",
              flush=True)
        while not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:
                break
            with conn:
                while not self._stop.is_set():
                    req = _recv(conn)
                    if req is None:
                        break
                    try:
                        verb = req.get("verb")
                        if verb == "ping":
                            _send(conn, {"ok": True})
                        elif verb == "sync":
                            _send(conn, self._sync(req))
                        elif verb == "schedule":
                            _send(conn, self._schedule(req))
                        elif verb == "shutdown":
                            _send(conn, {"ok": True})
                            self._stop.set()
                        else:
                            _send(conn, {"error": f"unknown verb {verb!r}"})
                    except Exception as e:  # noqa: BLE001 - wire error reply
                        _send(conn, {"error": repr(e)})
        self._listener.close()

    def shutdown(self) -> None:
        self._stop.set()
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass


class SidecarClient:
    """The host scheduler's side of the contract."""

    def __init__(self, socket_path: str, timeout: float = 60.0):
        self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._sock.settimeout(timeout)
        self._sock.connect(socket_path)

    def _call(self, req: dict) -> dict:
        _send(self._sock, req)
        resp = _recv(self._sock)
        if resp is None:
            raise ConnectionError("sidecar closed the connection")
        if "error" in resp:
            raise RuntimeError(f"sidecar: {resp['error']}")
        return resp

    def ping(self) -> bool:
        return bool(self._call({"verb": "ping"}).get("ok"))

    def sync_nodes(self, nodes) -> None:
        from ..core.apiserver import node_to_wire
        self._call({"verb": "sync",
                    "nodes": [node_to_wire(n) for n in nodes]})

    def schedule(self, pods) -> List[Optional[str]]:
        from ..core.apiserver import pod_to_wire
        resp = self._call({"verb": "schedule",
                           "pods": [pod_to_wire(p) for p in pods]})
        return resp["assignments"]

    def shutdown_server(self) -> None:
        try:
            self._call({"verb": "shutdown"})
        except (ConnectionError, OSError):
            pass

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


def main(argv=None) -> int:
    """`python -m kubernetes_tpu.parallel.sidecar --socket /tmp/tpu.sock
    [--platform cpu]` — the sidecar as its own OS process."""
    import argparse

    ap = argparse.ArgumentParser(prog="kubernetes-tpu-sidecar")
    ap.add_argument("--socket", required=True)
    ap.add_argument("--platform", default="auto", choices=("auto", "cpu"))
    args = ap.parse_args(argv)
    if args.platform == "cpu":
        import jax
        jax.config.update("jax_platforms", "cpu")
    SidecarServer(args.socket).serve_forever()
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
