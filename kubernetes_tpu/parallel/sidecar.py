"""TPU sidecar: the device scheduling backend behind a Unix-domain-socket
RPC boundary.

The reference's natural out-of-process integration shape is the HTTP
scheduler extender (pkg/scheduler/extender.go:44, verbs filter/prioritize/
bind/preempt :46-49); SURVEY §2.4 rows 9-10 call for the TPU build's
equivalent: a colocated sidecar process that OWNS the accelerator and is fed
cluster state + pod batches over gRPC/UDS, so the control-plane scheduler
process never links JAX/XLA. This module is the working UDS prototype of
that contract (docs/SIDECAR.md is the contract document):

- framing: 4-byte big-endian length prefix + JSON body, both directions;
- objects ride the SAME wire codec as the REST apiserver
  (core/apiserver.py pod_to_wire/node_to_wire — one serialization story
  for both process boundaries);
- verbs (mirroring the extender verb set, batched):
    {"verb": "sync",     "nodes": [...]}                  → {"ok": true}
    {"verb": "schedule", "pods": [...]}                   → {"assignments":
        [nodeName | null, ...], "deviceScheduled": n}
    {"verb": "ping"}                                      → {"ok": true}
    {"verb": "shutdown"}                                  → {"ok": true}
  errors: {"error": "..."} with the connection kept open.

The sidecar applies `sync` node diffs to its owned cluster mirror and runs
`schedule` batches through the full TPUScheduler device path; the caller
binds the returned assignments itself (the bind cycle — like the
reference's bind verb — stays host-side unless delegated).
"""

from __future__ import annotations

import json
import os
import socket
import struct
import threading
import time
from typing import List, Optional

_LEN = struct.Struct(">I")


def _send(sock: socket.socket, obj: dict) -> None:
    data = json.dumps(obj).encode()
    sock.sendall(_LEN.pack(len(data)) + data)


def _recv(sock: socket.socket) -> Optional[dict]:
    hdr = b""
    while len(hdr) < _LEN.size:
        chunk = sock.recv(_LEN.size - len(hdr))
        if not chunk:
            return None
        hdr += chunk
    (n,) = _LEN.unpack(hdr)
    body = b""
    while len(body) < n:
        chunk = sock.recv(min(1 << 20, n - len(body)))
        if not chunk:
            return None
        body += chunk
    return json.loads(body)


class SidecarServer:
    """Owns a TPUScheduler; serves the UDS contract. One request at a time
    per connection; multiple sequential connections supported (the host
    scheduler reconnects after a sidecar restart, like any RPC client)."""

    def __init__(self, socket_path: str, max_batch: Optional[int] = None,
                 mesh="auto"):
        self.socket_path = socket_path
        from ..core import FakeClientset
        from ..models import TPUScheduler
        self._cs = FakeClientset()
        self._sched = TPUScheduler(clientset=self._cs, max_batch=max_batch,
                                   mesh=mesh)
        self._listener: Optional[socket.socket] = None
        self._stop = threading.Event()
        self._conns: set = set()  # live client connections (kill())
        self.served_connections = 0  # accepted connections (tests)

    # -- verbs -------------------------------------------------------------

    def _sync(self, req: dict) -> dict:
        """Full node-set replacement (the prototype's re-list; a production
        sidecar would take generation-keyed diffs exactly like the mirror's
        dirty rows). An optional "pods" list carries BOUND pods: after a
        sidecar restart the fresh mirror has no memory of earlier
        placements, so the client's reconnect resync replays them as load
        (the reconstructible-from-host-snapshot contract, docs/SIDECAR.md
        + docs/RESILIENCE.md)."""
        from ..core.apiserver import node_from_wire, pod_from_wire
        wanted = {}
        for w in req.get("nodes", ()):
            node = node_from_wire(w)
            wanted[node.name] = node
        for name in list(self._cs.nodes):
            if name not in wanted:
                self._cs.delete_node(name)
        for name, node in wanted.items():
            if name in self._cs.nodes:
                self._cs.update_node(node)
            else:
                self._cs.create_node(node)
        for w in req.get("pods", ()):
            if w.get("uid") in self._cs.pods:
                continue  # live server, replayed sync: already tracked
            pod = pod_from_wire(w)
            if pod.node_name:  # bound pods only: they are node LOAD
                self._cs.create_pod(pod)
                self._cs.bindings[pod.uid] = pod.node_name
        if "nextStartNodeIndex" in req and not self._cs.bindings:
            # Round-robin rotation point: part of the reconstructible
            # scheduling state — without it a restarted sidecar restarts
            # its rotation at 0 and diverges from a fault-free run. Applied
            # only while this instance has scheduled NOTHING: on a live
            # server a reconnect resync carries the client's STALE value
            # (from the last reply it actually read), and rolling a live
            # rotation back would diverge exactly the way starting at 0
            # would. A live server's own counter is always the truth.
            self._sched.next_start_node_index = int(req["nextStartNodeIndex"])
        return {"ok": True}

    def _schedule(self, req: dict) -> dict:
        from ..core.apiserver import pod_from_wire
        pods = [pod_from_wire(w) for w in req.get("pods", ())]
        for p in pods:
            # Replay-idempotent (a reconnect replays the request whose reply
            # was lost): a pod this mirror already bound keeps its binding
            # instead of being re-created as pending and double-counted.
            if p.uid not in self._cs.bindings:
                self._cs.create_pod(p)
        self._sched.run_until_idle()
        assignments: List[Optional[str]] = []
        for p in pods:
            assignments.append(self._cs.bindings.get(p.uid) or None)
            # The caller owns the cluster truth; the sidecar's copy of the
            # pod served its purpose once scheduled (bound pods stay in the
            # mirror as load; unschedulable ones leave so the next batch
            # doesn't re-attempt them).
            if p.uid not in self._cs.bindings:
                self._cs.delete_pod(p)
        return {"assignments": assignments,
                "deviceScheduled": self._sched.device_scheduled,
                "nextStartNodeIndex": self._sched.next_start_node_index}

    # -- serving -----------------------------------------------------------

    def serve_forever(self) -> None:
        if os.path.exists(self.socket_path):
            os.unlink(self.socket_path)
        self._listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._listener.bind(self.socket_path)
        self._listener.listen(4)
        print(f"kubernetes-tpu-sidecar: serving on {self.socket_path}",
              flush=True)
        while not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:
                break
            self._conns.add(conn)
            self.served_connections += 1
            try:
                with conn:
                    self._serve_connection(conn)
            except OSError:
                # Client died mid-exchange (reset, broken pipe): this
                # connection is gone; the server survives and accepts the
                # client's reconnect — a sidecar must never crash because
                # its caller did.
                pass
            finally:
                self._conns.discard(conn)
        self._listener.close()

    def _serve_connection(self, conn: socket.socket) -> None:
        while not self._stop.is_set():
            req = _recv(conn)
            if req is None:
                break
            try:
                verb = req.get("verb")
                if verb == "ping":
                    _send(conn, {"ok": True})
                elif verb == "sync":
                    _send(conn, self._sync(req))
                elif verb == "schedule":
                    _send(conn, self._schedule(req))
                elif verb == "shutdown":
                    _send(conn, {"ok": True})
                    self._stop.set()
                else:
                    _send(conn, {"error": f"unknown verb {verb!r}"})
            except OSError:
                raise  # transport dead: drop the connection, not the server
            except Exception as e:  # noqa: BLE001 - wire error reply
                _send(conn, {"error": repr(e)})

    def shutdown(self) -> None:
        self._stop.set()
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass

    def kill(self) -> None:
        """Abrupt death (chaos: SIGKILL analogue): tear down the listener
        AND every live connection mid-exchange, no goodbye. Clients see a
        reset; a replacement server may then bind the same socket path."""
        self._stop.set()
        for s in list(self._conns) + ([self._listener] if self._listener else []):
            try:
                s.close()
            except OSError:
                pass


class SidecarClient:
    """The host scheduler's side of the contract.

    Crash-proof: a dead connection (sidecar killed/restarted, reset
    mid-reply) reconnects with backoff and REPLAYS the failed request. The
    sidecar's mirror is reconstructible-from-host-snapshot (docs/SIDECAR.md
    state ownership), so the client re-sends its last `sync` payload on
    every reconnect before the replay — a freshly restarted sidecar sees
    the node set first, exactly like the first connection did. A `schedule`
    whose reply was lost replays whole; the batch re-schedules against the
    re-synced mirror (level-triggered, like a re-attempted in-process
    cycle)."""

    def __init__(self, socket_path: str, timeout: float = 60.0, retry=None):
        from ..core.backoff import RetryConfig
        self._path = socket_path
        self._timeout = timeout
        self._retry_cfg = retry or RetryConfig(
            initial_backoff=0.05, max_backoff=2.0, max_attempts=8)
        self._last_sync: Optional[dict] = None
        # Every placement this client has bound since its last sync, by uid
        # (pod wire + nodeName): the reconnect resync replays these so a
        # RESTARTED sidecar rebuilds its load picture, not just its nodes.
        self._bound_pods: dict = {}
        self._next_start: Optional[int] = None  # rotation point (resync)
        self.reconnects = 0
        self._sock = self._connect()

    def _connect(self) -> socket.socket:
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        sock.settimeout(self._timeout)
        sock.connect(self._path)
        return sock

    def _roundtrip(self, sock: socket.socket, req: dict) -> dict:
        _send(sock, req)
        resp = _recv(sock)
        if resp is None:
            raise ConnectionError("sidecar closed the connection")
        return resp

    def _call(self, req: dict) -> dict:
        try:
            resp = self._roundtrip(self._sock, req)
        except (ConnectionError, OSError):
            resp = self._reconnect_and_replay(req)
        if "error" in resp:
            raise RuntimeError(f"sidecar: {resp['error']}")
        return resp

    def _reconnect_and_replay(self, req: dict) -> dict:
        try:
            self._sock.close()
        except OSError:
            pass
        last_exc: Optional[BaseException] = None
        if req.get("verb") == "sync":
            # The dying request IS a sync: enrich the replay itself with the
            # bound-pod load + rotation point, so a server restarted
            # mid-sync still rebuilds the full mirror state (a bare node
            # list would leave it loadless at rotation 0).
            req = dict(req)
            if self._bound_pods:
                req.setdefault("pods", list(self._bound_pods.values()))
            if self._next_start is not None:
                req.setdefault("nextStartNodeIndex", self._next_start)
        for delay in self._retry_cfg.delays():
            time.sleep(delay)
            try:
                sock = self._connect()
                # Re-establish the mirror before replaying (idempotent if
                # the server never died; required if it restarted empty):
                # the node set from the last sync plus every placement this
                # client has bound since.
                if self._last_sync is not None and req.get("verb") != "sync":
                    resync = dict(self._last_sync)
                    if self._bound_pods:
                        resync["pods"] = list(self._bound_pods.values())
                    if self._next_start is not None:
                        resync["nextStartNodeIndex"] = self._next_start
                    self._roundtrip(sock, resync)
                resp = self._roundtrip(sock, req)
            except (ConnectionError, OSError) as e:
                last_exc = e
                continue
            self._sock = sock
            self.reconnects += 1
            return resp
        raise ConnectionError(
            f"sidecar unreachable at {self._path} after "
            f"{self._retry_cfg.max_attempts - 1} reconnect attempts"
        ) from last_exc

    def ping(self) -> bool:
        return bool(self._call({"verb": "ping"}).get("ok"))

    def sync_nodes(self, nodes) -> None:
        from ..core.apiserver import node_to_wire
        req = {"verb": "sync", "nodes": [node_to_wire(n) for n in nodes]}
        self._last_sync = req
        # _bound_pods is NOT cleared: a later restart-resync must replay
        # every placement this client ever bound, not just the ones since
        # the last node sync (the server keeps them; a fresh server needs
        # them all).
        self._call(req)

    def schedule(self, pods) -> List[Optional[str]]:
        from ..core.apiserver import pod_to_wire
        wires = [pod_to_wire(p) for p in pods]
        resp = self._call({"verb": "schedule", "pods": wires})
        assignments = resp["assignments"]
        for w, node in zip(wires, assignments):
            if node:
                bound = dict(w)
                bound["nodeName"] = node
                self._bound_pods[w["uid"]] = bound
        if resp.get("nextStartNodeIndex") is not None:
            self._next_start = int(resp["nextStartNodeIndex"])
        return assignments

    def shutdown_server(self) -> None:
        # Graceful-stop best effort: no reconnect dance for a server we are
        # telling to exit.
        try:
            self._roundtrip(self._sock, {"verb": "shutdown"})
        except (ConnectionError, OSError):
            pass

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


def main(argv=None) -> int:
    """`python -m kubernetes_tpu.parallel.sidecar --socket /tmp/tpu.sock
    [--platform cpu]` — the sidecar as its own OS process."""
    import argparse

    ap = argparse.ArgumentParser(prog="kubernetes-tpu-sidecar")
    ap.add_argument("--socket", required=True)
    ap.add_argument("--platform", default="auto", choices=("auto", "cpu"))
    args = ap.parse_args(argv)
    if args.platform == "cpu":
        import jax
        jax.config.update("jax_platforms", "cpu")
    SidecarServer(args.socket).serve_forever()
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
