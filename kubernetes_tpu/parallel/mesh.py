"""Mesh construction + sharded dispatch of the batch scheduling kernel.

Sharding layout (scaling-book recipe: pick a mesh, annotate shardings, let
XLA insert collectives):

- `DeviceNodeState` row-major arrays shard their node dimension over the
  `"nodes"` mesh axis (`topo` is [K, NP] → shard dim 1).
- Per-node feature arrays (`exist_anti`, `ipa_base`) shard the same way;
  count tables ([C, VMAX]) and pod-level features replicate.
- An optional leading `"cells"` axis runs independent scheduling cells
  (separate clusters / Borg cells) data-parallel: every leaf gains a leading
  cell dimension and the kernel is vmapped over it.

The kernel's cross-node reductions (rotation cumsum, masked max/min, argmax
select) become XLA collectives over ICI; the scan carry's scatter updates
land on whichever shard owns the chosen row.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.device_state import DeviceNodeState
from ..ops.features import BatchFeatures
from ..ops.kernel import (LAP_MAX, MAX_NODE_SCORE, ScanCarry, _resource_eval,
                          _static_masks, schedule_batch)


def make_mesh(
    n_cells: int = 1,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Mesh over all (or given) devices: ("cells", "nodes"). With n_cells=1
    every chip shards the node axis of one cluster."""
    devs = list(devices if devices is not None else jax.devices())
    n = len(devs)
    if n % max(n_cells, 1) != 0:
        raise ValueError(f"{n} devices not divisible into {n_cells} cells")
    arr = np.array(devs).reshape(n_cells, n // n_cells)
    return Mesh(arr, axis_names=("cells", "nodes"))


def make_multihost_mesh(
    n_hosts: int,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Multi-HOST mesh ("dcn", "ici"): the outer axis spans hosts (data-
    center network), the inner axis a host's chips (ICI). The cluster-state
    node axis shards over BOTH axes jointly (P(("dcn", "ici"))), so one
    cluster's node tensors span every chip of every host; GSPMD then
    decomposes cross-node reductions into an intra-host ICI stage and a
    cross-host DCN stage — the scaling-book recipe for axes that cross the
    slice boundary (SURVEY §2.4 row 9's multi-host story). On real
    multi-host TPU the outer axis must follow the process/host grid
    (jax.devices() orders by process); virtual CPU devices validate the
    sharding + collective decomposition without N real hosts."""
    devs = list(devices if devices is not None else jax.devices())
    n = len(devs)
    if n_hosts <= 0 or n % n_hosts != 0:
        raise ValueError(f"{n} devices not divisible into {n_hosts} hosts")
    arr = np.array(devs).reshape(n_hosts, n // n_hosts)
    return Mesh(arr, axis_names=("dcn", "ici"))


def _node_axis_of(mesh: Mesh):
    """The spec entry for the cluster-state node dimension on this mesh:
    "nodes" on a ("cells", "nodes") mesh, the composite ("dcn", "ici") on a
    multi-host mesh."""
    return ("dcn", "ici") if "dcn" in mesh.axis_names else "nodes"


def _state_specs(axis) -> DeviceNodeState:
    return DeviceNodeState(
        alloc_r=P(axis, None), alloc_pods=P(axis), req_r=P(axis, None),
        nonzero=P(axis, None), pod_count=P(axis),
        taint_key=P(axis, None), taint_val=P(axis, None), taint_eff=P(axis, None),
        unsched=P(axis), valid=P(axis), name_id=P(axis),
        topo=P(None, axis),
    )


def _feature_specs(axis="nodes") -> BatchFeatures:
    """Per-node feature arrays shard over the node axis; the rest replicate."""
    specs = {name: P() for name in BatchFeatures._fields}
    for per_node in ("exist_anti", "ipa_base", "sel_match", "extra_ok",
                     "il_score", "na_raw", "aux_room", "nom_pods"):
        specs[per_node] = P(axis)
    specs["nom_req"] = P(axis, None)
    return BatchFeatures(**specs)


# Backwards-compatible single-host specs.
_STATE_SPECS = _state_specs("nodes")


_MESH_STATE_SHARDINGS_CACHE: dict = {}


def mesh_state_shardings(mesh: Mesh) -> DeviceNodeState:
    """The NamedShardings shard_node_state commits the state to, as one
    cached pytree — handed to the delta row patch (ops/device_state.py
    patch_rows / ops/kernel.py patch_carry_rows_pinned) as explicit
    `out_shardings`, so a patched state stays committed to the session
    kernel's input shardings and the next dispatch does not retrace.
    Cached per mesh: the pytree doubles as the jit-cache key over there."""
    got = _MESH_STATE_SHARDINGS_CACHE.get(mesh)
    if got is None:
        got = jax.tree_util.tree_map(
            lambda s: NamedSharding(mesh, s),
            _state_specs(_node_axis_of(mesh)),
            is_leaf=lambda x: isinstance(x, P))
        _MESH_STATE_SHARDINGS_CACHE[mesh] = got
    return got


def shard_node_state(state: DeviceNodeState, mesh: Mesh) -> DeviceNodeState:
    """Place a cell's node state onto the mesh's node axis (ICI on a
    single-host mesh; ICI within hosts + DCN across hosts on a multi-host
    mesh)."""
    return jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        state, _state_specs(_node_axis_of(mesh)))


def shard_features(feats: BatchFeatures, mesh: Mesh) -> BatchFeatures:
    """Place batch features: per-node vectors shard over the node axis,
    count tables and pod-level scalars replicate. With the inputs committed
    to these shardings, the ordinary jitted kernel compiles SPMD over the
    mesh (GSPMD propagation; cross-node reductions become ICI — and on a
    multi-host mesh, ICI+DCN — collectives): the production TPUScheduler
    path needs no separate sharded kernel."""
    return jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        feats, _feature_specs(_node_axis_of(mesh)))


def collective_report(compiled_text: str, n_hosts: int, per_host: int) -> dict:
    """Classify every collective in compiled HLO by the mesh axis it rides:
    a replica group whose members all live on ONE host is an ICI collective;
    a group spanning hosts rides the DCN. Device id -> host is id//per_host
    (the ("dcn", "ici") mesh lays devices out host-major). Returns
    {"ici": {op: n}, "dcn": {op: n}, "total": {op: n}} — the per-axis
    breakdown the multi-host dryrun prints so the DCN traffic of a sharding
    choice is visible, not guessed."""
    import re

    out = {"ici": {}, "dcn": {}, "total": {}}

    def classify(groups):
        spans_hosts = any(
            len({d // per_host for d in g}) > 1 for g in groups if g)
        return "dcn" if spans_hosts else "ici"

    for m in re.finditer(
            r"(all-reduce|all-gather|reduce-scatter|all-to-all|"
            r"collective-permute)(-start)?[^\n]*", compiled_text):
        line = m.group(0)
        op = m.group(1)
        groups = []
        # Match the FULL braced list: a non-greedy `\{(.*?)\}` would stop at
        # the first '}' of nested groups like {{0,1},{2,3}} and classify
        # only the first replica group — a collective whose later groups
        # span hosts would be misreported as ICI (ADVICE r5).
        rg = re.search(
            r"replica_groups=\{(\{[^}]*\}(?:,\{[^}]*\})*|[^{}]*)\}", line)
        if rg is not None:
            inner = rg.group(1)
            if "{" in inner:
                groups = [[int(x) for x in g.split(",") if x.strip()]
                          for g in re.findall(r"\{([\d,]*)\}", inner)]
            elif inner.strip():
                # flat form: replica_groups={0,1,2,3} — one group
                groups = [[int(x) for x in inner.split(",") if x.strip()]]
        stp = re.search(r"source_target_pairs=\{(.*)?\}", line)
        if stp is not None:
            groups = [[int(x) for x in pair.split(",")]
                      for pair in re.findall(r"\{(\d+,\d+)\}", stp.group(0))]
        axis = classify(groups) if groups else "ici"
        out[axis][op] = out[axis].get(op, 0) + 1
        out["total"][op] = out["total"].get(op, 0) + 1
    return out


def mesh_shard_count(mesh: Mesh) -> int:
    """Shards along the cluster-state node axis (the state's row dimension
    must divide by this for the explicit shard_map kernel)."""
    axis = _node_axis_of(mesh)
    names = axis if isinstance(axis, tuple) else (axis,)
    n = 1
    for a in names:
        n *= mesh.shape[a]
    return n


def mesh_host_split(mesh: Mesh):
    """(n_hosts, per_host) for collective_report: a ("dcn", "ici") mesh
    spans hosts on its outer axis; a ("cells", "nodes") mesh is one host —
    every collective (cells-spanning groups included) rides ICI, so
    per_host must cover ALL the mesh's devices, not just the node axis."""
    if "dcn" in mesh.axis_names:
        return mesh.shape["dcn"], mesh.shape["ici"]
    total = 1
    for n in mesh.axis_names:
        total *= mesh.shape[n]
    return 1, total


def _carry_specs(axis) -> ScanCarry:
    """shard_map specs for a row-local session carry: per-node lanes shard
    the node axis, the (empty, [0, V]) count tables and the rotation scalar
    replicate."""
    return ScanCarry(
        req_r=P(axis, None), nonzero=P(axis, None), pod_count=P(axis),
        fit_ok=P(axis), fit_sc=P(axis), ba=P(axis),
        dns_counts=P(), sa_counts=P(), anti_counts=P(), aff_counts=P(),
        ipa_delta=P(), start=P(), blocked=P(axis), aux_cnt=P(axis))


def _lap_body(state: DeviceNodeState, f: BatchFeatures, n_active, ext0,
              *, batch_pad: int, fit_strategy: int, axis_sizes,
              n_shards: int):
    """Per-shard body of the explicit shard_map lap kernel: the row-local
    (scores_carried ∧ incremental_feas) greedy assignment of
    ops/kernel.py:_lap_schedule, restated so every cross-shard exchange is
    a VISIBLE collective — exactly two small ones per lap:

    1. one ``all_gather`` of an i32 pair per shard — the shard's feasible
       count (global prefix-sum offsets + total_feas) and its contribution
       to F[start-1] (the rotation-rank origin);
    2. one packed ``pmax`` over [2·LAP_MAX] lanes — the per-window
       max-score-then-min-rotation selection keys and (negated) the
       per-window evaluated boundaries.

    Everything else — fit/BA re-eval, window segmentation, the landed-row
    aggregate updates — touches only shard-local rows. Integer arithmetic
    is exactly associative, so results are bit-identical to the
    single-device lap (and therefore to the scan and the host oracle).
    GSPMD compiles the same math from sharding propagation but inserts
    ~2× the collectives per step because it cannot prove the carried
    per-node lanes stay shard-local (MULTICHIP_r05 baseline)."""
    NPl = state.valid.shape[0]
    NP = NPl * n_shards
    B = batch_pad
    names = tuple(n for n, _s in axis_sizes)
    gather_axis = names if len(names) > 1 else names[0]
    # Flattened shard index, outer-axis-major — matching the host-major
    # device layout of make_multihost_mesh so global row ids line up with
    # the committed sharding's block order.
    shard = None
    for name, size in axis_sizes:
        ai = lax.axis_index(name)
        shard = ai if shard is None else shard * jnp.int32(size) + ai
    gidx = (shard * NPl + jnp.arange(NPl, dtype=jnp.int32)).astype(jnp.int32)
    num = jnp.maximum(f.num_nodes, 1)
    tf = jnp.maximum(f.to_find, 1)
    lanes = jnp.arange(LAP_MAX, dtype=jnp.int32)
    svec = jnp.arange(n_shards, dtype=jnp.int32)
    n_act = n_active.astype(jnp.int32)

    taint_ok, _pns, sel_ok, name_ok, unsched_ok, exist_anti_ok = \
        _static_masks(state, f)
    static_ok = (state.valid & name_ok & unsched_ok & taint_ok & sel_ok
                 & exist_anti_ok & f.extra_ok)
    w_tt, w_fit, _w_pts, _w_ipa, w_ba, _w_na, w_il = (
        f.weights[i] for i in range(7))
    il_term = w_il * f.il_score

    def cond(c):
        return c[0] < n_act

    def body(c):
        done, req_r, nonzero, pod_count, start, out = c
        fit_ok, fit_sc, ba = _resource_eval(
            f, fit_strategy, state.alloc_r, state.alloc_pods,
            req_r, nonzero, pod_count)
        okd = static_ok & fit_ok & (gidx < num)
        Fl = jnp.cumsum(okd.astype(jnp.int32))
        total = (w_tt * jnp.int64(MAX_NODE_SCORE) + w_fit * fit_sc
                 + w_ba * ba + il_term)
        # ---- collective 1: shard feasible-counts + F[start-1] origin -----
        sidx = start - jnp.int32(1)
        own = (start > 0) & (sidx >= shard * NPl) & (sidx < (shard + 1) * NPl)
        lpos = jnp.clip(sidx - shard * NPl, 0, NPl - 1)
        pair = jnp.stack([Fl[-1], jnp.where(own, Fl[lpos], 0)])
        g = lax.all_gather(pair, gather_axis)
        tots = g[:, 0]                                       # [S]
        total_feas = tots.sum()
        F = Fl + jnp.where(svec < shard, tots, 0).sum()      # global prefix
        owner = jnp.clip(sidx // jnp.int32(NPl), 0, n_shards - 1)
        f_start = jnp.where(
            start > 0,
            jnp.where(svec < owner, tots, 0).sum() + g[owner, 1], 0)
        rank = jnp.where(gidx >= start, F - f_start,
                         F + total_feas - f_start)
        rot = (gidx - start) % num
        l_full = total_feas // tf
        L = jnp.clip(jnp.minimum(l_full, n_act - done),
                     1, LAP_MAX).astype(jnp.int32)
        w = jnp.minimum((rank - 1) // tf, LAP_MAX)
        seg = jnp.where(okd & (w < L), w, LAP_MAX)
        in_w = seg[None, :] == lanes[:, None]                # [LAP_MAX, NPl]
        key = total * NP + (jnp.int32(NP - 1) - rot)
        key_w_l = jnp.max(jnp.where(in_w, key[None, :], -1), axis=1)
        is_b = okd & (rank % tf == 0)
        seg_b = jnp.where(is_b, jnp.minimum(rank // tf - 1, LAP_MAX), LAP_MAX)
        in_b = seg_b[None, :] == lanes[:, None]
        ev_w_l = jnp.min(jnp.where(in_b, rot[None, :] + 1, num), axis=1)
        # ---- collective 2: packed per-window reduction (mins negated) ----
        packed = jnp.concatenate([key_w_l, -ev_w_l.astype(jnp.int64)])
        red = lax.pmax(packed, gather_axis)
        key_w = red[:LAP_MAX]
        ev_w = (-red[LAP_MAX:]).astype(jnp.int32)
        has_w = (lanes < L) & (key_w >= 0)
        rot_w = jnp.int32(NP - 1) - (key_w % NP).astype(jnp.int32)
        row_w = jnp.where(has_w, (start + rot_w) % num, -1).astype(jnp.int32)
        start_w = (start + ev_w) % num
        # ---- apply the landings: shard-local one-hot updates -------------
        chosen_1h = (gidx[None, :] == row_w[:, None]) & has_w[:, None]
        cnt = chosen_1h.any(axis=0)
        c64 = cnt.astype(jnp.int64)
        req_r = req_r + f.request[None, :] * c64[:, None]
        nonzero = nonzero + f.nz_request[None, :] * c64[:, None]
        pod_count = pod_count + cnt.astype(jnp.int32)
        chosen_w = jnp.where(has_w, row_w, -1)
        block = jnp.stack([chosen_w, start_w.astype(jnp.int32)])
        out = lax.dynamic_update_slice(out, block, (jnp.int32(0), done))
        start = start_w[jnp.maximum(L - 1, 0)]
        return (done + L, req_r, nonzero, pod_count, start, out)

    out0 = jnp.full((2, B + LAP_MAX), -1, jnp.int32)
    c0 = (jnp.int32(0), ext0.req_r, ext0.nonzero, ext0.pod_count,
          ext0.start, out0)
    (_done, req_r, nonzero, pod_count, start, out) = lax.while_loop(
        cond, body, c0)
    fit_ok, fit_sc, ba = _resource_eval(
        f, fit_strategy, state.alloc_r, state.alloc_pods,
        req_r, nonzero, pod_count)
    carry = ScanCarry(req_r, nonzero, pod_count, fit_ok, fit_sc, ba,
                      ext0.dns_counts, ext0.sa_counts, ext0.anti_counts,
                      ext0.aff_counts, ext0.ipa_delta, start,
                      ext0.blocked, ext0.aux_cnt)
    return out[:, :B], carry


class _ShardedLap:
    """The compiled explicit-collectives lap kernel for one (mesh,
    batch_pad, fit_strategy, vmax): ``__call__(state, feats, n_active,
    carry_in)`` mirrors TPUScheduler._dispatch's schedule_batch contract —
    fresh (carry_in=None) and chained traces are separate jits, and the
    chained trace DONATES carry_in exactly like schedule_batch does."""

    def __init__(self, mesh: Mesh, batch_pad: int, fit_strategy: int,
                 vmax: int):
        self.mesh = mesh
        axis = _node_axis_of(mesh)
        names = axis if isinstance(axis, tuple) else (axis,)
        axis_sizes = tuple((a, mesh.shape[a]) for a in names)
        n_shards = mesh_shard_count(mesh)
        state_specs = _state_specs(axis)
        feat_specs = _feature_specs(axis)
        carry_specs = _carry_specs(axis)

        def body(state, f, n_active, ext0):
            return _lap_body(state, f, n_active, ext0,
                             batch_pad=batch_pad, fit_strategy=fit_strategy,
                             axis_sizes=axis_sizes, n_shards=n_shards)

        def fresh(state, f, n_active):
            fit_ok0, fit_sc0, ba0 = _resource_eval(
                f, fit_strategy, state.alloc_r, state.alloc_pods,
                state.req_r, state.nonzero, state.pod_count)
            npl = state.valid.shape[0]
            ext0 = ScanCarry(state.req_r, state.nonzero, state.pod_count,
                             fit_ok0, fit_sc0, ba0,
                             f.dns_counts, f.sa_counts, f.anti_counts,
                             f.aff_counts,
                             jnp.zeros((0, vmax), jnp.int64), f.start_index,
                             jnp.zeros(npl, bool), jnp.zeros(npl, jnp.int32))
            return body(state, f, n_active, ext0)

        def chained(state, f, n_active, carry_in):
            return body(state, f, n_active, carry_in)

        self.fresh = jax.jit(shard_map(
            fresh, mesh=mesh,
            in_specs=(state_specs, feat_specs, P()),
            out_specs=(P(), carry_specs), check_rep=False))
        self.chained = jax.jit(shard_map(
            chained, mesh=mesh,
            in_specs=(state_specs, feat_specs, P(), carry_specs),
            out_specs=(P(), carry_specs), check_rep=False),
            donate_argnums=3)

    def __call__(self, state, feats, n_active, carry_in=None):
        if carry_in is None:
            return self.fresh(state, feats, n_active)
        return self.chained(state, feats, n_active, carry_in)

    def lower(self, state, feats, n_active, carry_in=None):
        if carry_in is None:
            return self.fresh.lower(state, feats, n_active)
        return self.chained.lower(state, feats, n_active, carry_in)


_SHARDED_LAP_CACHE: dict = {}


def sharded_lap_schedule(mesh: Mesh, batch_pad: int, fit_strategy: int,
                         vmax: int) -> _ShardedLap:
    """Cached _ShardedLap per (mesh, statics) — the production dispatch's
    row-local path under a mesh (TPUScheduler._dispatch)."""
    key = (mesh, batch_pad, fit_strategy, vmax)
    fn = _SHARDED_LAP_CACHE.get(key)
    if fn is None:
        fn = _ShardedLap(mesh, batch_pad, fit_strategy, vmax)
        _SHARDED_LAP_CACHE[key] = fn
    return fn


def sharded_schedule_batch(mesh: Mesh, batch_pad: int, fit_strategy: int, vmax: int):
    """Build the mesh-sharded (and, when the mesh has >1 cell, cell-vmapped)
    compiled kernel. Call with (state, feats) whose leaves carry a leading
    cell dimension iff n_cells > 1."""
    n_cells = mesh.shape["cells"]
    kernel = partial(schedule_batch, batch_pad=batch_pad,
                     fit_strategy=fit_strategy, vmax=vmax)

    def run(state: DeviceNodeState, feats: BatchFeatures):
        return kernel(state, feats)

    if n_cells > 1:
        run = jax.vmap(run)

    def add_cells(spec: P) -> P:
        return P("cells", *spec) if n_cells > 1 else spec

    is_spec = lambda x: isinstance(x, P)  # noqa: E731
    state_specs = jax.tree_util.tree_map(add_cells, _STATE_SPECS, is_leaf=is_spec)
    feat_specs = jax.tree_util.tree_map(add_cells, _feature_specs(), is_leaf=is_spec)
    in_shardings = (
        jax.tree_util.tree_map(lambda s: NamedSharding(mesh, s), state_specs, is_leaf=is_spec),
        jax.tree_util.tree_map(lambda s: NamedSharding(mesh, s), feat_specs, is_leaf=is_spec),
    )
    # jit built ONCE: repeated calls hit the dispatch cache.
    return jax.jit(run, in_shardings=in_shardings)
