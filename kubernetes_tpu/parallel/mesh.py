"""Mesh construction + sharded dispatch of the batch scheduling kernel.

Sharding layout (scaling-book recipe: pick a mesh, annotate shardings, let
XLA insert collectives):

- `DeviceNodeState` row-major arrays shard their node dimension over the
  `"nodes"` mesh axis (`topo` is [K, NP] → shard dim 1).
- Per-node feature arrays (`exist_anti`, `ipa_base`) shard the same way;
  count tables ([C, VMAX]) and pod-level features replicate.
- An optional leading `"cells"` axis runs independent scheduling cells
  (separate clusters / Borg cells) data-parallel: every leaf gains a leading
  cell dimension and the kernel is vmapped over it.

The kernel's cross-node reductions (rotation cumsum, masked max/min, argmax
select) become XLA collectives over ICI; the scan carry's scatter updates
land on whichever shard owns the chosen row.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.device_state import DeviceNodeState
from ..ops.features import BatchFeatures
from ..ops.kernel import schedule_batch


def make_mesh(
    n_cells: int = 1,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Mesh over all (or given) devices: ("cells", "nodes"). With n_cells=1
    every chip shards the node axis of one cluster."""
    devs = list(devices if devices is not None else jax.devices())
    n = len(devs)
    if n % max(n_cells, 1) != 0:
        raise ValueError(f"{n} devices not divisible into {n_cells} cells")
    arr = np.array(devs).reshape(n_cells, n // n_cells)
    return Mesh(arr, axis_names=("cells", "nodes"))


# PartitionSpecs per DeviceNodeState field (node dim sharded).
_STATE_SPECS = DeviceNodeState(
    alloc_r=P("nodes", None), alloc_pods=P("nodes"), req_r=P("nodes", None),
    nonzero=P("nodes", None), pod_count=P("nodes"),
    taint_key=P("nodes", None), taint_val=P("nodes", None), taint_eff=P("nodes", None),
    unsched=P("nodes"), valid=P("nodes"), name_id=P("nodes"),
    topo=P(None, "nodes"),
)


def _feature_specs() -> BatchFeatures:
    """Per-node feature arrays shard over "nodes"; the rest replicate."""
    specs = {name: P() for name in BatchFeatures._fields}
    for per_node in ("exist_anti", "ipa_base", "sel_match", "extra_ok",
                     "il_score", "na_raw", "aux_room", "nom_pods"):
        specs[per_node] = P("nodes")
    specs["nom_req"] = P("nodes", None)
    return BatchFeatures(**specs)


def shard_node_state(state: DeviceNodeState, mesh: Mesh) -> DeviceNodeState:
    """Place a single cell's node state onto the mesh's "nodes" axis."""
    return jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        state, _STATE_SPECS)


def shard_features(feats: BatchFeatures, mesh: Mesh) -> BatchFeatures:
    """Place batch features: per-node vectors shard over "nodes", count
    tables and pod-level scalars replicate. With the inputs committed to
    these shardings, the ordinary jitted kernel compiles SPMD over the mesh
    (GSPMD propagation; cross-node reductions become ICI collectives) — the
    production TPUScheduler path needs no separate sharded kernel."""
    return jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        feats, _feature_specs())


def sharded_schedule_batch(mesh: Mesh, batch_pad: int, fit_strategy: int, vmax: int):
    """Build the mesh-sharded (and, when the mesh has >1 cell, cell-vmapped)
    compiled kernel. Call with (state, feats) whose leaves carry a leading
    cell dimension iff n_cells > 1."""
    n_cells = mesh.shape["cells"]
    kernel = partial(schedule_batch, batch_pad=batch_pad,
                     fit_strategy=fit_strategy, vmax=vmax)

    def run(state: DeviceNodeState, feats: BatchFeatures):
        return kernel(state, feats)

    if n_cells > 1:
        run = jax.vmap(run)

    def add_cells(spec: P) -> P:
        return P("cells", *spec) if n_cells > 1 else spec

    is_spec = lambda x: isinstance(x, P)  # noqa: E731
    state_specs = jax.tree_util.tree_map(add_cells, _STATE_SPECS, is_leaf=is_spec)
    feat_specs = jax.tree_util.tree_map(add_cells, _feature_specs(), is_leaf=is_spec)
    in_shardings = (
        jax.tree_util.tree_map(lambda s: NamedSharding(mesh, s), state_specs, is_leaf=is_spec),
        jax.tree_util.tree_map(lambda s: NamedSharding(mesh, s), feat_specs, is_leaf=is_spec),
    )
    # jit built ONCE: repeated calls hit the dispatch cache.
    return jax.jit(run, in_shardings=in_shardings)
