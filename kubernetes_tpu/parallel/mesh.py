"""Mesh construction + sharded dispatch of the batch scheduling kernel.

Sharding layout (scaling-book recipe: pick a mesh, annotate shardings, let
XLA insert collectives):

- `DeviceNodeState` row-major arrays shard their node dimension over the
  `"nodes"` mesh axis (`topo` is [K, NP] → shard dim 1).
- Per-node feature arrays (`exist_anti`, `ipa_base`) shard the same way;
  count tables ([C, VMAX]) and pod-level features replicate.
- An optional leading `"cells"` axis runs independent scheduling cells
  (separate clusters / Borg cells) data-parallel: every leaf gains a leading
  cell dimension and the kernel is vmapped over it.

The kernel's cross-node reductions (rotation cumsum, masked max/min, argmax
select) become XLA collectives over ICI; the scan carry's scatter updates
land on whichever shard owns the chosen row.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.device_state import DeviceNodeState
from ..ops.features import BatchFeatures
from ..ops.kernel import schedule_batch


def make_mesh(
    n_cells: int = 1,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Mesh over all (or given) devices: ("cells", "nodes"). With n_cells=1
    every chip shards the node axis of one cluster."""
    devs = list(devices if devices is not None else jax.devices())
    n = len(devs)
    if n % max(n_cells, 1) != 0:
        raise ValueError(f"{n} devices not divisible into {n_cells} cells")
    arr = np.array(devs).reshape(n_cells, n // n_cells)
    return Mesh(arr, axis_names=("cells", "nodes"))


def make_multihost_mesh(
    n_hosts: int,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Multi-HOST mesh ("dcn", "ici"): the outer axis spans hosts (data-
    center network), the inner axis a host's chips (ICI). The cluster-state
    node axis shards over BOTH axes jointly (P(("dcn", "ici"))), so one
    cluster's node tensors span every chip of every host; GSPMD then
    decomposes cross-node reductions into an intra-host ICI stage and a
    cross-host DCN stage — the scaling-book recipe for axes that cross the
    slice boundary (SURVEY §2.4 row 9's multi-host story). On real
    multi-host TPU the outer axis must follow the process/host grid
    (jax.devices() orders by process); virtual CPU devices validate the
    sharding + collective decomposition without N real hosts."""
    devs = list(devices if devices is not None else jax.devices())
    n = len(devs)
    if n_hosts <= 0 or n % n_hosts != 0:
        raise ValueError(f"{n} devices not divisible into {n_hosts} hosts")
    arr = np.array(devs).reshape(n_hosts, n // n_hosts)
    return Mesh(arr, axis_names=("dcn", "ici"))


def _node_axis_of(mesh: Mesh):
    """The spec entry for the cluster-state node dimension on this mesh:
    "nodes" on a ("cells", "nodes") mesh, the composite ("dcn", "ici") on a
    multi-host mesh."""
    return ("dcn", "ici") if "dcn" in mesh.axis_names else "nodes"


def _state_specs(axis) -> DeviceNodeState:
    return DeviceNodeState(
        alloc_r=P(axis, None), alloc_pods=P(axis), req_r=P(axis, None),
        nonzero=P(axis, None), pod_count=P(axis),
        taint_key=P(axis, None), taint_val=P(axis, None), taint_eff=P(axis, None),
        unsched=P(axis), valid=P(axis), name_id=P(axis),
        topo=P(None, axis),
    )


def _feature_specs(axis="nodes") -> BatchFeatures:
    """Per-node feature arrays shard over the node axis; the rest replicate."""
    specs = {name: P() for name in BatchFeatures._fields}
    for per_node in ("exist_anti", "ipa_base", "sel_match", "extra_ok",
                     "il_score", "na_raw", "aux_room", "nom_pods"):
        specs[per_node] = P(axis)
    specs["nom_req"] = P(axis, None)
    return BatchFeatures(**specs)


# Backwards-compatible single-host specs.
_STATE_SPECS = _state_specs("nodes")


_MESH_STATE_SHARDINGS_CACHE: dict = {}


def mesh_state_shardings(mesh: Mesh) -> DeviceNodeState:
    """The NamedShardings shard_node_state commits the state to, as one
    cached pytree — handed to the delta row patch (ops/device_state.py
    patch_rows / ops/kernel.py patch_carry_rows_pinned) as explicit
    `out_shardings`, so a patched state stays committed to the session
    kernel's input shardings and the next dispatch does not retrace.
    Cached per mesh: the pytree doubles as the jit-cache key over there."""
    got = _MESH_STATE_SHARDINGS_CACHE.get(mesh)
    if got is None:
        got = jax.tree_util.tree_map(
            lambda s: NamedSharding(mesh, s),
            _state_specs(_node_axis_of(mesh)),
            is_leaf=lambda x: isinstance(x, P))
        _MESH_STATE_SHARDINGS_CACHE[mesh] = got
    return got


def shard_node_state(state: DeviceNodeState, mesh: Mesh) -> DeviceNodeState:
    """Place a cell's node state onto the mesh's node axis (ICI on a
    single-host mesh; ICI within hosts + DCN across hosts on a multi-host
    mesh)."""
    return jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        state, _state_specs(_node_axis_of(mesh)))


def shard_features(feats: BatchFeatures, mesh: Mesh) -> BatchFeatures:
    """Place batch features: per-node vectors shard over the node axis,
    count tables and pod-level scalars replicate. With the inputs committed
    to these shardings, the ordinary jitted kernel compiles SPMD over the
    mesh (GSPMD propagation; cross-node reductions become ICI — and on a
    multi-host mesh, ICI+DCN — collectives): the production TPUScheduler
    path needs no separate sharded kernel."""
    return jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        feats, _feature_specs(_node_axis_of(mesh)))


def collective_report(compiled_text: str, n_hosts: int, per_host: int) -> dict:
    """Classify every collective in compiled HLO by the mesh axis it rides:
    a replica group whose members all live on ONE host is an ICI collective;
    a group spanning hosts rides the DCN. Device id -> host is id//per_host
    (the ("dcn", "ici") mesh lays devices out host-major). Returns
    {"ici": {op: n}, "dcn": {op: n}, "total": {op: n}} — the per-axis
    breakdown the multi-host dryrun prints so the DCN traffic of a sharding
    choice is visible, not guessed."""
    import re

    out = {"ici": {}, "dcn": {}, "total": {}}

    def classify(groups):
        spans_hosts = any(
            len({d // per_host for d in g}) > 1 for g in groups if g)
        return "dcn" if spans_hosts else "ici"

    for m in re.finditer(
            r"(all-reduce|all-gather|reduce-scatter|all-to-all|"
            r"collective-permute)(-start)?[^\n]*", compiled_text):
        line = m.group(0)
        op = m.group(1)
        groups = []
        # Match the FULL braced list: a non-greedy `\{(.*?)\}` would stop at
        # the first '}' of nested groups like {{0,1},{2,3}} and classify
        # only the first replica group — a collective whose later groups
        # span hosts would be misreported as ICI (ADVICE r5).
        rg = re.search(
            r"replica_groups=\{(\{[^}]*\}(?:,\{[^}]*\})*|[^{}]*)\}", line)
        if rg is not None:
            inner = rg.group(1)
            if "{" in inner:
                groups = [[int(x) for x in g.split(",") if x.strip()]
                          for g in re.findall(r"\{([\d,]*)\}", inner)]
            elif inner.strip():
                # flat form: replica_groups={0,1,2,3} — one group
                groups = [[int(x) for x in inner.split(",") if x.strip()]]
        stp = re.search(r"source_target_pairs=\{(.*)?\}", line)
        if stp is not None:
            groups = [[int(x) for x in pair.split(",")]
                      for pair in re.findall(r"\{(\d+,\d+)\}", stp.group(0))]
        axis = classify(groups) if groups else "ici"
        out[axis][op] = out[axis].get(op, 0) + 1
        out["total"][op] = out["total"].get(op, 0) + 1
    return out


def sharded_schedule_batch(mesh: Mesh, batch_pad: int, fit_strategy: int, vmax: int):
    """Build the mesh-sharded (and, when the mesh has >1 cell, cell-vmapped)
    compiled kernel. Call with (state, feats) whose leaves carry a leading
    cell dimension iff n_cells > 1."""
    n_cells = mesh.shape["cells"]
    kernel = partial(schedule_batch, batch_pad=batch_pad,
                     fit_strategy=fit_strategy, vmax=vmax)

    def run(state: DeviceNodeState, feats: BatchFeatures):
        return kernel(state, feats)

    if n_cells > 1:
        run = jax.vmap(run)

    def add_cells(spec: P) -> P:
        return P("cells", *spec) if n_cells > 1 else spec

    is_spec = lambda x: isinstance(x, P)  # noqa: E731
    state_specs = jax.tree_util.tree_map(add_cells, _STATE_SPECS, is_leaf=is_spec)
    feat_specs = jax.tree_util.tree_map(add_cells, _feature_specs(), is_leaf=is_spec)
    in_shardings = (
        jax.tree_util.tree_map(lambda s: NamedSharding(mesh, s), state_specs, is_leaf=is_spec),
        jax.tree_util.tree_map(lambda s: NamedSharding(mesh, s), feat_specs, is_leaf=is_spec),
    )
    # jit built ONCE: repeated calls hit the dispatch cache.
    return jax.jit(run, in_shardings=in_shardings)
