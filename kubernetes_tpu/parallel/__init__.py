"""Distribution layer: device meshes and sharded kernel dispatch.

The reference's distribution fabric is etcd watch → apiserver watch cache →
client-go informers (SURVEY.md §5 'distributed communication backend'); its
intra-cycle parallelism is a 16-goroutine chunked fan-out (§2.4). The
TPU-native equivalents here:

- the NODE axis of the cluster-state tensors shards across chips over ICI
  (tensor-parallel style: the "model" being sharded is the cluster state);
- independent scheduling *cells* (Borg-style cells / multi-cluster shards)
  map to a data-parallel mesh axis;
- XLA inserts the collectives (the cross-shard argmax/min/max reductions in
  the kernel) — no hand-written communication.
"""

from .mesh import (collective_report, make_mesh, make_multihost_mesh,
                   mesh_state_shardings, shard_features, shard_node_state,
                   sharded_schedule_batch)

__all__ = ["collective_report", "make_mesh", "make_multihost_mesh",
           "mesh_state_shardings", "shard_features", "shard_node_state",
           "sharded_schedule_batch"]
