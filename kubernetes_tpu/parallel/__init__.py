"""Distribution layer: device meshes and sharded kernel dispatch.

The reference's distribution fabric is etcd watch → apiserver watch cache →
client-go informers (SURVEY.md §5 'distributed communication backend'); its
intra-cycle parallelism is a 16-goroutine chunked fan-out (§2.4). The
TPU-native equivalents here:

- the NODE axis of the cluster-state tensors shards across chips over ICI
  (tensor-parallel style: the "model" being sharded is the cluster state);
- independent scheduling *cells* (Borg-style cells / multi-cluster shards)
  map to a data-parallel mesh axis;
- GSPMD inserts the collectives for the general kernel; ROW-LOCAL plans
  dispatch through an explicit `shard_map` lap kernel instead
  (`sharded_lap_schedule`) whose two small per-lap collectives are
  hand-placed and regression-pinned ≤ the GSPMD baseline (docs/PERF.md §5).
"""

from .mesh import (collective_report, make_mesh, make_multihost_mesh,
                   mesh_host_split, mesh_shard_count, mesh_state_shardings,
                   shard_features, shard_node_state, sharded_lap_schedule,
                   sharded_schedule_batch)

__all__ = ["collective_report", "make_mesh", "make_multihost_mesh",
           "mesh_host_split", "mesh_shard_count", "mesh_state_shardings",
           "shard_features", "shard_node_state", "sharded_lap_schedule",
           "sharded_schedule_batch"]
