"""Declarative fleet specifications.

A ``FleetSpec`` says WHAT many-process cluster to run — how many
apiserver replicas, how many shard scheduler processes (and whether they
pin per core), how many hollow-plane processes split one
``HollowProfile`` by deterministic name-prefix ranges, which controller
managers ride along, and the env/wire/hint seams every child inherits.
The conductor (conductor.py) owns HOW: staged bring-up, readiness
barriers, supervision, teardown.

Specs are plain dicts on disk (JSON) so the perf harness, the CLI
(``python -m kubernetes_tpu.fleet --spec fleet.json --pods N``), and
tests share one format — docs/SCALE.md § fleet conductor documents it:

    {"name": "fleet-100k", "shards": 2, "replicas": 1,
     "mesh_devices": 8, "hollow_procs": 2,
     "hollow": {"count": 100000, "zones": 100, "heartbeat_s": 120.0,
                "drift": 0.02, "churn_per_s": 2.0},
     "env": {"TPU_SCHED_HINT_LRU": "2"}}
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Dict, Optional

# Per-role crash policy (the restart-policy matrix, docs/SCALE.md):
#   restart — respawn the member, counted, never silent. Hollow members
#             respawn with --adopt so they re-register their EXACT
#             name-prefix range with zero duplicate nodes.
#   adopt   — do NOT respawn: the surviving peers absorb the dead
#             member's work through an existing protocol (a crashed
#             shard's lease expires and the ring successor adopts its
#             range — a conductor respawn would race that adoption).
#   never   — record the exit and leave it down (control-plane replicas:
#             losing the leader is a FAILOVER, not a supervision event).
RESTART_POLICIES = ("restart", "adopt", "never")
DEFAULT_RESTART = {
    "apiserver": "never",
    "follower": "never",
    "shard": "adopt",
    "hollow": "restart",
    "controller": "restart",
    "workload": "restart",
    "deschedule": "restart",
}


@dataclass
class FleetSpec:
    name: str = "fleet"
    # Shard scheduler plane (`python -m kubernetes_tpu --shard-index i`).
    shards: int = 1
    shard_lease_s: float = 15.0
    pin_shards: bool = True         # taskset shard i -> core i%cores (n>1)
    # mesh_devices > 1 gives every shard a virtual device mesh
    # (XLA_FLAGS --xla_force_host_platform_device_count=N, the
    # BENCH_MESH_DEVICES seam) so row-local plans dispatch mesh-SPMD.
    mesh_devices: int = 0
    # Replicated control plane: follower apiservers tailing the leader.
    replicas: int = 0
    repl_lease_s: float = 2.0
    # Hollow kubelet plane: one HollowProfile dict split across
    # hollow_procs processes by deterministic name-prefix ranges
    # (HollowProfile.split — disjoint-and-complete absolute index tiles).
    hollow: Optional[dict] = None
    hollow_procs: int = 1
    # Controller managers: node-lifecycle kwargs dict and/or workload
    # manager dict ({"managers": 2, "lease_ttl": s, "tick": s,
    # "autoscale": {...}, "trace": {...}}).
    node_lifecycle: Optional[dict] = None
    workload: Optional[dict] = None
    # Descheduler managers (drift-repair plane, docs/DESCHEDULE.md):
    # {"managers": 2, "lease_ttl": s, "tick": s, "hysteresis": n,
    #  "max_moves": n, "device": bool}.
    deschedule: Optional[dict] = None
    # Env seams every child inherits (wire plane TPU_SCHED_WIRE, hint
    # A/B TPU_SCHED_HINT_LRU / TPU_SCHED_SCORE_HINTS, ...); shard_env
    # lands on shard schedulers only.
    env: Dict[str, str] = field(default_factory=dict)
    shard_env: Dict[str, str] = field(default_factory=dict)
    # Observability / durability seams.
    flightrec_dir: str = ""
    data_dir: str = ""
    fair_tenants: bool = False
    apf_workload: str = ""
    # Supervision.
    restart: Dict[str, str] = field(
        default_factory=lambda: dict(DEFAULT_RESTART))
    max_restarts: int = 3           # per member, then the conductor gives up
    supervise_interval_s: float = 0.5
    startup_timeout_s: float = 300.0

    @classmethod
    def from_dict(cls, d: dict) -> "FleetSpec":
        restart = dict(DEFAULT_RESTART)
        restart.update({str(k): str(v)
                        for k, v in dict(d.get("restart", {})).items()})
        return cls(
            name=str(d.get("name", "fleet")),
            shards=int(d.get("shards", 1)),
            shard_lease_s=float(d.get("shard_lease_s", 15.0)),
            pin_shards=bool(d.get("pin_shards", True)),
            mesh_devices=int(d.get("mesh_devices", 0)),
            replicas=int(d.get("replicas", 0)),
            repl_lease_s=float(d.get("repl_lease_s", 2.0)),
            hollow=(dict(d["hollow"]) if d.get("hollow") else None),
            hollow_procs=int(d.get("hollow_procs", 1)),
            node_lifecycle=(dict(d["node_lifecycle"])
                            if d.get("node_lifecycle") else None),
            workload=(dict(d["workload"]) if d.get("workload") else None),
            deschedule=(dict(d["deschedule"])
                        if d.get("deschedule") else None),
            env={str(k): str(v) for k, v in dict(d.get("env", {})).items()},
            shard_env={str(k): str(v)
                       for k, v in dict(d.get("shard_env", {})).items()},
            flightrec_dir=str(d.get("flightrec_dir", "")),
            data_dir=str(d.get("data_dir", "")),
            fair_tenants=bool(d.get("fair_tenants", False)),
            apf_workload=str(d.get("apf_workload", "")),
            restart=restart,
            max_restarts=int(d.get("max_restarts", 3)),
            supervise_interval_s=float(d.get("supervise_interval_s", 0.5)),
            startup_timeout_s=float(d.get("startup_timeout_s", 300.0)),
        )

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "shards": self.shards,
            "shard_lease_s": self.shard_lease_s,
            "pin_shards": self.pin_shards,
            "mesh_devices": self.mesh_devices,
            "replicas": self.replicas,
            "repl_lease_s": self.repl_lease_s,
            "hollow": dict(self.hollow) if self.hollow else None,
            "hollow_procs": self.hollow_procs,
            "node_lifecycle": (dict(self.node_lifecycle)
                               if self.node_lifecycle else None),
            "workload": dict(self.workload) if self.workload else None,
            "deschedule": (dict(self.deschedule)
                           if self.deschedule else None),
            "env": dict(self.env),
            "shard_env": dict(self.shard_env),
            "flightrec_dir": self.flightrec_dir,
            "data_dir": self.data_dir,
            "fair_tenants": self.fair_tenants,
            "apf_workload": self.apf_workload,
            "restart": dict(self.restart),
            "max_restarts": self.max_restarts,
            "supervise_interval_s": self.supervise_interval_s,
            "startup_timeout_s": self.startup_timeout_s,
        }

    @classmethod
    def load(cls, path: str) -> "FleetSpec":
        with open(path) as fh:
            return cls.from_dict(json.load(fh))

    def validate(self) -> "FleetSpec":
        """Raise ValueError on an unrunnable spec (the conductor calls
        this before spawning anything — a bad spec must fail at stage
        zero, not as a half-up fleet)."""
        if self.shards < 1:
            raise ValueError("spec.shards must be >= 1")
        if self.replicas < 0:
            raise ValueError("spec.replicas must be >= 0")
        if self.hollow_procs < 1:
            raise ValueError("spec.hollow_procs must be >= 1")
        if self.mesh_devices < 0:
            raise ValueError("spec.mesh_devices must be >= 0")
        if self.max_restarts < 0:
            raise ValueError("spec.max_restarts must be >= 0")
        if self.supervise_interval_s <= 0:
            raise ValueError("spec.supervise_interval_s must be > 0")
        if self.startup_timeout_s <= 0:
            raise ValueError("spec.startup_timeout_s must be > 0")
        for role, policy in self.restart.items():
            if policy not in RESTART_POLICIES:
                raise ValueError(
                    f"spec.restart[{role!r}] = {policy!r}: must be one of "
                    f"{RESTART_POLICIES}")
        if self.hollow is not None:
            from ..hollow import HollowProfile
            prof = HollowProfile.from_dict(self.hollow)
            if prof.count < 1:
                raise ValueError("spec.hollow.count must be >= 1")
            if self.hollow_procs > prof.count:
                raise ValueError("spec.hollow_procs exceeds hollow.count")
        if self.workload is not None \
                and int(self.workload.get("managers", 2)) < 1:
            raise ValueError("spec.workload.managers must be >= 1")
        if self.deschedule is not None \
                and int(self.deschedule.get("managers", 2)) < 1:
            raise ValueError("spec.deschedule.managers must be >= 1")
        return self
