"""Declarative fleet conductor (docs/SCALE.md § fleet conductor).

One ``FleetSpec`` describes a whole many-process cluster — apiserver
replicas, shard schedulers (optionally on a virtual device mesh), N
hollow kubelet planes splitting one profile by name-prefix range,
controller managers — and one ``FleetConductor`` runs it as a unit:
staged bring-up with readiness barriers, per-role crash supervision,
periodic RSS/throughput sampling, SIGUSR2 flight-record fan-out, and
reverse-stage teardown. ``python -m kubernetes_tpu.fleet --spec
fleet.json --pods N`` is the CLI face; ``shard/harness.py`` and
``perf/harness.py`` drive the same conductor.
"""

from .conductor import FleetConductor, FleetMember
from .spec import DEFAULT_RESTART, RESTART_POLICIES, FleetSpec

__all__ = ["FleetConductor", "FleetMember", "FleetSpec",
           "DEFAULT_RESTART", "RESTART_POLICIES"]
