"""The fleet conductor: staged bring-up, supervision, and teardown of a
declarative many-process cluster (FleetSpec).

This is the subsystem the reference composes out of kubemark +
scheduler_perf: one object owns the whole process tree — apiserver
leader, follower replicas, shard schedulers, N hollow kubelet planes
splitting one profile by name-prefix range, controller managers — and
runs it as a unit:

- **staged bring-up with readiness barriers** — leader ready → followers
  tailing (election topology injected) → shards leased (the shard-lease
  table shows every slot owned) → hollow fleet registered (every member
  acknowledged its exact sub-range) → controllers active. Every spawn
  blocks on the child's ready line (testing/faults.spawn_ready) and
  every child's stdout is drained for the fleet's whole life
  (drain_pipe — the PR-8 unread-64KB-pipe stall class);
- **supervision with per-role restart policy** (spec.restart): a crashed
  hollow member respawns with ``--adopt`` and re-registers its exact
  prefix range with zero duplicate nodes; a crashed shard is NOT
  respawned — its lease expires and the ring successor adopts the range
  (a conductor respawn would race that adoption); apiserver replicas
  stay down (losing the leader is a failover, not a supervision event).
  Restarts are counted and ledgered in ``events`` — never silent;
- **periodic sampling** — per-process VmRSS peaks fold into one
  consolidated ``detail()`` line alongside bound-pod throughput samples
  (``note_bound``), stage timings, and the restart ledger;
- **flight-record collection** — SIGUSR2 fans out to every member that
  installs a dump handler before teardown, and ``artifacts()`` lists
  what landed in flightrec_dir.
"""

from __future__ import annotations

import json
import os
import shutil
import signal
import sys
import tempfile
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional

from ..shard.harness import _call, _env, _repo_root, rss_mb, scrape_metrics
from .spec import FleetSpec

READY_SERVING = r"serving on 127\.0\.0\.1:(\d+)"
READY_REGISTERED = r"registered (\d+) nodes"
READY_METRICS = r"metrics on (127\.0\.0\.1:\d+)"

# Roles whose processes install a SIGUSR2 flight-dump handler (apiserver
# and scheduler via core/spans.FlightRecorder, the hollow plane via its
# stats-line handler). Signalling a process WITHOUT a handler would kill
# it — the fan-out only targets these.
SIGUSR2_ROLES = ("apiserver", "follower", "shard", "hollow")


class FleetMember:
    """One supervised child process: its spawn recipe (for respawns), its
    live handles, and its supervision ledger."""

    def __init__(self, role: str, index: int, cmd: List[str], env: dict,
                 ready_pattern: str, respawn_extra: Optional[List[str]] = None):
        self.role = role
        self.index = index
        self.name = f"{role}-{index}"
        self.cmd = list(cmd)
        self.env = env
        self.ready_pattern = ready_pattern
        # Extra argv appended on a SUPERVISED respawn only (a hollow
        # member restarts with --adopt: survivors of its range are
        # claimed, not duplicated).
        self.respawn_extra = list(respawn_extra or ())
        self.proc = None
        self.tail = None            # drained stdout deque (drain_pipe)
        self.url = ""               # ready-line URL, when the role has one
        self.registered = 0         # hollow: nodes acknowledged at ready
        self.restarts = 0
        self.rss_peak_mb = 0.0
        self.stopping = False       # conductor-initiated stop in progress

    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    def summary(self) -> dict:
        return {"name": self.name, "role": self.role, "index": self.index,
                "pid": self.proc.pid if self.proc is not None else 0,
                "alive": self.alive(), "url": self.url,
                "restarts": self.restarts,
                "rss_peak_mb": self.rss_peak_mb}


class FleetConductor:
    def __init__(self, spec: FleetSpec):
        self.spec = spec.validate()
        self.members: List[FleetMember] = []
        self.stages: List[dict] = []       # bring-up timeline
        self.events: List[dict] = []       # supervision ledger
        self.restarts_total = 0
        self.base = ""                     # leader URL
        self.follower_urls: List[str] = []
        self.shard_urls: List[str] = []
        self.controller_urls: List[str] = []
        self._bound_samples: List[tuple] = []
        self._lock = threading.Lock()
        self._stopping = threading.Event()
        self._supervisor: Optional[threading.Thread] = None
        self._tmpdir = ""
        self._started = False
        self._env = _env()
        self._env.update(spec.env)
        if spec.flightrec_dir:
            os.makedirs(spec.flightrec_dir, exist_ok=True)
            self._env["TPU_SCHED_FLIGHTREC_DIR"] = spec.flightrec_dir
        if spec.fair_tenants:
            self._env["TPU_SCHED_FAIR_TENANTS"] = "1"
        if spec.apf_workload:
            self._env["TPU_SCHED_APF_WORKLOAD"] = spec.apf_workload

    # -- the ONE spawn site (supervision-discipline: readiness barrier +
    # -- drained pipe wired in the same slice) ------------------------------

    def _spawn(self, member: FleetMember, extra: Optional[List[str]] = None):
        """Spawn (or respawn) a member: block on its ready line, then wire
        the stdout drain for the member's whole life. Every child the
        conductor ever starts goes through here — the readiness barrier
        and the pipe drain are structurally inseparable from the spawn."""
        from ..testing.faults import drain_pipe, spawn_ready

        proc, m = spawn_ready(member.cmd + list(extra or ()),
                              member.ready_pattern, cwd=_repo_root(),
                              env=member.env,
                              timeout=self.spec.startup_timeout_s)
        member.proc = proc
        member.tail = drain_pipe(proc)
        if member.ready_pattern == READY_SERVING:
            member.url = f"http://127.0.0.1:{m.group(1)}"
        elif member.ready_pattern == READY_METRICS:
            member.url = f"http://{m.group(1)}"
        elif member.ready_pattern == READY_REGISTERED:
            member.registered = int(m.group(1))
        return member

    def _stage(self, name: str, t0: float, members: int) -> None:
        self.stages.append({"stage": name,
                            "elapsed_s": round(time.monotonic() - t0, 2),
                            "members": members})

    # -- staged bring-up ----------------------------------------------------

    def start(self) -> "FleetConductor":
        if self._started:
            return self
        self._started = True
        self._tmpdir = tempfile.mkdtemp(prefix="fleet-")
        try:
            self._start_leader()
            self._start_followers()
            self._start_shards()
            self._start_hollow()
            self._start_controllers()
        except BaseException:
            self._stopping.set()
            self._teardown_procs()
            raise
        self._supervisor = threading.Thread(
            target=self._supervise_loop, name="fleet-supervisor", daemon=True)
        self._supervisor.start()
        return self

    def _start_leader(self) -> None:
        t0 = time.monotonic()
        spec = self.spec
        cmd = [sys.executable, "-m", "kubernetes_tpu.core.apiserver",
               "--port", "0"]
        if spec.data_dir:
            cmd += ["--data-dir", spec.data_dir]
        if spec.replicas:
            cmd += ["--repl-lease-duration", str(spec.repl_lease_s)]
        leader = FleetMember("apiserver", 0, cmd, self._env, READY_SERVING)
        self.members.append(self._spawn(leader))
        self.base = leader.url
        self._stage("leader", t0, 1)

    def _start_followers(self) -> None:
        spec = self.spec
        if not spec.replicas:
            return
        t0 = time.monotonic()
        for rank in range(1, spec.replicas + 1):
            cmd = [sys.executable, "-m", "kubernetes_tpu.core.apiserver",
                   "--port", "0", "--replicate-from", self.base,
                   "--replica-rank", str(rank),
                   "--repl-lease-duration", str(spec.repl_lease_s)]
            if spec.data_dir:
                cmd += ["--data-dir", f"{spec.data_dir}-follower-{rank}"]
            f = FleetMember("follower", rank - 1, cmd, self._env,
                            READY_SERVING)
            self.members.append(self._spawn(f))
            self.follower_urls.append(f.url)
        # Ephemeral ports: inject the full election topology post-spawn —
        # only now are the followers "tailing" rather than merely serving.
        peers = {"0": self.base}
        peers.update({str(r + 1): u
                      for r, u in enumerate(self.follower_urls)})
        for url in [self.base] + self.follower_urls:
            _call(url, "POST", "/replication/peers", {"peers": peers})
        self._stage("followers", t0, spec.replicas)

    def _shard_env(self) -> dict:
        spec = self.spec
        env = dict(self._env)
        env.update(spec.shard_env)
        if spec.mesh_devices > 1:
            # The BENCH_MESH_DEVICES seam, applied where it must land for
            # a CHILD process: XLA_FLAGS before backend init gives every
            # shard a virtual device mesh, so TPUScheduler(mesh="auto")
            # builds it and row-local plans dispatch mesh-SPMD.
            flags = env.get("XLA_FLAGS", "")
            if "xla_force_host_platform_device_count" not in flags:
                env["XLA_FLAGS"] = (
                    flags + " --xla_force_host_platform_device_count="
                    + str(spec.mesh_devices)).strip()
        return env

    def _start_shards(self) -> None:
        t0 = time.monotonic()
        spec = self.spec
        env = self._shard_env()

        def build(i: int) -> FleetMember:
            # Shard-per-core placement (n>1 only): without pinning each
            # shard's XLA pool spans every core and the plane ping-pongs
            # instead of overlapping.
            pin: List[str] = []
            if spec.shards > 1 and spec.pin_shards and shutil.which("taskset"):
                pin = ["taskset", "-c",
                       str(i % max(1, os.cpu_count() or 1))]
            api_url = self.base
            extra: List[str] = []
            if self.follower_urls:
                api_url = self.follower_urls[i % len(self.follower_urls)]
                others = [u for u in self.follower_urls if u != api_url] \
                    + [self.base]
                extra = ["--api-fallbacks", ",".join(others)]
            cmd = pin + [sys.executable, "-m", "kubernetes_tpu",
                         "--api-url", api_url, "--platform", "cpu",
                         "--port", "0",
                         "--shard-index", str(i),
                         "--shard-count", str(spec.shards),
                         "--shard-lease-duration", str(spec.shard_lease_s)] \
                + extra
            return FleetMember("shard", i, cmd, env, READY_SERVING)

        shards = [build(i) for i in range(spec.shards)]
        # Parallel spawn: each shard pays the JAX import.
        with ThreadPoolExecutor(max_workers=spec.shards) as ex:
            list(ex.map(self._spawn, shards))
        self.members.extend(shards)
        self.shard_urls = [s.url for s in shards]
        self._wait_shards_leased()
        self._stage("shards", t0, spec.shards)

    def _wait_shards_leased(self) -> None:
        """Barrier: every shard-lease slot is owned. A shard that is
        'serving' but not yet leased would leave its range unscheduled
        until the first lease sweep — the stage gate makes bring-up mean
        bring-up."""
        spec = self.spec
        deadline = time.monotonic() + spec.startup_timeout_s
        while time.monotonic() < deadline:
            owned = 0.0
            for url in self.shard_urls:
                try:
                    owned += scrape_metrics(url).get(
                        "scheduler_shard_owned_shards", 0.0)
                except Exception:  # noqa: BLE001 - metrics not up yet
                    continue
            if owned >= spec.shards:
                return
            if self._stopping.wait(0.2):
                return
        raise TimeoutError(
            f"shards-leased barrier: {owned}/{spec.shards} slots owned "
            f"after {spec.startup_timeout_s}s")

    def _start_hollow(self) -> None:
        spec = self.spec
        if spec.hollow is None:
            return
        t0 = time.monotonic()
        from ..hollow import HollowProfile
        profile = HollowProfile.from_dict(spec.hollow)
        subs = profile.split(spec.hollow_procs)
        hollow_members: List[FleetMember] = []
        for i, sub in enumerate(subs):
            path = os.path.join(self._tmpdir, f"hollow-{i}.json")
            with open(path, "w") as fh:
                json.dump(sub.to_dict(), fh)
            cmd = [sys.executable, "-m", "kubernetes_tpu.hollow",
                   "--api-url", self.base, "--profile", path]
            hollow_members.append(FleetMember(
                "hollow", i, cmd, self._env, READY_REGISTERED,
                respawn_extra=["--adopt"]))
        # Parallel registration: each member bulk-creates its own
        # disjoint range, so the chunked POSTs interleave cleanly.
        with ThreadPoolExecutor(max_workers=len(hollow_members)) as ex:
            list(ex.map(self._spawn, hollow_members))
        self.members.extend(hollow_members)
        got = sum(m.registered for m in hollow_members)
        if got < profile.count:
            raise RuntimeError(
                f"hollow-registered barrier: {got}/{profile.count} nodes "
                f"acknowledged across {len(hollow_members)} members")
        self._stage("hollow", t0, len(hollow_members))

    def _start_controllers(self) -> None:
        spec = self.spec
        if (spec.node_lifecycle is None and spec.workload is None
                and spec.deschedule is None):
            return
        t0 = time.monotonic()
        n = 0
        if spec.node_lifecycle is not None:
            nl = spec.node_lifecycle
            cmd = [sys.executable, "-m", "kubernetes_tpu.controllers",
                   "--api-url", self.base,
                   "--grace", str(nl.get("grace", 4.0)),
                   "--noexec-after", str(nl.get("noexec_after", 2.0)),
                   "--tick", str(nl.get("tick", 0.5)),
                   "--primary-qps", str(nl.get("primary_qps", 2.0)),
                   "--secondary-qps", str(nl.get("secondary_qps", 0.1)),
                   "--unhealthy-threshold",
                   str(nl.get("unhealthy_threshold", 0.55))]
            for url in self.follower_urls:
                cmd += ["--fallback", url]
            m = FleetMember("controller", 0, cmd, self._env, READY_METRICS)
            self.members.append(self._spawn(m))
            self.controller_urls.append(m.url)
            n += 1
        if spec.workload is not None:
            wl = spec.workload
            for i in range(int(wl.get("managers", 2))):
                cmd = [sys.executable, "-m", "kubernetes_tpu.controllers",
                       "--mode", "workload", "--api-url", self.base,
                       "--identity", f"wm-{i}",
                       "--lease-ttl", str(wl.get("lease_ttl", 2.0)),
                       "--tick", str(wl.get("tick", 0.25))]
                for url in self.follower_urls:
                    cmd += ["--fallback", url]
                auto = wl.get("autoscale")
                if auto is not None:
                    cmd += ["--autoscale",
                            "--min-nodes", str(auto.get("min", 0)),
                            "--max-nodes", str(auto.get("max", 100)),
                            "--scale-wave", str(auto.get("wave", 2)),
                            "--pending-age",
                            str(auto.get("pending_age", 2.0)),
                            "--scale-cooldown",
                            str(auto.get("cooldown", 5.0))]
                trace = wl.get("trace")
                if trace is not None:
                    cmd += ["--trace-deployments",
                            str(trace.get("deployments", 0)),
                            "--trace-gangs", str(trace.get("gangs", 0)),
                            "--trace-rate", str(trace.get("rate", 2.0)),
                            "--trace-lifetime",
                            str(trace.get("lifetime", 0.0)),
                            "--trace-seed", str(trace.get("seed", 0))]
                m = FleetMember("workload", i, cmd, self._env, READY_METRICS)
                self.members.append(self._spawn(m))
                n += 1
        if spec.deschedule is not None:
            ds = spec.deschedule
            for i in range(int(ds.get("managers", 2))):
                cmd = [sys.executable, "-m", "kubernetes_tpu.controllers",
                       "--mode", "deschedule", "--api-url", self.base,
                       "--identity", f"dm-{i}",
                       "--lease-ttl", str(ds.get("lease_ttl", 2.0)),
                       "--tick", str(ds.get("tick", 0.25)),
                       "--hysteresis", str(ds.get("hysteresis", 5)),
                       "--margin", str(ds.get("margin", 0.10)),
                       "--max-moves", str(ds.get("max_moves", 64)),
                       "--primary-qps", str(ds.get("primary_qps", 20.0)),
                       "--secondary-qps",
                       str(ds.get("secondary_qps", 0.1))]
                if ds.get("device"):
                    cmd += ["--deschedule-device"]
                for url in self.follower_urls:
                    cmd += ["--fallback", url]
                m = FleetMember("deschedule", i, cmd, self._env,
                                READY_METRICS)
                self.members.append(self._spawn(m))
                n += 1
        self._stage("controllers", t0, n)

    # -- supervision --------------------------------------------------------

    def _supervise_loop(self) -> None:
        interval = self.spec.supervise_interval_s
        while not self._stopping.wait(interval):
            self.sample()
            for member in list(self.members):
                if member.stopping or member.proc is None \
                        or member.proc.poll() is None:
                    continue
                self._handle_exit(member)

    def _handle_exit(self, member: FleetMember) -> None:
        policy = self.spec.restart.get(member.role, "never")
        event = {"t": round(time.monotonic(), 2), "member": member.name,
                 "role": member.role, "exit": member.proc.returncode,
                 "policy": policy}
        if policy == "restart":
            if member.restarts >= self.spec.max_restarts:
                event["action"] = "gave-up"
            else:
                try:
                    # Respawn through the one barrier+drain spawn site;
                    # respawn_extra rides along (--adopt: a hollow member
                    # re-claims the survivors of its exact prefix range).
                    self._spawn(member, extra=member.respawn_extra)
                    member.restarts += 1
                    event["action"] = "restarted"
                    event["restarts"] = member.restarts
                    with self._lock:
                        self.restarts_total += 1
                except Exception as exc:  # noqa: BLE001 - ledger, not crash
                    event["action"] = "restart-failed"
                    event["error"] = str(exc)[:200]
        elif policy == "adopt":
            # The peer protocol absorbs the loss (a shard's lease expires
            # and the ring successor adopts its range). Respawning here
            # would RACE that adoption — record, don't act.
            event["action"] = "left-to-adoption"
            member.stopping = True      # don't re-ledger every tick
        else:
            event["action"] = "down"
            member.stopping = True
        with self._lock:
            self.events.append(event)

    def sample(self) -> None:
        """Fold current per-process VmRSS into each member's peak."""
        for member in self.members:
            if member.alive():
                member.rss_peak_mb = max(member.rss_peak_mb,
                                         rss_mb(member.proc.pid))

    def note_bound(self, bound: int) -> None:
        """Throughput sample from the driving harness's progress poll."""
        with self._lock:
            self._bound_samples.append((time.monotonic(), bound))

    # -- consolidated detail ------------------------------------------------

    def members_of(self, role: str) -> List[FleetMember]:
        return [m for m in self.members if m.role == role]

    def rss_peaks(self) -> Dict[str, object]:
        """Per-role peak-RSS map, shaped for the existing detail-line
        consumers (scalar leader, lists for the scaled-out roles)."""
        self.sample()
        hollows = self.members_of("hollow")
        ctrls = (self.members_of("controller") + self.members_of("workload")
                 + self.members_of("deschedule"))
        leader = self.members_of("apiserver")
        out: Dict[str, object] = {
            "apiserver": leader[0].rss_peak_mb if leader else 0.0,
            "shards": [m.rss_peak_mb for m in self.members_of("shard")],
            "followers": [m.rss_peak_mb for m in self.members_of("follower")],
        }
        if hollows:
            out["hollow"] = max(m.rss_peak_mb for m in hollows)
            out["hollow_members"] = [m.rss_peak_mb for m in hollows]
        if ctrls:
            out["controllers"] = [m.rss_peak_mb for m in ctrls]
        return out

    def detail(self) -> dict:
        """The one consolidated fleet line: stage timeline, per-member
        supervision state, per-role RSS peaks, restart ledger, and the
        bound-pod throughput window."""
        with self._lock:
            samples = list(self._bound_samples)
            events = list(self.events)
        rate = None
        if len(samples) >= 2:
            (t0, b0), (t1, b1) = samples[0], samples[-1]
            rate = {"bound": b1,
                    "window_s": round(t1 - t0, 2),
                    "pods_per_sec": round((b1 - b0) / (t1 - t0), 1)
                    if t1 > t0 else 0.0}
        return {
            "name": self.spec.name,
            "stages": list(self.stages),
            "members": [m.summary() for m in self.members],
            "rss_mb": self.rss_peaks(),
            "restarts": self.restarts_total,
            "events": events,
            "throughput": rate,
            "flightrec_artifacts": len(self.artifacts()),
        }

    # -- flight-record fan-out + teardown -----------------------------------

    def signal_flightrec(self) -> int:
        """SIGUSR2 fan-out: every live member with a dump handler writes
        its flight record / stats line NOW. Returns members signalled."""
        n = 0
        for member in self.members:
            if member.role in SIGUSR2_ROLES and member.alive():
                try:
                    member.proc.send_signal(signal.SIGUSR2)
                    n += 1
                except OSError:
                    continue
        return n

    def artifacts(self) -> List[str]:
        d = self.spec.flightrec_dir
        if not d or not os.path.isdir(d):
            return []
        return sorted(f for f in os.listdir(d)
                      if f.startswith("flightrec-") and f.endswith(".jsonl"))

    def _final_stats(self, member: FleetMember, marker: str):
        """Scan a stopped member's drained tail (newest first) for its
        final one-line JSON stats object."""
        time.sleep(0.1)  # let the drain thread swallow the stats line
        for line in reversed(list(member.tail or ())):
            if marker in line:
                try:
                    return json.loads(line)[marker]
                except (ValueError, KeyError):
                    return None
        return None

    def stop_member(self, member: FleetMember, kill: bool = False) -> None:
        member.stopping = True
        if member.proc is None or member.proc.poll() is not None:
            return
        if kill:
            member.proc.kill()
        else:
            member.proc.terminate()
        try:
            member.proc.wait(timeout=15)
        except Exception:  # noqa: BLE001
            member.proc.kill()

    def stop_hollow(self) -> Optional[dict]:
        """SIGTERM every hollow member and merge their final stats lines
        (counters summed; per-member breakdown under "members")."""
        hollows = self.members_of("hollow")
        if not hollows:
            return None
        for m in hollows:
            self.stop_member(m)
        per = [self._final_stats(m, "hollow_stats") for m in hollows]
        merged: dict = {}
        for stats in per:
            for k, v in (stats or {}).items():
                if k != "offset" and isinstance(v, (int, float)):
                    merged[k] = merged.get(k, 0) + v
        if len(per) > 1:
            merged["members"] = per
        return merged or None

    def stop_workload(self) -> Optional[list]:
        """SIGTERM the workload managers; per-process final stats."""
        managers = self.members_of("workload")
        if not managers:
            return None
        out = []
        for m in managers:
            self.stop_member(m)
            out.append(self._final_stats(m, "controller_stats"))
        return out

    def stop_deschedulers(self) -> Optional[list]:
        """SIGTERM the descheduler managers; per-process final stats."""
        managers = self.members_of("deschedule")
        if not managers:
            return None
        out = []
        for m in managers:
            self.stop_member(m)
            out.append(self._final_stats(m, "controller_stats"))
        return out

    def _teardown_procs(self) -> None:
        """Reverse-stage teardown: controllers → hollow → shards →
        followers → leader."""
        order = ("deschedule", "workload", "controller", "hollow", "shard",
                 "follower", "apiserver")
        for role in order:
            for m in self.members_of(role):
                self.stop_member(m)

    def stop(self) -> None:
        self._stopping.set()
        if self._supervisor is not None:
            self._supervisor.join(timeout=10)
            self._supervisor = None
        if self.spec.flightrec_dir:
            # Last flight records before the tree comes down — even a
            # member that never crashed leaves a fresh artifact.
            self.signal_flightrec()
            time.sleep(0.2)
        self._teardown_procs()
        if self._tmpdir:
            shutil.rmtree(self._tmpdir, ignore_errors=True)
            self._tmpdir = ""
