"""The fleet entrypoint:

    python -m kubernetes_tpu.fleet --spec fleet.json --pods N \
        [--nodes M] [--warm W] [--timeout S] [--measure-only]

Loads a FleetSpec, conducts the staged bring-up, drives N measured pods
through the plane (the shard harness's measured window — exactly-once
oracle, per-replica paged-plane counters, RSS peaks), and prints ONE
consolidated JSON detail line. Without ``--pods`` it brings the fleet up
and holds it until SIGTERM/SIGINT (a standing cluster to poke at),
printing the conductor detail line on teardown.
"""

from __future__ import annotations

import argparse
import json
import signal
import sys
import threading

from .spec import FleetSpec


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="kubernetes-tpu-fleet")
    ap.add_argument("--spec", required=True,
                    help="FleetSpec JSON file (docs/SCALE.md format)")
    ap.add_argument("--pods", type=int, default=0,
                    help="measured pods to drive through the fleet "
                         "(0 = bring up and hold until SIGTERM)")
    ap.add_argument("--nodes", type=int, default=0,
                    help="node count override (defaults to the spec's "
                         "hollow count; required for a hollow-less spec "
                         "with --pods)")
    ap.add_argument("--warm", type=int, default=128,
                    help="warm-up pods outside the measured window")
    ap.add_argument("--timeout", type=float, default=900.0)
    args = ap.parse_args(argv)

    spec = FleetSpec.load(args.spec).validate()
    if not args.pods:
        return _hold(spec)

    from ..shard.harness import run_sharded_cluster
    n_nodes = args.nodes or int((spec.hollow or {}).get("count", 0))
    if n_nodes <= 0:
        ap.error("--nodes is required when the spec has no hollow plane")
    out = run_sharded_cluster(
        spec.shards, n_nodes, args.pods, warm_pods=args.warm,
        timeout=args.timeout, spec=spec)
    print(json.dumps(out), flush=True)
    return 0 if out.get("all_bound") else 1


def _hold(spec: FleetSpec) -> int:
    from .conductor import FleetConductor

    conductor = FleetConductor(spec).start()
    # The ready line FIRST (spawn harnesses select()+readline on it).
    print(f"fleet up: {len(conductor.members)} members, leader "
          f"{conductor.base}", flush=True)
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    stop.wait()
    detail = conductor.detail()
    conductor.stop()
    print(json.dumps({"fleet": detail}), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
