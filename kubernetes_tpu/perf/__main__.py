"""CLI: run scheduler_perf workloads.

    python -m kubernetes_tpu.perf                      # all [performance]
    python -m kubernetes_tpu.perf --labels short       # CI subset
    python -m kubernetes_tpu.perf --scale 0.1          # scaled-down
    python -m kubernetes_tpu.perf --filter SchedulingBasic
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from .harness import load_config, run_workload

DEFAULT_CONFIG = os.path.join(os.path.dirname(__file__), "configs",
                              "performance-config.yaml")


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", default=DEFAULT_CONFIG)
    ap.add_argument("--labels", default="performance",
                    help="comma-separated label filter")
    ap.add_argument("--filter", default="", help="testcase/workload substring")
    ap.add_argument("--scale", type=float, default=1.0)
    args = ap.parse_args()

    labels = set(args.labels.split(",")) if args.labels else set()
    failed = 0
    for wl in load_config(args.config, scale=args.scale):
        if labels and not labels & set(wl.labels):
            continue
        full = f"{wl.testcase}/{wl.name}"
        if args.filter and args.filter not in full:
            continue
        res = run_workload(wl)
        ok = res.meets_thresholds()
        failed += 0 if ok else 1
        print(json.dumps({
            "workload": full,
            "ok": ok,
            "scheduled": res.scheduled,
            "failed_attempts": res.failed,
            "elapsed_s": round(res.elapsed, 2),
            "thresholds": wl.thresholds,
            "metrics": {k: {kk: round(vv, 1) for kk, vv in v.items()}
                        for k, v in res.metrics.items()},
        }))
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
