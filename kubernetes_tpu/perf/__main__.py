"""Run the scheduler_perf workload table.

    python -m kubernetes_tpu.perf                      # all [performance]
    python -m kubernetes_tpu.perf --labels short       # CI subset
    python -m kubernetes_tpu.perf --scale 0.1          # scaled-down
    python -m kubernetes_tpu.perf --only SchedulingBasic --out PERF.json

Each workload runs in a fresh TPUScheduler (shared process: the jit cache and
the persistent XLA compilation cache amortize compiles across workloads).
With --out, results stream to the file after every workload so partial runs
are usable.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
import traceback

from .harness import load_config, run_workload

DEFAULT_CONFIG = os.path.join(os.path.dirname(__file__), "configs",
                              "performance-config.yaml")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", default=DEFAULT_CONFIG)
    ap.add_argument("--labels", default="performance",
                    help="comma-separated label filter (empty = all)")
    ap.add_argument("--only", "--filter", dest="only", default="",
                    help="TESTCASE or TESTCASE/WORKLOAD substring filter")
    ap.add_argument("--scale", type=float, default=1.0)
    ap.add_argument("--out", default="")
    args = ap.parse_args(argv)

    wanted = [s for s in args.labels.split(",") if s]
    wls = load_config(args.config, scale=args.scale)
    if wanted:
        wls = [w for w in wls if all(lb in w.labels for lb in wanted)]
    if args.only:
        wls = [w for w in wls if args.only in f"{w.testcase}/{w.name}"]

    results = []
    meta = {
        "config": args.config,
        "scale": args.scale,
        "platform": os.environ.get("JAX_PLATFORMS", "default"),
        "started": time.strftime("%Y-%m-%dT%H:%M:%S"),
    }
    below = 0
    for wl in wls:
        key = f"{wl.testcase}/{wl.name}"
        t0 = time.perf_counter()
        entry = {"workload": key,
                 "threshold": wl.thresholds.get("SchedulingThroughput")}
        try:
            res = run_workload(wl)
            tp = res.metrics.get("SchedulingThroughput", {})
            avg = tp.get("Average", 0.0)
            thr = entry["threshold"] or 0
            entry.update({
                "pods_per_second": round(avg, 1),
                "vs_baseline": round(avg / thr, 2) if thr else None,
                "meets_threshold": res.meets_thresholds(),
                "percentiles": {k: round(v, 1) for k, v in tp.items()},
                "scheduled": res.scheduled,
                "failed_attempts": res.failed,
                "wall_s": round(time.perf_counter() - t0, 1),
                "detail": res.detail,
            })
            below += 0 if res.meets_thresholds() else 1
        except Exception as e:  # noqa: BLE001
            entry.update({"error": repr(e),
                          "trace": traceback.format_exc(limit=4),
                          "wall_s": round(time.perf_counter() - t0, 1)})
            below += 1
        results.append(entry)
        print(json.dumps(entry), flush=True)
        if args.out:
            with open(args.out, "w") as f:
                json.dump({"meta": meta, "results": results}, f, indent=1)
    ok = sum(1 for r in results if r.get("meets_threshold"))
    print(f"# {ok}/{len(results)} workloads met their thresholds", flush=True)
    return 1 if below else 0


if __name__ == "__main__":
    sys.exit(main())
