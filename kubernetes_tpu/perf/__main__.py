"""Run the scheduler_perf workload table.

    python -m kubernetes_tpu.perf                      # all [performance]
    python -m kubernetes_tpu.perf --labels short       # CI subset
    python -m kubernetes_tpu.perf --scale 0.1          # scaled-down
    python -m kubernetes_tpu.perf --only SchedulingBasic --out PERF.json

Each workload runs in a fresh TPUScheduler (shared process: the jit cache and
the persistent XLA compilation cache amortize compiles across workloads).
With --out, results stream to the file after every workload so partial runs
are usable.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
import traceback

from .harness import load_config, run_workload

DEFAULT_CONFIG = os.path.join(os.path.dirname(__file__), "configs",
                              "performance-config.yaml")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", default=DEFAULT_CONFIG)
    ap.add_argument("--labels", default="performance",
                    help="comma-separated label filter (empty = all)")
    ap.add_argument("--only", "--filter", dest="only", default="",
                    help="TESTCASE or TESTCASE/WORKLOAD substring filter")
    ap.add_argument("--scale", type=float, default=1.0)
    ap.add_argument("--out", default="")
    ap.add_argument("--runs", type=int, default=1,
                    help="consecutive full-table runs; per-workload results "
                         "report every run + the worst (the reference "
                         "asserts floors per CI run, so one quiet pass is "
                         "not evidence — VERDICT r3 weakness 3)")
    args = ap.parse_args(argv)

    wanted = [s for s in args.labels.split(",") if s]
    wls = load_config(args.config, scale=args.scale)
    if wanted:
        wls = [w for w in wls if all(lb in w.labels for lb in wanted)]
    if args.only:
        wls = [w for w in wls if args.only in f"{w.testcase}/{w.name}"]

    results = []
    meta = {
        "config": args.config,
        "scale": args.scale,
        "platform": os.environ.get("JAX_PLATFORMS", "default"),
        "started": time.strftime("%Y-%m-%dT%H:%M:%S"),
    }
    meta["runs"] = args.runs
    below = 0
    by_key = {}
    for run_i in range(args.runs):
        for wl in wls:
            key = f"{wl.testcase}/{wl.name}"
            t0 = time.perf_counter()
            entry = by_key.get(key)
            if entry is None:
                entry = by_key[key] = {
                    "workload": key,
                    "threshold": wl.thresholds.get("SchedulingThroughput"),
                    "runs": [],
                }
                results.append(entry)
            thr = entry["threshold"] or 0
            # Thresholds gate performance-, hollow-, and flood-labeled
            # workloads — the SAME label gate as
            # harness.PerfResult.meets_thresholds (scheduler_perf.go:
            # 282-368); hollow rows carry Max* RSS/unpaged-LIST ceilings
            # and flood rows FloodSheds/MaxFloodErrors floors that must
            # assert here too.
            asserted = bool({"performance", "hollow", "flood"}
                            & set(wl.labels))
            try:
                res = run_workload(wl)
                tp = res.metrics.get("SchedulingThroughput", {})
                avg = tp.get("Average", 0.0)
                entry["runs"].append(round(avg, 1))
                # Non-throughput thresholds (HintHitRate floor, Max*
                # ceilings) assert per run too — every run must clear them.
                for name, bound in wl.thresholds.items():
                    if name == "SchedulingThroughput" or not asserted:
                        continue
                    got = res.metrics.get(name, {}).get("Average", 0.0)
                    run_ok = (got <= bound if name.startswith("Max")
                              else got >= bound)
                    entry["other_thresholds_ok"] = (
                        entry.get("other_thresholds_ok", True) and run_ok)
                if run_i == 0:
                    entry.update({
                        "percentiles": {k: round(v, 1) for k, v in tp.items()},
                        "scheduled": res.scheduled,
                        "failed_attempts": res.failed,
                        "wall_s": round(time.perf_counter() - t0, 1),
                        "detail": res.detail,
                    })
                    extras = {k: v for k, v in res.metrics.items()
                              if k != "SchedulingThroughput"}
                    if extras:
                        entry["metrics"] = extras
            except Exception as e:  # noqa: BLE001
                entry["runs"].append(0.0)
                entry.update({"error": repr(e),
                              "trace": traceback.format_exc(limit=4)})
            # the WORST run is the claim (floors assert per run)
            worst = min(entry["runs"]) if entry["runs"] else 0.0
            entry["pods_per_second"] = worst
            entry["vs_baseline"] = round(worst / thr, 2) if thr else None
            entry["meets_threshold"] = (
                "error" not in entry
                and (not asserted or not thr or worst >= thr)
                and entry.get("other_thresholds_ok", True))
            print(json.dumps({"run": run_i + 1, "workload": key,
                              "pods_per_second": entry["runs"][-1],
                              "worst": worst}), flush=True)
            if args.out:
                with open(args.out, "w") as f:
                    json.dump({"meta": meta, "results": results}, f, indent=1)
    below = sum(1 for r in results if not r.get("meets_threshold"))
    ok = sum(1 for r in results if r.get("meets_threshold"))
    print(f"# {ok}/{len(results)} workloads met their thresholds", flush=True)
    return 1 if below else 0


if __name__ == "__main__":
    sys.exit(main())
