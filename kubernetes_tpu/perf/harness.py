"""The scheduler_perf opcode interpreter.

Config format (mirrors test/integration/scheduler_perf/*/performance-config.yaml):

    - name: SchedulingBasic
      defaultPodTemplate: &pod
        cpu: 100m
        memory: 128Mi
      workloadTemplate:
      - opcode: createNodes
        countParam: $nodes
        nodeTemplate: {cpu: 32, memory: 256Gi, pods: 110, zones: 50}
      - opcode: createPods
        countParam: $measurePods
        podTemplate: *pod
        collectMetrics: true
      workloads:
      - name: 5000Nodes_10000Pods
        labels: [performance]
        params: {nodes: 5000, measurePods: 10000}
        thresholds: {SchedulingThroughput: 680}

Opcodes: createNodes, createPods, createPodGroups, churn, barrier, sleep,
startCollectingMetrics/stopCollectingMetrics (scheduler_perf.go:64-80).
`barrier` drains the scheduler, sampling throughput; createPods with
collectMetrics wraps itself in start/barrier implicitly, as the reference
does for measured pods.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence

import yaml

from ..api.types import PodGroup
from ..core.scheduler import Scheduler
from ..testing.wrappers import make_node, make_pod

ZONE = "topology.kubernetes.io/zone"
HOSTNAME = "kubernetes.io/hostname"


@dataclass
class Workload:
    name: str
    testcase: str
    labels: List[str]
    params: Dict[str, Any]
    thresholds: Dict[str, float]
    ops: List[Dict[str, Any]]
    default_pod_template: Optional[Dict[str, Any]] = None


@dataclass
class PerfResult:
    workload: Workload
    scheduled: int = 0
    failed: int = 0
    elapsed: float = 0.0
    metrics: Dict[str, Dict[str, float]] = field(default_factory=dict)
    # Device/host attribution (TPUScheduler counters; zero on host-only runs):
    # which path the pods took and where the wall-clock went.
    detail: Dict[str, Any] = field(default_factory=dict)

    def meets_thresholds(self) -> bool:
        """Thresholds gate `performance`-labeled runs only — the reference
        asserts them on perf hardware, not on integration-test variants
        (scheduler_perf.go:282-368 / misc/performance-config.yaml:1-19)."""
        if "performance" not in self.workload.labels:
            return True
        for name, floor in self.workload.thresholds.items():
            got = self.metrics.get(name, {}).get("Average", 0.0)
            if got < floor:
                return False
        return True


def load_config(path: str, scale: float = 1.0) -> List[Workload]:
    """Load testcases → one Workload per (testcase, workload) pair.
    `scale` multiplies every count param (CI runs scaled-down clusters;
    thresholds scale linearly with the count scale)."""
    with open(path) as f:
        testcases = yaml.safe_load(f)
    out: List[Workload] = []
    for tc in testcases:
        for wl in tc.get("workloads", ()):
            params = dict(wl.get("params", {}))
            if scale != 1.0:
                params = {k: max(1, int(v * scale)) if isinstance(v, int) else v
                          for k, v in params.items()}
            thresholds = {
                k: v * scale if scale != 1.0 else v
                for k, v in wl.get("thresholds", {}).items()}
            out.append(Workload(
                name=wl["name"],
                testcase=tc["name"],
                labels=list(wl.get("labels", ())),
                params=params,
                thresholds=thresholds,
                ops=tc.get("workloadTemplate", []),
                default_pod_template=tc.get("defaultPodTemplate"),
            ))
    return out


def _resolve_count(op: Dict[str, Any], params: Dict[str, Any]) -> int:
    if "count" in op:
        return int(op["count"])
    ref = op.get("countParam", "")
    return int(params[ref.lstrip("$")])


class _ThroughputCollector:
    """SchedulingThroughput (util.go:477): samples pods-scheduled per
    interval while collecting; summarizes Average + percentiles."""

    INTERVAL = 0.1

    def __init__(self, sched: Scheduler):
        self.sched = sched
        self.samples: List[float] = []
        self._last_t = 0.0
        self._last_n = 0
        self._t0 = 0.0
        self._n0 = 0
        self.active = False

    def start(self) -> None:
        self.active = True
        self._t0 = self._last_t = time.perf_counter()
        self._n0 = self._last_n = self.sched.scheduled

    def tick(self) -> None:
        if not self.active:
            return
        now = time.perf_counter()
        if now - self._last_t >= self.INTERVAL:
            rate = (self.sched.scheduled - self._last_n) / (now - self._last_t)
            self.samples.append(rate)
            self._last_t, self._last_n = now, self.sched.scheduled

    def stop(self) -> Dict[str, float]:
        self.active = False
        elapsed = time.perf_counter() - self._t0
        total = self.sched.scheduled - self._n0
        avg = total / elapsed if elapsed > 0 else 0.0
        s = sorted(self.samples) or [avg]

        def pct(q: float) -> float:
            return s[min(len(s) - 1, int(q * len(s)))]

        return {"Average": avg, "Perc50": pct(0.50), "Perc90": pct(0.90),
                "Perc95": pct(0.95), "Perc99": pct(0.99)}


def _make_node_from_template(i: int, tpl: Dict[str, Any]):
    zones = int(tpl.get("zones", 0))
    b = make_node().name(f"node-{i}").capacity({
        "cpu": tpl.get("cpu", 32),
        "memory": tpl.get("memory", "256Gi"),
        "pods": tpl.get("pods", 110),
    })
    if zones:
        b = b.zone(f"zone-{i % zones}")
    for k, v in tpl.get("labels", {}).items():
        b = b.label(k, v)
    for t in tpl.get("taints", ()):
        b = b.taint(t["key"], t.get("value", ""), t.get("effect", "NoSchedule"))
    return b.obj()


def _make_pod_from_template(name: str, tpl: Dict[str, Any]):
    b = make_pod().name(name).req({
        "cpu": tpl.get("cpu", "100m"), "memory": tpl.get("memory", "128Mi")})
    for k, v in tpl.get("labels", {}).items():
        b = b.label(k, v)
    if tpl.get("nodeSelector"):
        b = b.node_selector(dict(tpl["nodeSelector"]))
    for tol in tpl.get("tolerations", ()):
        b = b.toleration(tol["key"], tol.get("value", ""),
                         tol.get("operator", "Equal"), tol.get("effect", ""))
    for c in tpl.get("topologySpreadConstraints", ()):
        b = b.spread_constraint(
            c.get("maxSkew", 1),
            c.get("topologyKey", ZONE),
            c.get("whenUnsatisfiable", "DoNotSchedule"),
            c.get("labelSelector", tpl.get("labels", {})))
    aff = tpl.get("podAntiAffinity")
    if aff:
        b = b.pod_affinity(aff.get("topologyKey", HOSTNAME),
                           aff.get("matchLabels", tpl.get("labels", {})),
                           anti=True, weight=aff.get("weight", 0))
    aff = tpl.get("podAffinity")
    if aff:
        b = b.pod_affinity(aff.get("topologyKey", ZONE),
                           aff.get("matchLabels", tpl.get("labels", {})),
                           weight=aff.get("weight", 0))
    if tpl.get("priority"):
        b = b.priority(int(tpl["priority"]))
    pod = b.obj()
    if tpl.get("podGroup"):
        pod.pod_group = tpl["podGroup"]
    return pod


def _drain(sched: Scheduler, collector: _ThroughputCollector, max_cycles: int = 10_000_000) -> None:
    """barrier opcode: drive scheduling until the queue stops yielding."""
    n = 0
    while n < max_cycles:
        progressed = sched.schedule_one()
        collector.tick()
        if not progressed:
            sched.queue.flush_backoff_completed()
            if not sched.schedule_one():
                break
        n += 1


def run_workload(wl: Workload, sched: Optional[Scheduler] = None) -> PerfResult:
    """Execute one workload's opcode list (the RunBenchmarkPerfScheduling
    inner loop, scheduler_perf.go:282+)."""
    from ..models.tpu_scheduler import TPUScheduler

    sched = sched or TPUScheduler()
    cs = sched.clientset
    collector = _ThroughputCollector(sched)
    params = wl.params
    pod_seq = 0
    result = PerfResult(workload=wl)
    t0 = time.perf_counter()

    for op in wl.ops:
        opcode = op["opcode"]
        if opcode == "createNodes":
            count = _resolve_count(op, params)
            tpl = op.get("nodeTemplate", {})
            for i in range(count):
                cs.create_node(_make_node_from_template(i, tpl))
        elif opcode == "createPods":
            count = _resolve_count(op, params)
            tpl = op.get("podTemplate") or wl.default_pod_template or {}
            collect = bool(op.get("collectMetrics"))
            if collect:
                # Compile the kernel shapes outside the measured window
                # (the reference's measured runs start against a warm
                # scheduler process; XLA compilation is our cold-start).
                warm = getattr(sched, "warm_for", None)
                if warm is not None:
                    warm(_make_pod_from_template("warm-template", tpl))
                collector.start()
            for i in range(count):
                cs.create_pod(_make_pod_from_template(f"pod-{pod_seq}", tpl))
                pod_seq += 1
            _drain(sched, collector)
            if collect:
                result.metrics["SchedulingThroughput"] = collector.stop()
        elif opcode == "createPodGroups":
            count = _resolve_count(op, params)
            size = int(op.get("groupSize", 2))
            tpl = dict(op.get("podTemplate") or wl.default_pod_template or {})
            for g in range(count):
                name = f"group-{g}"
                cs.create_pod_group(PodGroup(name=name, min_count=size))
                tpl_g = dict(tpl, podGroup=name)
                for i in range(size):
                    cs.create_pod(_make_pod_from_template(f"pod-{pod_seq}", tpl_g))
                    pod_seq += 1
            _drain(sched, collector)
        elif opcode == "churn":
            # simplified: n create→schedule→delete rounds (scheduler_perf.go:72)
            rounds = int(op.get("number", 10))
            tpl = op.get("podTemplate") or wl.default_pod_template or {}
            for i in range(rounds):
                p = _make_pod_from_template(f"churn-{i}", tpl)
                cs.create_pod(p)
                _drain(sched, collector)
                cs.delete_pod(p)
        elif opcode == "barrier":
            _drain(sched, collector)
        elif opcode == "sleep":
            time.sleep(float(op.get("duration", 0.1)))
        elif opcode == "startCollectingMetrics":
            collector.start()
        elif opcode == "stopCollectingMetrics":
            result.metrics["SchedulingThroughput"] = collector.stop()
        else:
            raise ValueError(f"unknown opcode {opcode!r}")

    result.elapsed = time.perf_counter() - t0
    result.scheduled = sched.scheduled
    result.failed = sched.failures
    for attr in ("device_batches", "device_scheduled", "host_path_pods",
                 "plan_build_s", "device_wait_s", "host_commit_s"):
        v = getattr(sched, attr, None)
        if v is not None:
            result.detail[attr] = round(v, 3) if isinstance(v, float) else v
    # in-flight invariant (scheduler_perf.go:878-880 checkEmptyInFlightEvents)
    assert not sched.queue._in_flight, "in-flight events remain after workload"
    return result
