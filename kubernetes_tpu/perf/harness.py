"""The scheduler_perf opcode interpreter.

Config format (mirrors test/integration/scheduler_perf/*/performance-config.yaml):

    - name: SchedulingBasic
      defaultPodTemplate: &pod
        cpu: 100m
        memory: 128Mi
      workloadTemplate:
      - opcode: createNodes
        countParam: $nodes
        nodeTemplate: {cpu: 32, memory: 256Gi, pods: 110, zones: 50}
      - opcode: createPods
        countParam: $measurePods
        podTemplate: *pod
        collectMetrics: true
      workloads:
      - name: 5000Nodes_10000Pods
        labels: [performance]
        params: {nodes: 5000, measurePods: 10000}
        thresholds: {SchedulingThroughput: 680}

Opcodes: createNodes, createPods, createPodGroups, churn, barrier, sleep,
startCollectingMetrics/stopCollectingMetrics (scheduler_perf.go:64-80).
`barrier` drains the scheduler, sampling throughput; createPods with
collectMetrics wraps itself in start/barrier implicitly, as the reference
does for measured pods.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence

import yaml

from ..api.types import Namespace, PodGroup, Volume
from ..core.scheduler import Scheduler
from ..testing.wrappers import make_node, make_pod

ZONE = "topology.kubernetes.io/zone"
HOSTNAME = "kubernetes.io/hostname"


@dataclass
class Workload:
    name: str
    testcase: str
    labels: List[str]
    params: Dict[str, Any]
    thresholds: Dict[str, float]
    ops: List[Dict[str, Any]]
    default_pod_template: Optional[Dict[str, Any]] = None
    # Per-workload featureGates (misc/performance-config.yaml:65-81 variant
    # style) and the simulated apiserver round-trip for the watch-seam
    # transport (core/remote.py); 0 = in-process clientset.
    feature_gates: Dict[str, bool] = field(default_factory=dict)
    api_rtt_ms: float = 0.0


@dataclass
class PerfResult:
    workload: Workload
    scheduled: int = 0
    failed: int = 0
    elapsed: float = 0.0
    metrics: Dict[str, Dict[str, float]] = field(default_factory=dict)
    # Device/host attribution (TPUScheduler counters; zero on host-only runs):
    # which path the pods took and where the wall-clock went.
    detail: Dict[str, Any] = field(default_factory=dict)

    def meets_thresholds(self) -> bool:
        """Thresholds gate `performance`-, `hollow`-, and `flood`-labeled
        runs only — the reference asserts them on perf hardware, not on
        integration-test variants (scheduler_perf.go:282-368 /
        misc/performance-config.yaml:1-19). `flood` rows assert their
        overload floors (FloodSheds/MaxFloodErrors) wherever they run —
        they ARE the scenario's acceptance contract. A threshold named
        ``Max*`` is a CEILING (e.g. MaxApiserverRssMb — the
        bounded-memory floor of the paged read plane); everything else
        is a floor."""
        if not {"performance", "hollow", "flood"} & set(self.workload.labels):
            return True
        for name, bound in self.workload.thresholds.items():
            got = self.metrics.get(name, {}).get("Average", 0.0)
            if name.startswith("Max"):
                if got > bound:
                    return False
            elif got < bound:
                return False
        return True


def load_config(path: str, scale: float = 1.0) -> List[Workload]:
    """Load testcases → one Workload per (testcase, workload) pair.
    `scale` multiplies every count param (CI runs scaled-down clusters;
    thresholds scale linearly with the count scale)."""
    with open(path) as f:
        testcases = yaml.safe_load(f)
    out: List[Workload] = []
    for tc in testcases:
        for wl in tc.get("workloads", ()):
            params = dict(wl.get("params", {}))
            if scale != 1.0:
                # `shards` is topology, not load — scaling it would silently
                # turn a sharded workload into a single-scheduler one.
                params = {k: (v if k == "shards"
                              else max(1, int(v * scale)))
                          if isinstance(v, int) else v
                          for k, v in params.items()}
            thresholds = {
                k: v * scale if scale != 1.0 else v
                for k, v in wl.get("thresholds", {}).items()}
            gates = dict(tc.get("featureGates", {}))
            gates.update(wl.get("featureGates", {}))
            out.append(Workload(
                name=wl["name"],
                testcase=tc["name"],
                labels=list(wl.get("labels", ())),
                params=params,
                thresholds=thresholds,
                ops=tc.get("workloadTemplate", []),
                default_pod_template=tc.get("defaultPodTemplate"),
                feature_gates=gates,
                api_rtt_ms=float(wl.get("apiRttMs", tc.get("apiRttMs", 0.0))),
            ))
    return out


def _resolve_count(op: Dict[str, Any], params: Dict[str, Any]) -> int:
    if "count" in op:
        return int(op["count"])
    ref = op.get("countParam", "")
    return int(params[ref.lstrip("$")])


class _ThroughputCollector:
    """SchedulingThroughput (util.go:477): samples pods-scheduled per
    interval while collecting; summarizes Average + percentiles."""

    INTERVAL = 0.1

    def __init__(self, sched: Scheduler):
        self.sched = sched
        self.samples: List[float] = []
        self._last_t = 0.0
        self._last_n = 0
        self._t0 = 0.0
        self._n0 = 0
        self.active = False

    WINDOW_COUNTERS = ("plan_build_s", "device_wait_s", "host_commit_s",
                       "device_scheduled", "host_path_pods", "device_batches",
                       "plan_rebuilds_full", "plan_rebuilds_delta",
                       "plan_rebuilds_resume", "delta_dirty_rows",
                       "hint_hits", "hint_misses", "hint_invalidations")

    def start(self) -> None:
        self.active = True
        self._t0 = self._last_t = time.perf_counter()
        self._n0 = self._last_n = self.sched.scheduled
        self._win0 = {a: getattr(self.sched, a, 0) for a in self.WINDOW_COUNTERS}
        self.in_window: Dict[str, float] = {}

    def tick(self) -> None:
        if not self.active:
            return
        now = time.perf_counter()
        if now - self._last_t >= self.INTERVAL:
            rate = (self.sched.scheduled - self._last_n) / (now - self._last_t)
            self.samples.append(rate)
            self._last_t, self._last_n = now, self.sched.scheduled

    def stop(self) -> Dict[str, float]:
        self.active = False
        elapsed = time.perf_counter() - self._t0
        total = self.sched.scheduled - self._n0
        # In-window attribution: the share of the MEASURED window each
        # pipeline stage took (the workload-cumulative counters in `detail`
        # also cover setup/warm phases and cannot attribute the window).
        self.in_window = {"window_s": round(elapsed, 3)}
        for a in self.WINDOW_COUNTERS:
            v = getattr(self.sched, a, None)
            if v is not None:
                d = v - self._win0.get(a, 0)
                self.in_window[a] = round(d, 3) if isinstance(d, float) else d
        # Window scheduled count + hint-hit rate (share of the window's
        # pods bound via the score-hint fast path — the
        # HomogeneousReplicaSurge threshold's denominator).
        self.window_scheduled = total
        avg = total / elapsed if elapsed > 0 else 0.0
        s = sorted(self.samples) or [avg]

        def pct(q: float) -> float:
            return s[min(len(s) - 1, int(q * len(s)))]

        return {"Average": avg, "Perc50": pct(0.50), "Perc90": pct(0.90),
                "Perc95": pct(0.95), "Perc99": pct(0.99)}


def _record_hint_hit_rate(result: "PerfResult",
                          collector: _ThroughputCollector) -> None:
    """HintHitRate metric (HomogeneousReplicaSurge threshold): the share of
    the measured window's scheduled pods bound through the score-hint fast
    path (models/score_hints.py) — zero/absent on host-only schedulers."""
    hits = collector.in_window.get("hint_hits")
    if hits is None:
        return
    rate = hits / max(1, getattr(collector, "window_scheduled", 0))
    result.metrics["HintHitRate"] = {"Average": round(rate, 4)}


def _make_node_from_template(i: int, tpl: Dict[str, Any]):
    zones = int(tpl.get("zones", 0))
    cap = {
        "cpu": tpl.get("cpu", 32),
        "memory": tpl.get("memory", "256Gi"),
        "pods": tpl.get("pods", 110),
    }
    # extended/scalar resources (node-with-extended-resource.yaml shape)
    cap.update(tpl.get("extended", {}))
    b = make_node().name(tpl.get("name", f"node-{i}")).capacity(cap)
    if zones:
        b = b.zone(f"zone-{i % zones}")
    for k, v in tpl.get("labels", {}).items():
        b = b.label(k, v)
    for t in tpl.get("taints", ()):
        b = b.taint(t["key"], t.get("value", ""), t.get("effect", "NoSchedule"))
    for img in tpl.get("images", ()):
        b = b.image(img["name"], int(img.get("sizeBytes", 0)))
    node = b.obj()
    nf = int(tpl.get("declaredFeatures", 0))
    if nf:
        node.declared_features = {f"feature-{j}": True for j in range(nf)}
    return node


# Template → prototype pod. Building a pod from a template parses resource
# quantities and assembles spec objects (~20µs); a createPods op stamps tens
# of thousands of IDENTICAL pods inside the measured window, so the spec is
# built once and each instance is a cheap identity clone sharing the spec and
# the signature memo (Pod.clone_from_template). Keyed by template-dict
# identity (the strong ref in the entry keeps the id stable); pvc templates
# have per-pod volume names and always take the full build path.
_POD_PROTO_CACHE: Dict[Tuple[int, str], Tuple[Dict[str, Any], Any]] = {}


def _make_pod_from_template(name: str, tpl: Dict[str, Any], namespace: str = "default"):
    if not tpl.get("pvc"):
        key = (id(tpl), namespace)
        ent = _POD_PROTO_CACHE.get(key)
        if ent is not None and ent[0] is tpl:
            return ent[1].clone_from_template(name)
        if len(_POD_PROTO_CACHE) > 4096:  # bound gang-workload growth
            _POD_PROTO_CACHE.clear()
        proto = _build_pod_from_template("proto", tpl, namespace)
        _POD_PROTO_CACHE[key] = (tpl, proto)
        return proto.clone_from_template(name)
    return _build_pod_from_template(name, tpl, namespace)


def _build_pod_from_template(name: str, tpl: Dict[str, Any], namespace: str = "default"):
    req = {"cpu": tpl.get("cpu", "100m"), "memory": tpl.get("memory", "128Mi")}
    req.update(tpl.get("extended", {}))  # extended-resource requests
    b = make_pod().name(name).namespace(namespace).req(req)
    for k, v in tpl.get("labels", {}).items():
        b = b.label(k, v)
    if tpl.get("nodeSelector"):
        b = b.node_selector(dict(tpl["nodeSelector"]))
    for tol in tpl.get("tolerations", ()):
        b = b.toleration(tol["key"], tol.get("value", ""),
                         tol.get("operator", "Equal"), tol.get("effect", ""))
    for c in tpl.get("topologySpreadConstraints", ()):
        b = b.spread_constraint(
            c.get("maxSkew", 1),
            c.get("topologyKey", ZONE),
            c.get("whenUnsatisfiable", "DoNotSchedule"),
            c.get("labelSelector", tpl.get("labels", {})),
            node_taints_policy=c.get("nodeTaintsPolicy", "Ignore"))
    for kind, anti in (("podAntiAffinity", True), ("podAffinity", False)):
        aff = tpl.get(kind)
        if aff:
            b = b.pod_affinity(
                aff.get("topologyKey", HOSTNAME if anti else ZONE),
                aff.get("matchLabels", tpl.get("labels", {})),
                anti=anti, weight=aff.get("weight", 0),
                ns_labels=aff.get("namespaceSelector"))
    na = tpl.get("nodeAffinityIn")
    if na:
        b = b.node_affinity_in(na["key"], list(na["values"]))
    pna = tpl.get("preferredNodeAffinity")
    if pna:
        b = b.preferred_node_affinity(
            int(pna.get("weight", 1)), pna["key"], list(pna["values"]))
    if tpl.get("nodeAffinityName"):
        # daemonset-pod.yaml shape: matchFields metadata.name In [node]
        b = b.node_affinity_name(tpl["nodeAffinityName"])
    if tpl.get("hostPort"):
        b = b.host_port(int(tpl["hostPort"]))
    for g in tpl.get("schedulingGates", ()):
        b = b.scheduling_gate(g)
    if tpl.get("image"):
        b = b.image(tpl["image"])
    if tpl.get("priority"):
        b = b.priority(int(tpl["priority"]))
    pod = b.obj()
    if tpl.get("requiredFeatures"):
        nf = int(tpl["requiredFeatures"])
        pod.annotations["features.k8s.io/required"] = ",".join(
            f"feature-{j}" for j in range(nf))
    if tpl.get("finalizers"):
        pod.finalizers = list(tpl["finalizers"])
    for j in range(int(tpl.get("secretVolumes", 0))):
        pod.volumes.append(Volume(name=f"secret-{j}"))
    if tpl.get("pvc"):
        pod.volumes.append(Volume(name="data", pvc_name=tpl["pvc"].format(name=name)))
    if tpl.get("podGroup"):
        pod.pod_group = tpl["podGroup"]
    return pod


class _ThreadedCreator:
    """createPods with a concurrent client: the reference's createPodsOp
    issues creates from the test client while the scheduler schedules
    (scheduler_perf.go createPodsOp → client-go rate-limited creates); here a
    creator thread writes through the clientset and the scheduler's
    off-thread event inbox (Scheduler._threaded) replays the adds on the
    scheduling loop — creation overlaps the measured window instead of
    serializing in front of it."""

    blocks_idle = True  # _drain must not exit while creates are in flight

    def __init__(self, fn):
        import threading
        self._exc: Optional[BaseException] = None

        def run():
            try:
                fn()
            except BaseException as e:  # noqa: BLE001 - re-raised on main thread
                self._exc = e

        self._thread = threading.Thread(target=run, daemon=True)
        self._thread.start()

    def tick(self) -> bool:
        if self._exc is not None:
            # A failed create op must fail the workload (synchronous creates
            # propagated); surface the creator-thread exception here.
            raise self._exc
        return self._thread.is_alive()


class _RateDeleter:
    """deletePods opcode with skipWaitToCompletion: deletes pods at a fixed
    rate CONCURRENTLY with the measured scheduling window (the reference
    runs this in a goroutine — scheduler_perf.go deletePodsOp)."""

    def __init__(self, cs, pods: List, per_second: float, now=time.perf_counter):
        self.cs = cs
        self.pods = list(pods)
        self.per_second = max(per_second, 1e-9)
        self.now = now
        self._t0 = now()
        self._done = 0

    def tick(self) -> bool:
        due = int((self.now() - self._t0) * self.per_second)
        while self._done < min(due, len(self.pods)):
            self.cs.delete_pod(self.pods[self._done])
            self._done += 1
        return self._done < len(self.pods)


class _Churner:
    """churn opcode (scheduler_perf.go:72): every interval, create/delete (or
    recreate) objects WHILE the measured window runs — exercising mid-session
    invalidations, queue moves, and device-mirror refreshes for real."""

    def __init__(self, cs, pod_tpl: Dict[str, Any], number: int,
                 interval_ms: float, mode: str = "recreate",
                 churn_nodes: bool = False, now=time.perf_counter):
        self.cs = cs
        self.pod_tpl = pod_tpl
        self.number = number
        self.interval = max(interval_ms, 1.0) / 1000.0
        self.mode = mode
        self.churn_nodes = churn_nodes
        self.now = now
        self._next = now()
        self._seq = 0
        self._live_pods: List = []
        self._live_nodes: List = []

    def tick(self) -> bool:
        while self.now() >= self._next:
            self._next += self.interval
            self._seq += 1
            p = _make_pod_from_template(f"churn-pod-{self._seq}", self.pod_tpl)
            self.cs.create_pod(p)
            self._live_pods.append(p)
            if self.churn_nodes:
                n = _make_node_from_template(0, {"name": f"churn-node-{self._seq}"})
                self.cs.create_node(n)
                self._live_nodes.append(n)
            if self.mode == "recreate" and len(self._live_pods) > self.number:
                self.cs.delete_pod(self._live_pods.pop(0))
                if len(self._live_nodes) > self.number:
                    self.cs.delete_node(self._live_nodes.pop(0).name)
        return True  # churns for the whole workload


def _drain(sched: Scheduler, collector: _ThroughputCollector,
           tickers: Optional[List] = None, max_cycles: int = 10_000_000) -> None:
    """barrier opcode: drive scheduling until the queue stops yielding.
    Active tickers (churners, rate deleters) run interleaved with the
    scheduling loop — i.e. concurrently with the measured window."""
    n = 0
    tickers = tickers if tickers is not None else []
    while n < max_cycles:
        for t in list(tickers):
            if not t.tick():
                tickers.remove(t)
        progressed = sched.schedule_one()
        collector.tick()
        if not progressed:
            sched.queue.flush_backoff_completed()
            sched.flush_expired_waiters()
            if not sched.schedule_one():
                if any(getattr(t, "blocks_idle", False) for t in tickers):
                    # A creator thread is still writing: wait for its events
                    # instead of declaring the queue drained.
                    sched.drain_event_inbox() or time.sleep(0.0002)
                    continue
                break
        n += 1


def _warm_group_shapes(sched, cs, wl: Workload, start_op) -> None:
    """Warm device kernel tiers for createPodGroups ops that run inside the
    upcoming measured window: the plain session tier for default-algorithm
    gangs, and the stacked placement tier for topology-constrained ones."""
    warm = getattr(sched, "warm_for", None)
    if warm is None:
        return
    started = False
    for op in wl.ops:
        if op is start_op:
            started = True
            continue
        if not started or op.get("opcode") != "createPodGroups":
            continue
        tpl = dict(op.get("podTemplate") or wl.default_pod_template or {})
        pod = _make_pod_from_template("warm-group-template", tpl)
        tkey = op.get("topologyKey")
        if tkey:
            warm_p = getattr(sched, "warm_for_placements", None)
            if warm_p is not None:
                domains = {n.labels.get(tkey) for n in cs.nodes.values()}
                domains.discard(None)
                warm_p(pod, int(op.get("groupSize", 2)),
                       max(1, len(domains)))
        else:
            warm(pod)


def run_sharded_workload(wl: Workload,
                         n_shards: Optional[int] = None) -> PerfResult:
    """Run a createNodes/createPods workload through the MULTI-PROCESS shard
    plane (shard/harness.py): one apiserver process, N scheduler processes,
    everything over HTTP. The measured window is first-measured-create →
    all-bound, so the reported pods/s composes shard throughput the way the
    acceptance criterion counts it (1-shard vs N-shard, same transport)."""
    from ..shard.harness import run_sharded_cluster

    n_nodes = n_pods = 0
    node_tpl: Dict[str, Any] = {}
    pod_tpl: Dict[str, Any] = dict(wl.default_pod_template or {})
    for op in wl.ops:
        if op["opcode"] == "createNodes":
            n_nodes += _resolve_count(op, wl.params)
            node_tpl = op.get("nodeTemplate", {})
        elif op["opcode"] == "createPods":
            n_pods += _resolve_count(op, wl.params)
            pod_tpl = dict(op.get("podTemplate") or pod_tpl)
        else:
            raise ValueError(
                f"sharded workloads support createNodes/createPods only, "
                f"got {op['opcode']!r}")
    shards = int(n_shards or wl.params.get("shards", 2))
    # Adversarial-tenant flood (overload plane, docs/RESILIENCE.md):
    # `floodThreads` in params spawns that many flood workers hammering
    # single-pod creates in their own namespace for the measured window —
    # the apiserver's flow control must shed them (429 + Retry-After)
    # while the measured tenant's pods keep binding.
    flood = None
    if wl.params.get("floodThreads"):
        flood = {"threads": int(wl.params["floodThreads"]),
                 "namespace": wl.params.get("floodNamespace", "flood-tenant")}
    out = run_sharded_cluster(
        shards, n_nodes, n_pods,
        lease_duration=float(wl.params.get("leaseDuration", 3.0)),
        warm_pods=int(wl.params.get("warmPods", min(256, max(1, n_pods // 8)))),
        zones=int(node_tpl.get("zones", 50)),
        node_capacity={"cpu": node_tpl.get("cpu", 32),
                       "memory": node_tpl.get("memory", "256Gi"),
                       "pods": node_tpl.get("pods", 110)},
        pod_request={"cpu": pod_tpl.get("cpu", "100m"),
                     "memory": pod_tpl.get("memory", "128Mi")},
        flood=flood)
    result = PerfResult(workload=wl, scheduled=out["bound"],
                        failed=0 if out["all_bound"] else 1,
                        elapsed=out["elapsed_s"])
    rate = out["pods_per_sec"]
    result.metrics["SchedulingThroughput"] = {
        "Average": rate, "Perc50": rate, "Perc90": rate, "Perc95": rate,
        "Perc99": rate}
    if flood is not None and out.get("flood") is not None:
        # FloodSheds floor: the flood really was shed (not absorbed);
        # MaxFloodErrors ceiling: sheds are 429s, never transport failures.
        result.metrics["FloodSheds"] = {"Average": out["flood"]["shed"]}
        result.metrics["MaxFloodErrors"] = {"Average": out["flood"]["errors"]}
    result.detail = dict(out)
    return result


def run_hollow_workload(wl: Workload) -> PerfResult:
    """Run a hollow-plane scale workload (docs/SCALE.md): the node fleet
    is impersonated by a kubernetes_tpu/hollow plane process (register +
    heartbeats + capacity drift + cordon/delete/re-register churn) while
    `shards` scheduler processes bind the measured pods over the paged
    read plane. The result carries the scale-plane acceptance numbers:

    - ``SchedulingThroughput`` — the usual floor;
    - ``MaxApiserverRssMb`` / ``MaxShardRssMb`` — peak RSS CEILINGS
      (sampled by the harness poll loop), the bounded-memory claim;
    - ``MaxUnpagedLists`` — apiserver_list_unpaged_total, asserted 0:
      zero full-cluster single-response LISTs crossed the wire."""
    from ..shard.harness import run_sharded_cluster

    n_nodes = n_pods = 0
    pod_tpl: Dict[str, Any] = dict(wl.default_pod_template or {})
    for op in wl.ops:
        if op["opcode"] == "createNodes":
            n_nodes += _resolve_count(op, wl.params)
        elif op["opcode"] == "createPods":
            n_pods += _resolve_count(op, wl.params)
            pod_tpl = dict(op.get("podTemplate") or pod_tpl)
        else:
            raise ValueError(
                f"hollow workloads support createNodes/createPods only, "
                f"got {op['opcode']!r}")
    params = wl.params
    profile = {
        "heartbeat_s": float(params.get("hollowHeartbeatS", 30.0)),
        "drift": float(params.get("hollowDrift", 0.0)),
        "churn_per_s": float(params.get("hollowChurnPerS", 0.0)),
        "zones": int(params.get("zones", 100)),
        # Failure injection (hollow/profile.py): silenced/flapping slices
        # and zone blackout for node-lifecycle-controller runs
        # (docs/RESILIENCE.md § node lifecycle).
        "silence": float(params.get("hollowSilence", 0.0)),
        "silence_after_s": float(params.get("hollowSilenceAfterS", 0.0)),
        "flap": float(params.get("hollowFlap", 0.0)),
        "flap_period_s": float(params.get("hollowFlapPeriodS", 2.0)),
        "outage_zone": int(params.get("hollowOutageZone", -1)),
        "outage_after_s": float(params.get("hollowOutageAfterS", 0.0)),
        # Capacity-imbalance knob (profile.imbalance, docs/DESCHEDULE.md):
        # churn re-registrations land capacity-skewed off the one seed —
        # the descheduler rows' drift source.
        "imbalance": float(params.get("hollowImbalance", 0.0)),
        "seed": int(params.get("hollowSeed", 0)),
    }
    # Standing workload-manager row (ROADMAP: trace profile at hollow
    # scale): `workloadManagers` spawns the HA manager pair; trace*
    # params feed the seeded Borg-marginal deployment/gang arrival feed.
    workload = None
    if params.get("workloadManagers"):
        workload = {"managers": int(params["workloadManagers"]),
                    "lease_ttl": float(params.get("workloadLeaseTtlS", 2.0))}
        if params.get("traceDeployments") or params.get("traceGangs"):
            workload["trace"] = {
                "deployments": int(params.get("traceDeployments", 0)),
                "gangs": int(params.get("traceGangs", 0)),
                "rate": float(params.get("traceRate", 2.0)),
                "lifetime": float(params.get("traceLifetimeS", 0.0)),
                "seed": int(params.get("traceSeed", 0))}
    # Descheduler rows (docs/DESCHEDULE.md): `deschedule: true` spawns
    # the HA descheduler pair; the rebalance happens inside the
    # `settleS` window after the last measured pod binds.
    deschedule = None
    if params.get("deschedule"):
        deschedule = {
            "managers": int(params.get("descheduleManagers", 2)),
            "lease_ttl": float(params.get("descheduleLeaseTtlS", 2.0)),
            "tick": float(params.get("descheduleTickS", 0.5)),
            "hysteresis": int(params.get("descheduleHysteresis", 5)),
            "margin": float(params.get("descheduleMargin", 0.10)),
            "max_moves": int(params.get("descheduleMaxMoves", 64))}
    # PDB-cleanliness oracle: `pdbMinAvailable` posts one PDB over the
    # measured pods' {app: sharded} selector before rebalance starts;
    # every progress poll then asserts the bound count never dips below
    # the floor once it has been reached — a dip means an eviction the
    # server should have 429'd (the zero-violations-at-every-poll
    # contract). The count rides the existing summary poll: no extra
    # read traffic.
    pdb_min = int(params.get("pdbMinAvailable", 0))
    warm_pods = int(params.get("warmPods", min(256, max(1, n_pods // 8))))
    pdb_state = {"created": False, "armed": False, "polls": 0,
                 "violations": 0}

    def _pdb_cb(bound: int, cluster) -> None:
        from ..shard.harness import _call
        if not pdb_state["created"]:
            try:
                _call(cluster.base, "POST", "/api/v1/pdbs",
                      {"name": "measured-pdb", "namespace": "default",
                       "minAvailable": pdb_min,
                       "matchLabels": {"app": "sharded"}})
            except Exception:  # noqa: BLE001 - next poll retries
                return
            pdb_state["created"] = True
        # The cb's `bound` excludes warm pods; the server's PDB gate
        # counts the whole {app: sharded} matched set (warm + measured),
        # so compare the same total the gate compares.
        total_bound = bound + warm_pods
        pdb_state["polls"] += 1
        if total_bound >= pdb_min:
            pdb_state["armed"] = True
        elif pdb_state["armed"]:
            pdb_state["violations"] += 1

    out = run_sharded_cluster(
        int(params.get("shards", 1)), n_nodes, n_pods,
        hollow=profile,
        # Fleet-conductor seams (docs/SCALE.md § fleet conductor): split
        # the hollow fleet across N plane processes by name-prefix range,
        # and give every shard a virtual device mesh so row-local plans
        # dispatch mesh-SPMD (the 100k fusion row runs both).
        hollow_procs=int(params.get("hollowProcs", 1)),
        mesh_devices=int(params.get("meshDevices", 0)),
        child_env=({"TPU_SCHED_HINT_LRU": str(params["hintLru"])}
                   if params.get("hintLru") else None),
        replicas=int(params.get("replicas", 0)),
        lease_duration=float(params.get("leaseDuration", 15.0)),
        warm_pods=warm_pods,
        timeout=float(params.get("timeoutS", 3600.0)),
        workload=workload,
        deschedule=deschedule,
        settle_s=float(params.get("settleS", 0.0)),
        progress_cb=(_pdb_cb if pdb_min else None),
        pod_request={"cpu": pod_tpl.get("cpu", "100m"),
                     "memory": pod_tpl.get("memory", "128Mi")})
    result = PerfResult(workload=wl, scheduled=out["bound"],
                        failed=0 if out["all_bound"] else 1,
                        elapsed=out["elapsed_s"])
    rate = out["pods_per_sec"]
    result.metrics["SchedulingThroughput"] = {
        "Average": rate, "Perc50": rate, "Perc90": rate, "Perc95": rate,
        "Perc99": rate}
    rss = out.get("rss_mb") or {}
    result.metrics["MaxApiserverRssMb"] = {"Average": max(
        [rss.get("apiserver", 0.0)] + list(rss.get("followers", ())))}
    result.metrics["MaxShardRssMb"] = {"Average": max(
        list(rss.get("shards", ())) or [0.0])}
    # Peak RSS of the hollow plane processes themselves: at 100k nodes
    # split across members, the impersonation layer's memory is part of
    # the bounded-memory claim too.
    result.metrics["MaxHollowRssMb"] = {"Average": float(
        rss.get("hollow", 0.0) or 0.0)}
    # Zero-unpaged must hold on EVERY replica (the shards list from
    # followers): the replication detail scrapes each one, leader
    # included; without replicas, fall back to the leader's counter.
    reps = out.get("replication")
    if reps:
        unpaged = sum(float(rep.get("listUnpaged", 0)) for rep in reps)
        relisted = sum(float(rep.get("relistedWatches", 0)) for rep in reps)
    else:
        api = out.get("api") or {}
        unpaged = float(api.get("apiserver_list_unpaged_total", 0.0))
        relisted = float(api.get("apiserver_relisted_watches_total", 0.0))
    result.metrics["MaxUnpagedLists"] = {"Average": unpaged}
    # Watch-plane health ceiling: a relisted watch means a watcher fell
    # off the cache ring and re-LISTed — at 100k nodes that is a paged
    # but still fleet-sized read. The fusion row pins it to zero.
    result.metrics["MaxRelistedWatches"] = {"Average": relisted}
    if workload is not None:
        # Standing trace-row floors: the trace profile really fed
        # (profile_fed counts deployment/gang arrivals minted) and the
        # reconcilers really created pods through the deterministic-name
        # /409 seam (summed over both managers — only the active one
        # creates, but a takeover splits the count).
        wls = [s for s in (out.get("workload") or []) if s]
        result.metrics["WorkloadTraceFed"] = {"Average": float(
            sum(int(s.get("profile_fed", 0)) for s in wls))}
        result.metrics["WorkloadPodsCreated"] = {"Average": float(sum(
            int((s.get("replicasets") or {}).get("pods_created", 0))
            + int((s.get("gangs") or {}).get("pods_created", 0))
            for s in wls))}
    if deschedule is not None:
        dss = [s for s in (out.get("deschedule") or []) if s]
        # Post-rebalance utilization stddev (milli-cpu): the ACTIVE
        # manager's last reconcile computed it; standbys report 0.0, so
        # take the max over managers that actually held the lease.
        # MaxUtilizationStddevMilli is the convergence CEILING the
        # ChurnDriftRebalance row pins.
        result.metrics["MaxUtilizationStddevMilli"] = {"Average": max(
            [float(s.get("util_stddev_milli", 0.0)) for s in dss
             if int(s.get("active_ticks", 0))] or [0.0])}
        # DescheduleMoves floor: the rebalance actually moved pods (a
        # zero here means the drift never formed or hysteresis ate it).
        result.metrics["DescheduleMoves"] = {"Average": float(sum(
            sum(int(v) for v in (s.get("moves") or {}).values())
            for s in dss))}
        # Exactly-once contract as a ceiling: every eviction the server
        # committed came back around as exactly one scheduler requeue —
        # a gap either way means a lost or double-counted move.
        api = out.get("api") or {}
        requeues = sum(
            float(sm.get("scheduler_eviction_requeues_total", 0.0))
            for sm in out.get("shard_metrics") or [])
        evictions = float(api.get("apiserver_pod_evictions_total", 0.0))
        result.metrics["MaxEvictionRequeueGap"] = {
            "Average": abs(requeues - evictions)}
    if pdb_min:
        # Zero-PDB-violations-at-every-poll: once the bound count reached
        # the PDB floor it never dipped below it again — every rebalance
        # eviction was budget-gated server-side.
        result.metrics["MaxPdbViolations"] = {
            "Average": float(pdb_state["violations"])}
    result.detail = dict(out)
    if pdb_min:
        result.detail["pdb"] = dict(pdb_state)
    return result


def run_workload(wl: Workload, sched: Optional[Scheduler] = None) -> PerfResult:
    """Execute one workload's opcode list (the RunBenchmarkPerfScheduling
    inner loop, scheduler_perf.go:282+)."""
    from ..models.tpu_scheduler import TPUScheduler

    if wl.params.get("hollow") and sched is None:
        # Hollow-plane scale workloads (HollowNodeScale): the node fleet
        # is impersonated by a hollow plane process, pods bind through
        # real scheduler shards over the paged read plane.
        return run_hollow_workload(wl)
    if wl.params.get("shards") and sched is None:
        # Sharded workloads (ShardedSchedulingBasic) run the multi-process
        # shard plane — one apiserver + N scheduler processes — rather than
        # an in-process scheduler loop.
        return run_sharded_workload(wl)

    # Each workload builds a fresh scheduler/framework; proto pods (and their
    # framework-id-keyed signature holders) must not outlive the frameworks
    # they were signed against (CPython id() reuse would alias a stale memo).
    _POD_PROTO_CACHE.clear()

    if sched is None:
        cfg = None
        cs_arg = {}
        if wl.feature_gates or wl.api_rtt_ms:
            from ..core.config import SchedulerConfiguration
            cfg = SchedulerConfiguration(
                feature_gates=dict(wl.feature_gates),
                async_dispatch_threads=wl.feature_gates.get(
                    "SchedulerAsyncAPICalls", False))
        if wl.api_rtt_ms:
            from ..core.remote import RemoteClientset
            cs_arg["clientset"] = RemoteClientset(rtt=wl.api_rtt_ms / 1000.0)
        if any(op.get("topologyKey") for op in wl.ops
               if op.get("opcode") == "createPodGroups"):
            # Topology-constrained gangs need the placement plugin set
            # (GenericWorkload-gated in the reference).
            from ..core.registry import gang_placement_profiles
            sched = TPUScheduler(profile_factory=gang_placement_profiles,
                                 config=cfg, **cs_arg)
        elif any(op.get("opcode") == "createResourceSlices" for op in wl.ops):
            # DRA workloads need the DynamicResources plugin
            # (DynamicResourceAllocation-gated in the reference).
            from ..core.registry import DEFAULT_PLUGINS, build_framework
            plugins = DEFAULT_PLUGINS + (("DynamicResources", 0),)
            sched = TPUScheduler(profile_factory=lambda h: {
                "default-scheduler": build_framework(h, plugins=plugins)},
                config=cfg, **cs_arg)
        else:
            sched = TPUScheduler(config=cfg, **cs_arg)
    cs = sched.clientset
    collector = _ThroughputCollector(sched)
    params = wl.params
    pod_seq = 0
    node_seq = 0
    created_nodes: List[str] = []
    result = PerfResult(workload=wl)
    tickers: List = []
    created_pods: Dict[str, List] = {}  # namespace -> pods (deletePods targets)
    t0 = time.perf_counter()

    def _create_pods(op, tpl, namespace, count):
        nonlocal pod_seq
        claim_tpl = tpl.get("resourceClaimTemplate")
        pv_tpl = op.get("persistentVolumeTemplate")
        pvc_tpl = op.get("persistentVolumeClaimTemplate")
        batch = []
        for _ in range(count):
            if pv_tpl is not None and pvc_tpl is not None:
                # One pre-bound PV+PVC pair per pod (the reference's
                # persistentVolumeTemplatePath/persistentVolumeClaimTemplatePath
                # prep: pv-aws.yaml / pv-csi.yaml / pvc.yaml with
                # pv.kubernetes.io/bind-completed).
                from ..api.storage import PersistentVolume, PersistentVolumeClaim
                from ..api.resource import parse_quantity
                cap = int(parse_quantity(str(pv_tpl.get("capacity", "1Gi"))))
                modes = tuple(pv_tpl.get("accessModes", ("ReadOnlyMany",)))
                pv = PersistentVolume(
                    name=f"pv-{pod_seq}", capacity=cap, access_modes=modes,
                    csi_driver=pv_tpl.get("csi", ""),
                    labels=dict(pv_tpl.get("labels", {})))
                pvc = PersistentVolumeClaim(
                    name=f"pvc-{pod_seq}", namespace=namespace, request=cap,
                    access_modes=modes)
                pv.claim_ref = pvc.key
                pvc.volume_name = pv.name
                pvc.annotations["pv.kubernetes.io/bind-completed"] = "true"
                cs.create_pv(pv)
                cs.create_pvc(pvc)
                tpl = dict(tpl, pvc="pvc-%d" % pod_seq)
            p = _make_pod_from_template(f"pod-{pod_seq}", tpl, namespace=namespace)
            if claim_tpl:
                # resourceClaimTemplate: one generated claim per pod
                # (dra/performance-config.yaml SchedulingWithResourceClaimTemplate)
                from ..api.dra import DeviceRequest, ResourceClaim
                cname = f"{p.name}-claim"
                cs.create_resource_claim(ResourceClaim(
                    name=cname, namespace=namespace,
                    requests=[DeviceRequest(
                        name="req",
                        count=int(claim_tpl.get("count", 1)),
                        selectors=dict(claim_tpl.get("selectors", {})),
                        expression=claim_tpl.get("expression", ""))]))
                p.resource_claims = [cname]
            pod_seq += 1
            cs.create_pod(p)
            batch.append(p)
        created_pods.setdefault(namespace, []).extend(batch)
        return batch

    for op in wl.ops:
        opcode = op["opcode"]
        if opcode == "createNodes":
            count = _resolve_count(op, params)
            tpl = op.get("nodeTemplate", {})
            tpl = {k: (params[v[1:]] if isinstance(v, str) and v.startswith("$")
                       else v) for k, v in tpl.items()}
            csi_alloc = op.get("csiNodeAllocatable")  # {driver: count}
            if tpl.get("name"):
                # Named template (node-with-name.yaml): names must be unique,
                # so multi-count named ops get an index suffix.
                for i in range(count):
                    t = dict(tpl, name=tpl["name"] if count == 1 else f"{tpl['name']}-{i}")
                    created_nodes.append(cs.create_node(_make_node_from_template(i, t)).name)
            else:
                # Continue the node name sequence across ops: a second
                # unnamed createNodes in the same workload must not overwrite
                # the first op's node-<i> names.
                for i in range(count):
                    created_nodes.append(
                        cs.create_node(_make_node_from_template(node_seq + i, tpl)).name)
                node_seq += count
            if csi_alloc:
                from ..api.storage import CSINode
                for name in created_nodes[-count:]:
                    cs.create_csi_node(CSINode(
                        node_name=name,
                        driver_limits={d: int(c) for d, c in csi_alloc.items()}))
        elif opcode == "createNamespaces":
            count = _resolve_count(op, params) if ("count" in op or "countParam" in op) else 1
            prefix = op.get("prefix", "ns")
            labels = dict(op.get("labels", {}))
            for i in range(count):
                cs.create_namespace(Namespace(name=f"{prefix}-{i}", labels=labels))
        elif opcode == "createPodSets":
            # one createPods op per namespace prefix-i (affinity NS-selector
            # configs; scheduler_perf.go createPodSetsOp)
            count = _resolve_count(op, params)
            prefix = op.get("namespacePrefix", "ns")
            inner = op["createPodsOp"]
            tpl = inner.get("podTemplate") or wl.default_pod_template or {}
            per_ns = _resolve_count(inner, params)
            for i in range(count):
                _create_pods(inner, tpl, f"{prefix}-{i}", per_ns)
            _drain(sched, collector, tickers)
        elif opcode == "createPods":
            count = _resolve_count(op, params)
            tpl = op.get("podTemplate") or wl.default_pod_template or {}
            namespace = op.get("namespace", "default")
            collect = bool(op.get("collectMetrics"))
            if collect:
                # Compile the kernel shapes outside the measured window
                # (the reference's measured runs start against a warm
                # scheduler process; XLA compilation is our cold-start).
                if tpl.get("resourceClaimTemplate") or op.get(
                        "persistentVolumeTemplate"):
                    # Claim/volume pods plan with the counted-aux kernel
                    # variant (has_aux) — a template-only warm pod would
                    # compile the WRONG tier. Schedule ONE real
                    # measured-shaped pod (claim/PV included) before the
                    # window opens instead.
                    _create_pods(op, tpl, namespace, 1)
                    _drain(sched, collector, tickers)
                else:
                    warm = getattr(sched, "warm_for", None)
                    if warm is not None:
                        warm(_make_pod_from_template("warm-template", tpl,
                                                     namespace=namespace))
                collector.start()
                # Measured creates run on a concurrent client thread (the
                # reference's createPodsOp issues creates from the test
                # client while the scheduler runs); setup creates stay
                # synchronous for determinism.
                tickers.append(_ThreadedCreator(
                    lambda op=op, tpl=tpl, namespace=namespace, count=count:
                    _create_pods(op, tpl, namespace, count)))
            else:
                _create_pods(op, tpl, namespace, count)
            if not op.get("skipWaitToCompletion"):
                _drain(sched, collector, tickers)
            if collect:
                result.metrics["SchedulingThroughput"] = collector.stop()
                _record_hint_hit_rate(result, collector)
                result.detail["in_window"] = collector.in_window
        elif opcode == "deletePods":
            namespace = op.get("namespace", "default")
            targets = created_pods.get(namespace, [])
            rate = float(op.get("deletePodsPerSecond", 100))
            deleter = _RateDeleter(cs, targets, rate)
            if op.get("skipWaitToCompletion"):
                tickers.append(deleter)  # deletes overlap the measured window
            else:
                while deleter.tick():
                    time.sleep(0.001)
        elif opcode == "createPodGroups":
            count = _resolve_count(op, params)
            size = int(op.get("groupSize", 2))
            tkeys = (op["topologyKey"],) if op.get("topologyKey") else ()
            tpl = dict(op.get("podTemplate") or wl.default_pod_template or {})
            for g in range(count):
                name = f"group-{g}"
                cs.create_pod_group(PodGroup(name=name, min_count=size,
                                             topology_keys=tkeys))
                tpl_g = dict(tpl, podGroup=name)
                for i in range(size):
                    cs.create_pod(_make_pod_from_template(f"pod-{pod_seq}", tpl_g))
                    pod_seq += 1
            _drain(sched, collector, tickers)
        elif opcode == "churn":
            # Concurrent churn (scheduler_perf.go:72): the churner ticks
            # inside _drain, i.e. DURING the measured window.
            tickers.append(_Churner(
                cs,
                op.get("podTemplate") or wl.default_pod_template or {"cpu": "4"},
                number=int(op.get("number", 1)),
                interval_ms=float(op.get("intervalMilliseconds", 1000)),
                mode=op.get("mode", "recreate"),
                churn_nodes=bool(op.get("churnNodes", True)),
            ))
        elif opcode == "barrier":
            _drain(sched, collector, tickers)
        elif opcode == "sleep":
            time.sleep(float(op.get("duration", 0.1)))
        elif opcode == "startCollectingMetrics":
            # Compile the kernel shapes LATER ops will hit before the window
            # opens (group sessions / stacked placement evaluation — the
            # reference measures against a warm scheduler process; XLA
            # compilation is our cold-start analogue).
            _warm_group_shapes(sched, cs, wl, op)
            collector.start()
        elif opcode == "stopCollectingMetrics":
            result.metrics["SchedulingThroughput"] = collector.stop()
            _record_hint_hit_rate(result, collector)
            result.detail["in_window"] = collector.in_window
        elif opcode == "createResourceSlices":
            # One slice per node with N devices (dra configs' resource-slice
            # prep; devices get a model attribute for selector exercises).
            # Slices attach to the MOST RECENTLY created `count` nodes — the
            # dra configs create the DRA nodes immediately before this op.
            from ..api.dra import Device, ResourceSlice
            count = _resolve_count(op, params)
            per_node = int(op.get("devicesPerNode", 4))
            driver = op.get("driver", "gpu.example.com")
            targets = created_nodes[-count:]
            for name in targets:
                cs.create_resource_slice(ResourceSlice(
                    node_name=name, driver=driver,
                    devices=[Device(name=f"{name}-dev{j}",
                                    attributes={"model": "a100", "index": str(j)})
                             for j in range(per_node)]))
        elif opcode == "allocResourceClaims":
            # DRA pre-allocation (dra/performance-config.yaml): allocate all
            # pending claims against the current ResourceSlices.
            from ..plugins.dynamicresources import allocate_pending_claims
            allocate_pending_claims(cs)
        else:
            raise ValueError(f"unknown opcode {opcode!r}")

    result.elapsed = time.perf_counter() - t0
    result.scheduled = sched.scheduled
    result.failed = sched.failures
    for attr in _ThroughputCollector.WINDOW_COUNTERS + (
            "placement_device_evals", "shard_map_dispatches"):
        v = getattr(sched, attr, None)
        if v is not None:
            result.detail[attr] = round(v, 3) if isinstance(v, float) else v
    # Mesh plane: compile-time per-step ici/dcn collective counts of the
    # workload's own dispatch path (the MULTICHIP collective budget).
    # Opt-in (one lower+compile per run) — the bench/dryrun mains set it.
    import os as _os
    if (getattr(sched, "mesh", None) is not None
            and wl.default_pod_template
            and _os.environ.get("TPU_SCHED_COLLECTIVES_DETAIL") == "1"):
        try:
            result.detail["collectives"] = sched.collective_counts(
                _make_pod_from_template("collective-probe",
                                        dict(wl.default_pod_template)))
        except Exception as e:  # noqa: BLE001 - detail only, never the run
            result.detail["collectives"] = {"error": str(e)[:200]}
    # Per-extension-point latency (scheduler_perf.go:866-871 collects the
    # framework_extension_point_duration_seconds histogram per workload).
    hist = sched.metrics.framework_extension_point_duration
    points = {}
    for key in list(hist._totals):
        label = key[0] if key[1] == "Success" else f"{key[0]}/{key[1]}"
        points[label] = {
            "count": hist.count(*key),
            "p50_ms": round(hist.percentile(0.50, *key) * 1e3, 3),
            "p99_ms": round(hist.percentile(0.99, *key) * 1e3, 3),
        }
    if points:
        result.detail["extension_points"] = points
    # e2e scheduling latency (queue admission -> bound; fed from span ends
    # — docs/OBSERVABILITY.md): p50/p99 truth next to the throughput number.
    e2e = sched.metrics.e2e_scheduling_duration
    if e2e.count():
        result.detail["e2e_ms"] = {
            "count": e2e.count(),
            "p50": round(e2e.percentile(0.50) * 1e3, 3),
            "p99": round(e2e.percentile(0.99) * 1e3, 3),
        }
    # Preemption-storm attribution (the PreemptionStorm rows): attempts,
    # victim totals, and async victim-deletion results in the detail line.
    m = sched.metrics
    if m.preemption_attempts.value() or m.workload_preemption_attempts._values:
        result.detail["preemption"] = {
            "attempts": int(m.preemption_attempts.value()),
            "victims": int(m.preemption_victims.count()),
            "workload_attempts": {
                k[0]: int(v)
                for k, v in m.workload_preemption_attempts._values.items()},
            "workload_victims": int(m.workload_preemption_victims.count()),
        }
    # in-flight invariant (scheduler_perf.go:878-880 checkEmptyInFlightEvents)
    assert not sched.queue._in_flight, "in-flight events remain after workload"
    close = getattr(cs, "close", None)
    if close is not None:
        close()  # stop the per-workload apiserver thread (core/remote.py)
    return result
