"""scheduler_perf: the data-driven performance/integration harness.

Re-expresses test/integration/scheduler_perf — YAML workloads executed by an
opcode interpreter (scheduler_perf.go:64-80: createNodes, createPods,
createPodGroups, churn, barrier, sleep, start/stopCollectingMetrics), with
SchedulingThroughput Average/P50/P90/P95/P99 collectors (util.go:477,686-694)
and per-workload thresholds (scheduler_perf.go:282-368).
"""

from .harness import PerfResult, Workload, load_config, run_workload

__all__ = ["PerfResult", "Workload", "load_config", "run_workload"]
