"""ShardMember: wires one scheduler instance into the shard plane.

Installs the shard-scoped admission predicate (core/scheduler.py
``pod_admission``), keeps the member's lease alive, and recomputes
ownership (adopting expired peers' ranges) on the scheduling thread.

Liveness and ownership are deliberately split:

- **Renewal** runs on a small background thread (`start_renewer`): it only
  PUTs the lease and refreshes the read-only lease view, so it stays alive
  while the scheduling thread is pinned inside a long drain or an XLA
  compile — a busy shard must never look dead.
- **Ownership** (adoption + the pending-pod sweep) mutates the queue, so it
  runs only from ``tick()`` on the scheduling thread — wired as the
  scheduler's per-cycle ``loop_hook``, rate-limited internally.

Without a renewer thread, ``tick()`` does both (the in-process/unit shape,
where clocks are injectable and nothing sleeps).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional, Set

from .leases import ShardMap
from .partition import shard_of_pod


class ShardMember:
    def __init__(self, scheduler, index: int, count: int,
                 lease_duration: float = 3.0,
                 renew_interval: Optional[float] = None,
                 identity: str = "",
                 now: Callable[[], float] = time.monotonic):
        self.scheduler = scheduler
        self.index = index
        self.count = count
        self.map = ShardMap(scheduler.clientset, index, count,
                            lease_duration=lease_duration,
                            identity=identity, now=now)
        self.identity = self.map.identity
        self.lease_duration = lease_duration
        # Renew well inside the lease period: 3 renew chances per duration.
        self.renew_interval = (renew_interval if renew_interval is not None
                               else lease_duration / 3.0)
        self.now = now
        self._next_tick = 0.0  # first tick runs immediately
        self._own_ok = False
        self._renewer: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.owned: Set[int] = {index}
        self.renewals = 0
        self.adoptions = 0
        self.handbacks = 0
        scheduler.pod_admission = self.admits
        scheduler.shard_member = self
        scheduler.loop_hook = self.tick
        # Shard binds terminate at the binding subresource, which validates
        # committed per-node usage (409 OutOfCapacity): device sessions may
        # ride through peer shards' bind events optimistically — but ONLY
        # when the clientset really has that backstop (HTTPClientset sets
        # validates_bind_capacity; a FakeClientset member — the unit-test
        # shape — binds unconditionally, so optimistic in-flight commits
        # there could silently overcommit a node).
        scheduler.bind_capacity_validated = bool(getattr(
            scheduler.clientset, "validates_bind_capacity", False))
        scheduler.metrics.shard_owned_shards.set(1.0)
        # Pods that entered the queue BEFORE the admission predicate existed
        # (informer replay at clientset registration) leave now; their owner
        # admits them on its own feed.
        self._purge_unowned()

    # -- admission (the queue-side partition) -------------------------------

    def admits(self, pod) -> bool:
        return shard_of_pod(pod, self.count) in self.owned

    def _purge_unowned(self) -> int:
        """Drop queued entities outside this shard's range (gangs leave
        whole — the partitioner pins them by group key, so one member's
        verdict is the group's)."""
        from ..core.queue import QueuedPodGroupInfo, QueuedPodInfo

        q = self.scheduler.queue
        removed = 0
        for ent in (list(q.active_q.items()) + list(q.backoff_q.items())
                    + list(q.unschedulable.values())):
            if isinstance(ent, QueuedPodInfo) and not self.admits(ent.pod):
                q.delete(ent.pod)
                removed += 1
            elif (isinstance(ent, QueuedPodGroupInfo) and ent.members
                    and not self.admits(ent.members[0].pod)):
                for m in list(ent.members):
                    q.delete(m.pod)
                removed += 1
        for members in list(q._group_members.values()):
            for m in list(members):
                if not self.admits(m.pod):
                    q.delete(m.pod)
                    removed += 1
        return removed

    # -- liveness (renew) ---------------------------------------------------

    def _renew_once(self) -> None:
        """One renew + view refresh. HTTP only — safe off-thread; the lease
        view lands by reference assignment (GIL-atomic), ownership is
        recomputed from it on the scheduling thread."""
        try:
            self._own_ok = self.map.renew_own()
            if self._own_ok:
                self.renewals += 1
                self.scheduler.metrics.shard_lease_renewals.inc()
            self.map.refresh()
        except Exception:  # noqa: BLE001 - transient API failure: the lease
            pass           # simply ages; the next renew attempt catches up

    def start_renewer(self) -> None:
        """Background renewals: the shard stays visibly alive while the
        scheduling thread is pinned (long drains, XLA compiles)."""
        if self._renewer is not None:
            return

        def loop():
            while not self._stop.wait(self.renew_interval):
                self._renew_once()

        self._renew_once()  # synchronous first acquire (ready-gate)
        self._renewer = threading.Thread(
            target=loop, name=f"shard-renew-{self.index}", daemon=True)
        self._renewer.start()

    def stop(self) -> None:
        self._stop.set()
        if self._renewer is not None:
            self._renewer.join(timeout=5)
            self._renewer = None

    # -- ownership + failover (scheduling thread only) ----------------------

    def tick(self) -> bool:
        """Rate-limited ownership refresh; wired as the scheduler's
        per-cycle loop_hook. Renews inline when no renewer thread runs."""
        now = self.now()
        if now < self._next_tick:
            return False
        self._next_tick = now + self.renew_interval
        if self._renewer is None:
            self._renew_once()
        new_owned = self.map.compute_owned(self._own_ok)
        grown = new_owned - self.owned
        shrunk = self.owned - new_owned
        self.owned = new_owned
        self.scheduler.metrics.shard_owned_shards.set(float(len(new_owned)))
        if shrunk:
            # A dead peer came back (its renewal made the slot alive again):
            # possession-by-observation hands the range back with no
            # protocol. Pods of that range already in OUR queue finish
            # normally; overlap resolves through bind 409s.
            self.handbacks += len(shrunk)
        if grown:
            self.adoptions += len(grown)
            self.scheduler.metrics.shard_adoptions.inc(value=len(grown))
            # Failover is a forensic moment: a 100%-sampled span marks it
            # in the trace stream, and the flight recorder (when installed)
            # dumps the ring so the adoption's surroundings survive even if
            # this process dies next (docs/OBSERVABILITY.md).
            from ..core import spans as _spans
            tr = _spans.default_tracer()
            tr.record("shard.adopt", tr.proc_ctx(), shards=sorted(grown),
                      owned=sorted(new_owned))
            _spans.request_dump("shard_adoption")
            self.sweep_pending()
        return True

    def sweep_pending(self) -> int:
        """Adoption sweep: enqueue every pending pod the informer cache
        holds that the new ownership admits and the queue/cache doesn't
        already track. This is how a dead shard's range drains — its
        ASSUMED-but-unbound pods died with its cache (nothing to unwind
        anywhere else), its BOUND pods are in the store, and everything
        still pending re-enters here."""
        s = self.scheduler
        # Slim-projection hydration (core/watchcache.py): the watch stream's
        # shard filter is static (`shard=i/n` — this member's OWN slot), so
        # an adopted range's pods arrived as slim projections without their
        # real spec. Fetch the full wire in bulk BEFORE enqueueing; a pod
        # whose hydration fails stays out this sweep (the next tick — or
        # the per-event hydration in _on_pod_event — retries).
        stale = [p.uid for p in list(s.clientset.pods.values())
                 if getattr(p, "wire_slim", False) and not p.node_name
                 and p.deletion_ts is None and self.admits(p)]
        if stale and hasattr(s.clientset, "hydrate_pods"):
            try:
                s.clientset.hydrate_pods(stale)
            except Exception:  # noqa: BLE001 - transient API failure
                pass
        added = 0
        for pod in list(s.clientset.pods.values()):
            if pod.node_name or pod.deletion_ts is not None:
                continue
            if getattr(pod, "wire_slim", False):
                continue  # hydration failed: never schedule a projection
            if not s._responsible_for_pod(pod) or not self.admits(pod):
                continue
            if pod.uid in s.cache.pod_states or s.queue.has_entity(pod.uid):
                continue
            s.queue.add(pod)
            added += 1
        return added

    def lease_view(self) -> List[dict]:
        """The last-refreshed lease table (debugger dump)."""
        return list(self.map.last_view)
