"""Scheduler shard plane: optimistic multi-scheduler scale-out.

N scheduler instances run against ONE apiserver, Omega-style (Schwarzkopf
et al., EuroSys'13): every shard plans against the FULL watch-fed cluster
state, admission into each shard's queue is partitioned deterministically
(`partition.py` — PodGroups pinned whole so gang all-or-nothing never spans
shards), and conflicting commits meet at the binding subresource, where the
loser's 409 becomes a conflict-driven requeue through the existing backoffQ
(core/scheduler.py _unwind_binding). Shard liveness rides durable lease
records renewed through the apiserver (`leases.py`; they ride the WAL, so
a `kill -9`'d control plane recovers the holder table); an expired shard's
pod range is adopted by its ring successor (`member.py`) and the PR-2
reconciliation unwinds anything the dead shard left half-finished.

See docs/SHARDING.md for the protocol and its invariants.
"""

from .harness import run_sharded_cluster
from .leases import LEASE_PREFIX, ShardMap, lease_name
from .member import ShardMember
from .partition import shard_key, shard_of_key, shard_of_pod
from .plane import ShardPlane

__all__ = [
    "LEASE_PREFIX", "ShardMap", "ShardMember", "ShardPlane", "lease_name",
    "run_sharded_cluster", "shard_key", "shard_of_key", "shard_of_pod",
]
