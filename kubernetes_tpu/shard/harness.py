"""Multi-process shard-plane harness: one apiserver process + N scheduler
processes (`python -m kubernetes_tpu --shard-index i --shard-count n`),
driven entirely over HTTP. This is the production-shaped scale-out path —
each shard is an OS process with its own GIL, so shard throughput actually
adds up on CPU — used by ``bench.py --shards N``, the perf harness's
ShardedSchedulingBasic workload, and the shard-kill chaos test.
"""

from __future__ import annotations

import json
import os
import re
import sys
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Dict, List, Optional
from urllib import request as urlrequest

_READY = r"serving on 127\.0\.0\.1:(\d+)"


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))


def _env() -> dict:
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # never touch the TPU tunnel
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = _repo_root()
    # Persistent XLA compilation cache (see tests/conftest.py): every shard
    # process compiles the same kernel statics — across a plane AND across
    # runs, only the first ever pays the backend compile.
    env.setdefault("JAX_COMPILATION_CACHE_DIR", os.path.join(
        os.path.expanduser("~"), ".cache", "kubernetes-tpu-xla"))
    return env


_KA_CLIENTS: Dict[str, object] = {}


def rss_mb(pid=None) -> float:
    """VmRSS of `pid` (default: this process) in MiB, straight from
    /proc/<pid>/status — 0.0 when unreadable (process gone, non-Linux).
    The bench/perf poll loops sample this so the bounded-memory claims
    are numbers, not assertions."""
    try:
        with open(f"/proc/{pid or 'self'}/status") as fh:
            for line in fh:
                if line.startswith("VmRSS:"):
                    return round(int(line.split()[1]) / 1024.0, 1)
    except (OSError, ValueError, IndexError):
        pass
    return 0.0


def _call(base: str, method: str, path: str, body=None, timeout: float = 30):
    """Pooled keep-alive call (core/apiserver.py KeepAliveClient): the
    creator threads POST thousands of pods — per-call connection setup
    costs the apiserver a thread spawn per request on top of the TCP
    handshake, CPU the shard schedulers are competing for."""
    from ..core.apiserver import KeepAliveClient

    client = _KA_CLIENTS.get(base)
    if client is None:
        client = _KA_CLIENTS[base] = KeepAliveClient(base)
    return client.call(method, path, body, timeout=timeout)


def scrape_histogram(base: str, name: str,
                     text: Optional[str] = None) -> Optional[dict]:
    """One histogram's merged bucket table across all label
    sets: {"buckets": [(le, cumulative_count)...], "count": n, "sum": s}.
    None when the series is absent. Lets the harness compute cross-shard
    p50/p99 by summing per-shard cumulative buckets (bucket bounds are
    identical — one metrics.py declaration)."""
    if text is None:
        text = _fetch_metrics(base)
    buckets: Dict[float, float] = {}
    count = total = 0.0
    seen = False
    for line in text.splitlines():
        if not line.startswith(name):
            continue
        m = re.match(rf'{name}_bucket{{.*le="([^"]+)".*}} (\S+)', line)
        if m is not None:
            seen = True
            le = float("inf") if m.group(1) == "+Inf" else float(m.group(1))
            buckets[le] = buckets.get(le, 0.0) + float(m.group(2))
            continue
        m = re.match(rf"{name}_count(?:{{[^}}]*}})? (\S+)", line)
        if m is not None:
            count += float(m.group(1))
            continue
        m = re.match(rf"{name}_sum(?:{{[^}}]*}})? (\S+)", line)
        if m is not None:
            total += float(m.group(1))
    if not seen:
        return None
    return {"buckets": sorted(buckets.items()), "count": count, "sum": total}


def merge_histograms(hists: List[Optional[dict]]) -> Optional[dict]:
    merged: Dict[float, float] = {}
    count = total = 0.0
    any_seen = False
    for h in hists:
        if h is None:
            continue
        any_seen = True
        for le, c in h["buckets"]:
            merged[le] = merged.get(le, 0.0) + c
        count += h["count"]
        total += h["sum"]
    if not any_seen:
        return None
    return {"buckets": sorted(merged.items()), "count": count, "sum": total}


def histogram_percentile(hist: dict, q: float) -> float:
    """Bucket-interpolated percentile over a merged cumulative table (the
    same interpolation as core/metrics.py Histogram.percentile)."""
    if not hist or hist["count"] <= 0:
        return 0.0
    target = q * hist["count"]
    prev_le, prev_cum = 0.0, 0.0
    for le, cum in hist["buckets"]:
        if cum >= target:
            if le == float("inf"):
                return prev_le
            span = cum - prev_cum
            frac = (target - prev_cum) / span if span else 1.0
            return prev_le + (le - prev_le) * frac
        prev_le, prev_cum = (0.0 if le == float("inf") else le), cum
    return prev_le


def _fetch_metrics(base: str) -> str:
    """GET /metrics once; every parser below accepts the fetched text so a
    multi-way scrape (totals + histogram + labeled) costs ONE round trip."""
    req = urlrequest.Request(base + "/metrics")
    with urlrequest.urlopen(req, timeout=30) as resp:
        return resp.read().decode()


def scrape_labeled(base: str, name: str, label: str,
                   text: Optional[str] = None) -> Dict[str, float]:
    """One series' per-label-value breakdown, e.g.
    scrape_labeled(url, "scheduler_watch_decoded_events", "form") ->
    {"full": n, "slim": m} (scrape_metrics sums label sets away)."""
    if text is None:
        text = _fetch_metrics(base)
    out: Dict[str, float] = {}
    pat = re.compile(rf'{name}{{.*?{label}="([^"]+)".*?}} (\S+)')
    for line in text.splitlines():
        m = pat.match(line)
        if m is not None:
            try:
                out[m.group(1)] = out.get(m.group(1), 0.0) + float(m.group(2))
            except ValueError:
                continue
    return out


def scrape_metrics(base: str, text: Optional[str] = None) -> Dict[str, float]:
    """{series name: value}, label sets summed per name."""
    if text is None:
        text = _fetch_metrics(base)
    out: Dict[str, float] = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        m = re.match(r"([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{[^}]*\})? (\S+)", line)
        if m is None:
            continue
        try:
            out[m.group(1)] = out.get(m.group(1), 0.0) + float(m.group(2))
        except ValueError:
            continue
    return out


class ShardedCluster:
    """Handles to a running sharded cluster (context for progress_cb).

    Since the fleet conductor landed (kubernetes_tpu/fleet/), this is a
    compatibility VIEW over a FleetConductor: the conductor owns the
    process tree (staged bring-up, drained pipes, supervision, RSS
    sampling, teardown); this class keeps the attribute surface the
    chaos tests and bench drivers always had."""

    def __init__(self, conductor):
        self.conductor = conductor
        self.killed: List[int] = []

    # -- conductor-derived handles -----------------------------------------

    @property
    def base(self) -> str:
        return self.conductor.base

    @property
    def api_proc(self):
        leaders = self.conductor.members_of("apiserver")
        return leaders[0].proc if leaders else None

    @property
    def shard_procs(self) -> List:
        return [m.proc for m in self.conductor.members_of("shard")]

    @property
    def shard_urls(self) -> List[str]:
        return list(self.conductor.shard_urls)

    @property
    def follower_procs(self) -> List:
        return [m.proc for m in self.conductor.members_of("follower")]

    @property
    def follower_urls(self) -> List[str]:
        return list(self.conductor.follower_urls)

    @property
    def hollow_proc(self):
        hollows = self.conductor.members_of("hollow")
        return hollows[0].proc if hollows else None

    @property
    def log_tails(self) -> List:
        return [m.tail for m in self.conductor.members if m.tail is not None]

    @property
    def rss_peaks(self) -> Dict[str, object]:
        return self.conductor.rss_peaks()

    def sample_rss(self) -> Dict[str, object]:
        """Fold the current per-process VmRSS into the peaks (the
        conductor's supervisor also samples on its own cadence)."""
        return self.conductor.rss_peaks()

    def stop_hollow(self) -> Optional[dict]:
        """SIGTERM the hollow members and merge their final stats lines
        (`{"hollow_stats": ...}`) from the drained tails."""
        return self.conductor.stop_hollow()

    def kill(self, index: int) -> None:
        """SIGKILL one shard scheduler process — no goodbye, no flush.
        The conductor's shard policy is `adopt`: the kill is LEDGERED but
        never respawned — the dead range drains through lease adoption."""
        import signal as _signal
        member = self.conductor.members_of("shard")[index]
        if member.alive():
            member.proc.send_signal(_signal.SIGKILL)
            member.proc.wait(timeout=30)
        self.killed.append(index)

    def alive_shard_urls(self) -> List[str]:
        return [u for i, u in enumerate(self.shard_urls)
                if i not in self.killed]

    def stop(self) -> None:
        self.conductor.stop()


def start_sharded_cluster(n_shards: int, lease_duration: float = 15.0,
                          data_dir: str = "",
                          flightrec_dir: str = "",
                          startup_timeout: float = 180.0,
                          replicas: int = 0,
                          repl_lease: float = 2.0,
                          fair_tenants: bool = False,
                          apf_workload: str = "",
                          spec=None) -> ShardedCluster:
    """Bring up the apiserver + N shard scheduler processes through the
    fleet conductor (kubernetes_tpu/fleet/): staged readiness barriers
    (leader → followers tailing → shards leased), every child's stdout
    drained, per-role supervision. ``flightrec_dir`` installs the flight
    recorder in every process (TPU_SCHED_FLIGHTREC_DIR): periodic + exit
    dumps land there, so even a SIGKILLed member leaves a recent forensic
    artifact.

    ``replicas`` > 0 builds the REPLICATED control plane
    (kubernetes_tpu/replication/): that many follower apiservers tail the
    leader's WAL, and each shard reads (list/watch/RESUME) from follower
    ``i % replicas`` — with the siblings + leader as reflector fallbacks —
    while its writes redirect to the leader. One apiserver process stops
    being both the durability point and the availability ceiling for
    N shards x M watch streams.

    ``spec`` (a fleet.FleetSpec) overrides the argument-built spec
    entirely — the seam `python -m kubernetes_tpu.fleet` drives."""
    from ..fleet import FleetConductor, FleetSpec

    if spec is None:
        spec = FleetSpec(shards=n_shards, shard_lease_s=lease_duration,
                         data_dir=data_dir, flightrec_dir=flightrec_dir,
                         startup_timeout_s=startup_timeout,
                         replicas=replicas, repl_lease_s=repl_lease,
                         fair_tenants=fair_tenants,
                         apf_workload=apf_workload)
    return ShardedCluster(FleetConductor(spec).start())


def start_hollow_plane(base: str, profile, cwd: str, env: dict,
                       timeout: float = 900.0):
    """Spawn the hollow-node plane process (`python -m
    kubernetes_tpu.hollow`) against `base` and block until its fleet is
    registered. Returns (proc, registered_count)."""
    import tempfile

    from ..testing.faults import spawn_ready

    prof_dict = (profile.to_dict() if hasattr(profile, "to_dict")
                 else dict(profile))
    fd, path = tempfile.mkstemp(prefix="hollow-profile-", suffix=".json")
    try:
        with os.fdopen(fd, "w") as fh:
            json.dump(prof_dict, fh)
        cmd = [sys.executable, "-m", "kubernetes_tpu.hollow",
               "--api-url", base, "--profile", path]
        proc, m = spawn_ready(cmd, r"registered (\d+) nodes", cwd=cwd,
                              env=env, timeout=timeout)
    finally:
        # The child reads the profile before printing its ready line —
        # once spawn_ready returns (or fails), the file is garbage.
        try:
            os.unlink(path)
        except OSError:
            pass
    return proc, int(m.group(1))


def start_controller(base: str, cwd: str, env: dict,
                     fallbacks=(), grace: float = 4.0,
                     noexec_after: float = 2.0, tick: float = 0.5,
                     primary_qps: float = 2.0, secondary_qps: float = 0.1,
                     unhealthy_threshold: float = 0.55,
                     timeout: float = 120.0):
    """Spawn the node-lifecycle controller process (`python -m
    kubernetes_tpu.controllers`) against `base` and block until its ready
    line. Returns (proc, metrics_url) — `metrics_url` serves the
    `node_lifecycle_*` series the chaos acceptance scrapes."""
    from ..testing.faults import spawn_ready

    cmd = [sys.executable, "-m", "kubernetes_tpu.controllers",
           "--api-url", base,
           "--grace", str(grace), "--noexec-after", str(noexec_after),
           "--tick", str(tick), "--primary-qps", str(primary_qps),
           "--secondary-qps", str(secondary_qps),
           "--unhealthy-threshold", str(unhealthy_threshold)]
    for url in fallbacks:
        cmd += ["--fallback", url]
    proc, m = spawn_ready(cmd, r"metrics on (127\.0\.0\.1:\d+)", cwd=cwd,
                          env=env, timeout=timeout)
    return proc, f"http://{m.group(1)}"


def start_workload_manager(base: str, cwd: str, env: dict,
                           identity: str = "workload-manager-0",
                           fallbacks=(), lease_ttl: float = 2.0,
                           tick: float = 0.25, autoscale=None, trace=None,
                           timeout: float = 120.0):
    """Spawn one workload controller-manager process (`python -m
    kubernetes_tpu.controllers --mode workload`) against `base` and block
    until its ready line. Spawn TWO with distinct identities for the HA
    pair — they race the shared lease, one ACTIVE, one STANDBY.
    `autoscale` is an optional dict of ClusterAutoscaler bounds
    (min/max/wave/pending_age/cooldown); `trace` an optional dict of
    WorkloadProfile marginals (deployments/gangs/rate/lifetime/seed).
    Returns (proc, metrics_url)."""
    from ..testing.faults import spawn_ready

    cmd = [sys.executable, "-m", "kubernetes_tpu.controllers",
           "--mode", "workload", "--api-url", base,
           "--identity", identity, "--lease-ttl", str(lease_ttl),
           "--tick", str(tick)]
    for url in fallbacks:
        cmd += ["--fallback", url]
    if autoscale is not None:
        cmd += ["--autoscale",
                "--min-nodes", str(autoscale.get("min", 0)),
                "--max-nodes", str(autoscale.get("max", 100)),
                "--scale-wave", str(autoscale.get("wave", 2)),
                "--pending-age", str(autoscale.get("pending_age", 2.0)),
                "--scale-cooldown", str(autoscale.get("cooldown", 5.0))]
    if trace is not None:
        cmd += ["--trace-deployments", str(trace.get("deployments", 0)),
                "--trace-gangs", str(trace.get("gangs", 0)),
                "--trace-rate", str(trace.get("rate", 2.0)),
                "--trace-lifetime", str(trace.get("lifetime", 0.0)),
                "--trace-seed", str(trace.get("seed", 0))]
    proc, m = spawn_ready(cmd, r"metrics on (127\.0\.0\.1:\d+)", cwd=cwd,
                          env=env, timeout=timeout)
    return proc, f"http://{m.group(1)}"


def start_descheduler(base: str, cwd: str, env: dict,
                      identity: str = "descheduler-0",
                      fallbacks=(), lease_ttl: float = 2.0,
                      tick: float = 0.25, hysteresis: int = 5,
                      margin: float = 0.10,
                      max_moves: int = 64, primary_qps: float = 20.0,
                      secondary_qps: float = 0.1, device: bool = False,
                      timeout: float = 120.0):
    """Spawn one descheduler process (`python -m kubernetes_tpu.controllers
    --mode deschedule`) against `base` and block until its ready line.
    Spawn TWO with distinct identities for the HA pair — they race the
    `descheduler` lease, one ACTIVE, one STANDBY; the standby re-derives
    the ACTIVE's `uid@node` intents after a kill9 (docs/DESCHEDULE.md).
    Returns (proc, metrics_url) — `metrics_url` serves the
    `descheduler_*` series."""
    from ..testing.faults import spawn_ready

    cmd = [sys.executable, "-m", "kubernetes_tpu.controllers",
           "--mode", "deschedule", "--api-url", base,
           "--identity", identity, "--lease-ttl", str(lease_ttl),
           "--tick", str(tick), "--hysteresis", str(hysteresis),
           "--margin", str(margin), "--max-moves", str(max_moves),
           "--primary-qps", str(primary_qps),
           "--secondary-qps", str(secondary_qps)]
    if device:
        cmd += ["--deschedule-device"]
    for url in fallbacks:
        cmd += ["--fallback", url]
    proc, m = spawn_ready(cmd, r"metrics on (127\.0\.0\.1:\d+)", cwd=cwd,
                          env=env, timeout=timeout)
    return proc, f"http://{m.group(1)}"


def stop_controller(proc, tail=None):
    """SIGTERM the controller and collect its final stats line
    (`{"controller_stats": ...}`) from a drained tail, if one was kept."""
    if proc.poll() is None:
        proc.terminate()
        try:
            proc.wait(timeout=15)
        except Exception:  # noqa: BLE001
            proc.kill()
    if tail is None:
        return None
    time.sleep(0.1)  # let the drain thread swallow the stats line
    for line in reversed(list(tail)):
        if "controller_stats" in line:
            try:
                return json.loads(line)["controller_stats"]
            except (ValueError, KeyError):
                return None
    return None


def run_sharded_cluster(
    n_shards: int,
    n_nodes: int,
    n_pods: int,
    *,
    lease_duration: float = 15.0,
    warm_pods: int = 256,
    zones: int = 50,
    node_capacity: Optional[dict] = None,
    pod_request: Optional[dict] = None,
    creator_threads: int = 8,
    timeout: float = 900.0,
    progress_cb: Optional[Callable[[int, ShardedCluster], None]] = None,
    flightrec_dir: str = "",
    replicas: int = 0,
    repl_lease: float = 2.0,
    hollow=None,
    hollow_procs: int = 1,
    mesh_devices: int = 0,
    child_env: Optional[dict] = None,
    node_lifecycle=None,
    flood=None,
    workload=None,
    deschedule=None,
    settle_s: float = 0.0,
    spec=None,
) -> dict:
    """The sharded SchedulingBasic shape end to end: create `n_nodes`,
    warm the shards with `warm_pods` (XLA compilation + first sessions land
    OUTSIDE the measured window, as every other bench here does), then
    measure wall-clock from the first measured-pod create until every
    measured pod is bound. `progress_cb(bound_count, cluster)` fires on
    every poll — chaos tests churn nodes / SIGKILL shards from it.

    With ``hollow`` set (a kubernetes_tpu/hollow profile dict or
    HollowProfile), the `n_nodes` fleet is IMPERSONATED by a hollow-node
    plane process — registration, heartbeats, capacity drift, and
    cordon/delete/re-register churn all run against the leader for the
    whole measured window — instead of being bulk-created inert.

    With ``flood`` set (``{"threads": T, "namespace": ns, "cpu": req}``),
    an adversarial-tenant flood hammers single-pod creates in its own
    namespace for the whole measured window — flood pods request an
    unsatisfiable CPU so they never consume the measured capacity; the
    result carries ``flood`` stats (posted / shed-at-429 / errors) next
    to the apiserver's flowcontrol counters (docs/RESILIENCE.md
    § overload & fairness), and every shard runs per-tenant fair dequeue.

    With ``workload`` set (``{"managers": 2, "lease_ttl": s, "tick": s,
    "autoscale": {...}, "trace": {...}}``), that many workload
    controller-manager processes run for the whole window as an HA pair
    racing the shared lease — ReplicaSet/Deployment/gang reconcile,
    optional cluster autoscaler and Borg-style trace feed — and the
    result carries each process's final stats (docs/RESILIENCE.md
    § workload controllers).

    With ``deschedule`` set (``{"managers": 2, "lease_ttl": s, "tick": s,
    "hysteresis": n, "margin": f, "max_moves": n}``), that many
    descheduler processes
    run as an HA pair racing their own lease — drift detection, what-if
    scored rebalance moves through the eviction subresource
    (docs/DESCHEDULE.md) — and the result carries each process's final
    stats plus the apiserver's eviction counters (the ``api`` filter
    includes ``eviction`` series). ``settle_s`` holds the cluster up for
    that many extra seconds AFTER the last measured pod binds — the
    rebalance window — still firing ``progress_cb`` on every poll so
    callers can assert invariants (e.g. PDB cleanliness) mid-rebalance.

    Returns the one-line-JSON-able result dict: pods/s, per-shard metric
    scrapes, apiserver conflict counters, peak per-process RSS, and a
    bound-exactly-once check (the store can't hold duplicates, so
    'duplicates' asserts bindings == bound pods)."""
    import threading as _threading
    from urllib.error import HTTPError

    from ..core.apiserver import fetch_paged, node_to_wire, pod_to_wire
    from ..testing.wrappers import make_node, make_pod

    cap = node_capacity or {"cpu": 32, "memory": "256Gi", "pods": 110}
    req = pod_request or {"cpu": "100m", "memory": "128Mi"}
    if spec is None:
        from ..fleet import FleetSpec
        # One declarative spec for the whole process tree — the conductor
        # owns bring-up order, readiness barriers, drained pipes, and
        # per-role supervision (docs/SCALE.md § fleet conductor).
        hollow_dict = None
        if hollow is not None:
            from ..hollow import HollowProfile
            prof = (hollow if isinstance(hollow, HollowProfile)
                    else HollowProfile.from_dict(dict(hollow)))
            prof.count = n_nodes
            if not prof.zones:
                prof.zones = zones
            hollow_dict = prof.to_dict()
        spec = FleetSpec(
            shards=n_shards, shard_lease_s=lease_duration,
            mesh_devices=mesh_devices,
            flightrec_dir=flightrec_dir,
            replicas=replicas, repl_lease_s=repl_lease,
            hollow=hollow_dict, hollow_procs=hollow_procs,
            node_lifecycle=node_lifecycle,
            # HA workload controller-manager pair (or singleton): both
            # race the shared PUT-CAS lease; drained tails keep their
            # SIGTERM stats lines collectable at teardown.
            workload=workload,
            deschedule=deschedule,
            env=dict(child_env or {}),
            fair_tenants=flood is not None,
            # A tightened workload lane makes shedding demonstrable at
            # test-box scale (stock lanes mostly ADMIT a paced flood — APF
            # bounds concurrency, not rate) while leaving enough seats for
            # the measured tenant's create/bind traffic; override via
            # flood["apf_workload"].
            apf_workload=(flood or {}).get("apf_workload", "4,8,4,2,0.5")
            if flood is not None else "",
            startup_timeout_s=max(timeout, 300.0))
    else:
        hollow = spec.hollow if spec.hollow is not None else hollow
        workload = spec.workload
        deschedule = spec.deschedule
        n_shards = spec.shards
        replicas = spec.replicas
        flightrec_dir = spec.flightrec_dir
        if hollow is not None:
            n_nodes = int(spec.hollow["count"])
    cluster = start_sharded_cluster(n_shards, spec=spec)
    base = cluster.base
    try:

        def post_many(path: str, wires: List[dict], chunk: int = 200) -> None:
            """Bulk creates (JSON-array POST): one HTTP turnaround per
            chunk instead of per object. Chunks stay modest so each bulk
            request's write-lock hold (~0.3ms/object) never stalls the
            bind plane for more than ~60ms. The creator is a client on
            the 429 surface like any other: sheds replay through
            core/backoff.py's Retry-After-honoring retry_call — the
            well-behaved tenant backs off and lands, never errors out."""
            from ..core.backoff import RetryConfig, retry_call

            cfg = RetryConfig(initial_backoff=0.05, max_backoff=1.0,
                              max_attempts=30, seed=11, retry_after_cap=2.0)
            parts = [wires[i:i + chunk] for i in range(0, len(wires), chunk)]
            with ThreadPoolExecutor(max_workers=creator_threads) as ex:
                list(ex.map(
                    lambda c: retry_call(
                        lambda c=c: _call(base, "POST", path, c,
                                          timeout=120), cfg),
                    parts))

        # Hollow fleets were registered during the conductor's bring-up
        # (its hollow stage barrier: every member acknowledged its exact
        # sub-range); inert fleets are bulk-created here.
        if hollow is None:
            nodes = []
            for i in range(n_nodes):
                b = make_node().name(f"node-{i}").capacity(dict(cap))
                if zones:
                    b = b.zone(f"zone-{i % zones}")
                nodes.append(node_to_wire(b.obj()))
            post_many("/api/v1/nodes", nodes)

        proto = make_pod().name("proto").req(dict(req)).labels(
            {"app": "sharded"}).obj()

        def pod_wires(prefix: str, n: int) -> List[dict]:
            return [pod_to_wire(proto.clone_from_template(f"{prefix}-{i}"))
                    for i in range(n)]

        # Follower-served reads (watch-cache read plane): progress polls go
        # to the FOLLOWER replicas when the plane has them — the leader's
        # cycles belong to the write plane. Each replica's watch cache
        # serves the summary under its own lock in the shared rv space;
        # `read_counts` proves where the reads actually landed.
        read_counts = {"leader": 0, "follower": 0}
        poll_bases = cluster.follower_urls or [base]
        poll_state = {"i": 0}

        def poll_summary() -> dict:
            for _ in range(len(poll_bases) + 1):
                url = poll_bases[poll_state["i"] % len(poll_bases)]
                poll_state["i"] += 1
                try:
                    s = _call(url, "GET", "/api/v1/pods?summary=true",
                              timeout=60)
                    read_counts["follower" if url != base else "leader"] += 1
                    return s
                except Exception:  # noqa: BLE001 - replica down: try next
                    continue
            # every follower unreachable: the leader still answers
            s = _call(base, "GET", "/api/v1/pods?summary=true", timeout=60)
            read_counts["leader"] += 1
            return s

        def wait_bound(target: int, deadline: float,
                       cb: Optional[Callable] = None) -> int:
            bound = 0
            while time.monotonic() < deadline:
                # summary=true: the apiserver counts instead of encoding the
                # full pod list — at 10k pods a full-list poll costs the
                # control plane more CPU than the binds themselves, CPU the
                # shard schedulers need on a small box.
                bound = poll_summary()["bound"]
                # Peak-RSS sampling rides the existing poll cadence: the
                # bounded-memory claim of the paged read plane is a
                # sampled number in every detail line. The bound count
                # feeds the conductor's throughput samples too.
                cluster.sample_rss()
                cluster.conductor.note_bound(bound)
                if cb is not None:
                    cb(bound)
                if bound >= target:
                    return bound
                time.sleep(0.5)
            return bound

        t_start = time.monotonic()
        if warm_pods:
            post_many("/api/v1/pods", pod_wires("warm", warm_pods))
            got = wait_bound(warm_pods, t_start + timeout / 2)
            if got < warm_pods:
                raise TimeoutError(
                    f"warm phase stalled: {got}/{warm_pods} bound")

        # Adversarial-tenant flood (overload plane acceptance): T threads
        # hammer single-pod creates in the flood namespace for the whole
        # measured window. Flood pods request an unsatisfiable CPU, so
        # they stress the write plane + scheduler queues without consuming
        # the capacity the measured pods bind into. Each worker keeps its
        # OWN counters (no racy shared increments); stats sum at stop.
        flood_stop = _threading.Event()
        flood_threads: List[_threading.Thread] = []
        flood_counts: List[dict] = []
        if flood is not None:
            flood_ns = flood.get("namespace", "flood-tenant")
            flood_proto = make_pod().name("proto").namespace(flood_ns).req(
                {"cpu": str(flood.get("cpu", 4096)),
                 "memory": "1Gi"}).obj()

            # Pacing: a shed worker backs off briefly (even an adversary
            # pays a network RTT, and an unpaced spin would measure the
            # harness box's CPU, not the plane's shedding). Each accepted
            # pod is deleted right back — the flood is a create/delete
            # churn hammer (TWO admissions per iteration), so it stresses
            # the write plane and the watch fanout at full rate without
            # accumulating an unbounded unschedulable pool in every
            # shard (that accumulation measures the harness box's memory,
            # not the plane's fairness).
            shed_pause = float(flood.get("shed_pause_s", 0.25))
            think = float(flood.get("think_s", 0.05))

            def flood_worker(widx: int) -> None:
                # "shed" counts CREATE 429s only — the FloodSheds floor
                # asserts the create path was shed, not the cleanup. A
                # shed delete-back retries (bounded) so accepted flood
                # pods don't leak into every shard's unschedulable pool
                # for the measured window.
                stats = {"posted": 0, "shed": 0, "errors": 0}
                flood_counts.append(stats)
                seq = 0
                while not flood_stop.is_set():
                    seq += 1
                    pod = flood_proto.clone_from_template(
                        f"flood-{widx}-{seq}")
                    try:
                        _call(base, "POST", "/api/v1/pods",
                              pod_to_wire(pod), timeout=30)
                        stats["posted"] += 1
                    except HTTPError as e:
                        if e.code == 429:
                            stats["shed"] += 1
                            flood_stop.wait(shed_pause)
                        else:
                            stats["errors"] += 1
                        continue
                    except Exception:  # noqa: BLE001 - transport noise
                        stats["errors"] += 1
                        continue
                    for _ in range(4):
                        try:
                            _call(base, "DELETE",
                                  f"/api/v1/pods/{pod.uid}", timeout=30)
                            break
                        except HTTPError as e:
                            if e.code != 429:
                                stats["errors"] += 1
                                break
                            flood_stop.wait(shed_pause)
                        except Exception:  # noqa: BLE001 - transport noise
                            stats["errors"] += 1
                            break
                    flood_stop.wait(think)

            for widx in range(int(flood.get("threads", 48))):
                t = _threading.Thread(target=flood_worker, args=(widx,),
                                      name=f"flood-{widx}", daemon=True)
                t.start()
                flood_threads.append(t)

        t0 = time.perf_counter()
        wires = pod_wires("pod", n_pods)
        t_wires = time.perf_counter()
        post_many("/api/v1/pods", wires)
        t_created = time.perf_counter()
        total = warm_pods + n_pods
        got = wait_bound(
            total, time.monotonic() + timeout,
            cb=(lambda b: progress_cb(b - warm_pods, cluster))
            if progress_cb is not None else None)
        elapsed = time.perf_counter() - t0
        if settle_s > 0:
            # Rebalance window: binds are done; keep the fleet up so the
            # descheduler can repair drift, polling progress_cb so chaos /
            # invariant callbacks (PDB cleanliness, exactly-once ledgers)
            # keep firing through the window.
            settle_deadline = time.monotonic() + settle_s
            while time.monotonic() < settle_deadline:
                if progress_cb is not None:
                    progress_cb(got - warm_pods, cluster)
                time.sleep(0.5)
        flood_result = None
        if flood is not None:
            flood_stop.set()
            for t in flood_threads:
                t.join(timeout=30)
            flood_result = {
                "namespace": flood.get("namespace", "flood-tenant"),
                "threads": len(flood_threads),
                "posted": sum(s["posted"] for s in flood_counts),
                "shed": sum(s["shed"] for s in flood_counts),
                "errors": sum(s["errors"] for s in flood_counts),
            }

        # Exactly-once oracle read, PAGED (`?limit=&continue=`): even the
        # harness's own final sweep never asks for a full-cluster
        # single-response body — apiserver_list_unpaged_total stays 0.
        pods = fetch_paged(base, "pods", limit=2000)
        bound = {p["uid"]: p["nodeName"] for p in pods if p["nodeName"]}
        hollow_stats = cluster.stop_hollow() if hollow is not None else None
        workload_stats = (cluster.conductor.stop_workload()
                          if workload is not None else None)
        deschedule_stats = (cluster.conductor.stop_deschedulers()
                            if deschedule is not None else None)
        shard_metrics = []
        e2e_hists = []
        watch_decode = []
        for url in cluster.alive_shard_urls():
            try:
                text = _fetch_metrics(url)  # one GET, parsed three ways
                shard_metrics.append(scrape_metrics(url, text=text))
                e2e_hists.append(scrape_histogram(
                    url, "scheduler_e2e_scheduling_duration_seconds",
                    text=text))
                # Per-shard decoded events/bytes by wire form — the
                # measurable 1/N of the shard-filtered watch plane — and
                # by codec (core/wire.py): which plane this shard's
                # decode actually ran on, and what it cost in bytes.
                watch_decode.append({
                    "events": scrape_labeled(
                        url, "scheduler_watch_decoded_events", "form",
                        text=text),
                    "bytes": scrape_labeled(
                        url, "scheduler_watch_decoded_bytes", "form",
                        text=text),
                    "events_by_codec": scrape_labeled(
                        url, "scheduler_watch_decoded_events", "codec",
                        text=text),
                    "bytes_by_codec": scrape_labeled(
                        url, "scheduler_watch_decoded_bytes", "codec",
                        text=text)})
            except Exception:  # noqa: BLE001 - a killed shard has no /metrics
                shard_metrics.append({})
                watch_decode.append({})
        api_text = _fetch_metrics(base)
        api_metrics = scrape_metrics(base, text=api_text)
        # Wire-plane summary (apiserver_wire_bytes_total{codec,surface}):
        # server-served bytes by codec and by surface — aggregated over
        # the LEADER and every follower replica (the shards' watch/list
        # reads land on followers when the plane has them) — plus the
        # per-shard decoded-bytes totals by codec: the one detail object
        # that proves WHICH plane (binary vs JSON) ran end-to-end.
        wire_by_codec: Dict[str, float] = {}
        wire_by_surface: Dict[str, float] = {}
        enc_us_by_surface: Dict[str, float] = {}
        deltas = {"minted": 0.0, "applied": 0.0}
        for url in [base] + list(cluster.follower_urls):
            try:
                text = api_text if url == base else _fetch_metrics(url)
                for k, v in scrape_labeled(
                        url, "apiserver_wire_bytes_total", "codec",
                        text=text).items():
                    wire_by_codec[k] = wire_by_codec.get(k, 0.0) + v
                for k, v in scrape_labeled(
                        url, "apiserver_wire_bytes_total", "surface",
                        text=text).items():
                    wire_by_surface[k] = wire_by_surface.get(k, 0.0) + v
                # Encode CPU per surface (PR 18): µs the server spent
                # building frames — divided by events it attributes any
                # shard-scaling gap to encode cost.
                for k, v in scrape_labeled(
                        url, "apiserver_wire_encode_micros_total",
                        "surface", text=text).items():
                    enc_us_by_surface[k] = enc_us_by_surface.get(k, 0.0) + v
                m = scrape_metrics(url, text=text)
                deltas["minted"] += m.get(
                    "apiserver_wire_deltas_minted_total", 0.0)
                deltas["applied"] += m.get(
                    "apiserver_wire_deltas_applied_total", 0.0)
            except Exception:  # noqa: BLE001 - replica down mid-teardown
                continue
        wire_summary = {
            "server_bytes_by_codec": wire_by_codec,
            "server_bytes_by_surface": wire_by_surface,
            "server_encode_us_by_surface": {
                k: round(v, 1) for k, v in enc_us_by_surface.items()},
            "deltas": {k: int(v) for k, v in deltas.items()},
            "shard_decoded_bytes_by_codec": [
                wd.get("bytes_by_codec", {}) for wd in watch_decode],
        }
        # Follower-served /metrics/resources: one scrape off a follower
        # replica proves the per-pod resource read plane serves away from
        # the leader (the same watch-cache snapshot, shared rv space).
        resource_series = None
        try:
            req = urlrequest.Request(
                (cluster.follower_urls[0] if cluster.follower_urls else base)
                + "/metrics/resources")
            with urlrequest.urlopen(req, timeout=30) as resp:
                resource_series = sum(
                    1 for ln in resp.read().decode().splitlines()
                    if ln.startswith("kube_pod_resource_request{"))
        except Exception:  # noqa: BLE001 - replica down mid-teardown
            pass
        # Cross-shard e2e latency truth (queue admission -> bound): merged
        # cumulative buckets, the p50/p99 bench.py --shards reports.
        e2e = merge_histograms(e2e_hists)
        e2e_ms = None
        if e2e is not None and e2e["count"]:
            e2e_ms = {
                "p50": round(histogram_percentile(e2e, 0.50) * 1e3, 3),
                "p99": round(histogram_percentile(e2e, 0.99) * 1e3, 3),
                "count": int(e2e["count"]),
            }
        # Replication detail: per-replica role/lag (leader + followers) —
        # the bench.py --shards --replicas detail line.
        replication = None
        if cluster.follower_urls:
            replication = []
            for url in [base] + cluster.follower_urls:
                try:
                    rm = scrape_metrics(url)
                    replication.append({
                        "url": url,
                        "role": int(rm.get("apiserver_replication_role", 0)),
                        "lag": int(rm.get(
                            "apiserver_replication_lag_records", 0)),
                        "failovers": int(rm.get(
                            "apiserver_failover_total", 0)),
                        # reads THIS replica's watch cache served — the
                        # counter proving follower-served polls landed here
                        "cacheHits": int(rm.get(
                            "apiserver_watch_cache_hits_total", 0)),
                        # paged-plane truth per replica: the shards list
                        # from FOLLOWERS, so the zero-unpaged claim must
                        # hold on every replica, not just the leader
                        "listPages": int(rm.get(
                            "apiserver_list_pages_total", 0)),
                        "listUnpaged": int(rm.get(
                            "apiserver_list_unpaged_total", 0)),
                        # watch-plane health per replica: a relist means a
                        # watcher fell off the cache ring and re-LISTed —
                        # the 100k fusion row pins this to zero everywhere
                        "relistedWatches": int(rm.get(
                            "apiserver_relisted_watches_total", 0)),
                    })
                except Exception:  # noqa: BLE001 - replica down
                    replication.append({"url": url, "role": -1})
        return {
            "shards": n_shards,
            "replicas": replicas,
            # The conductor's consolidated line: stage timeline,
            # per-member supervision state (restarts are NEVER silent),
            # per-role RSS peaks, throughput window, artifact count.
            "fleet": cluster.conductor.detail(),
            "replication": replication,
            "nodes": n_nodes,
            "pods": n_pods,
            "bound": got - warm_pods,
            "all_bound": got >= total,
            "elapsed_s": round(elapsed, 2),
            # Phase split of the measured window: template/wire encode,
            # create POSTs, and the bind tail after the last create — tells
            # an arrival-limited run from a scheduler-limited one.
            "wire_encode_s": round(t_wires - t0, 2),
            "create_s": round(t_created - t_wires, 2),
            "drain_after_create_s": round(t0 + elapsed - t_created, 2),
            "pods_per_sec": round(n_pods / elapsed, 1) if elapsed > 0 else 0.0,
            "distinct_bound_pods": len(bound),
            "killed_shards": list(cluster.killed),
            "e2e_ms": e2e_ms,
            "flightrec_dir": flightrec_dir,
            # Peak per-process RSS (MiB), sampled every progress poll —
            # the bounded-memory claim as a number.
            "rss_mb": cluster.sample_rss(),
            "hollow": hollow_stats,
            # Workload controller-manager stats (HA pair): per-process
            # final stats lines — active/standby split, takeovers,
            # reconcile counters, autoscaler adds/removes.
            "workload": workload_stats,
            # Descheduler manager stats (HA pair): moves by strategy,
            # blocked-by-reason, what-if batch timings, final utilization
            # stddev — the drift-repair plane's exactly-once story pairs
            # with the "eviction" series in the api filter below.
            "deschedule": deschedule_stats,
            # Where the progress/summary reads landed (follower-served read
            # plane) + one follower /metrics/resources scrape's series count.
            "read_plane": dict(read_counts,
                               resource_series=resource_series),
            "watch_decode": watch_decode,
            "wire": wire_summary,
            # Overload plane (core/flowcontrol.py): flood-tenant stats +
            # the leader's per-priority-level admission counters ride the
            # bench detail line ("flowcontrol" matches the api filter).
            "flood": flood_result,
            "flowcontrol": {
                metric: scrape_labeled(
                    base, f"apiserver_flowcontrol_{metric}_total",
                    "priority_level", text=api_text)
                for metric in ("rejected", "dispatched", "queued")
            },
            "api": {k: v for k, v in api_metrics.items()
                    if "conflict" in k or "lease" in k
                    or "replication" in k or "failover" in k
                    or "watch" in k or "list" in k
                    or "snapshot" in k or "heartbeat" in k
                    or "flowcontrol" in k or "eviction" in k},
            "shard_metrics": [
                {k: v for k, v in sm.items()
                 if k.startswith(("scheduler_shard_",
                                  "scheduler_bind_conflict",
                                  "scheduler_hint_",
                                  "scheduler_eviction_requeues",
                                  "scheduler_queue_starvation"))}
                for sm in shard_metrics],
        }
    finally:
        cluster.stop()
