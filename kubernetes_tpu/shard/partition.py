"""Deterministic pod → shard partitioner.

Every scheduler process must compute the SAME answer with no coordination,
across interpreter runs (Python's builtin ``hash`` is salted per process —
useless here): crc32 of a stable key, mod the shard count.

PodGroup members are pinned WHOLE to one shard by keying on the group, not
the pod: gang scheduling is all-or-nothing within one scheduler's cycle
(schedule_pod_group), so a gang split across shards could deadlock half-
placed. Composite trees follow the same rule through their leaf groups'
shared namespace/group keys only when they name the same group; composite
scheduling across groups remains a single-shard concern — the partitioner
routes by the pod's own group, and a composite whose leaves hash apart is
simply owned by whichever shards own its leaves (each schedules only the
leaves it admits; min-count gating keeps half-trees parked).
"""

from __future__ import annotations

import zlib


def shard_key(pod) -> str:
    """The stable partition key: the gang's identity when the pod belongs
    to one (pin the whole group to one shard), else the pod uid."""
    group = getattr(pod, "pod_group", "")
    if group:
        return f"pg:{pod.namespace}/{group}"
    return pod.uid


def shard_of_key(key: str, count: int) -> int:
    return zlib.crc32(key.encode()) % max(1, count)


def shard_of_pod(pod, count: int) -> int:
    return shard_of_key(shard_key(pod), count)
