"""ShardPlane: N in-process shard schedulers against one apiserver.

Each shard is a full scheduler stack — its OWN HTTPClientset (reflector
threads, informer cache, decoded object copies), queue, cache, and device
sessions — driven by its own thread, so cross-shard interleaving is real
(watch-feed lag between shards is what makes optimistic conflicts
possible). The chaos/conflict tests and in-process experiments build this;
production-shaped scale-out runs one shard per OS process instead
(``python -m kubernetes_tpu --shard-index i --shard-count n``, see
shard/harness.py).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional

from .member import ShardMember


class _ShardHandle:
    def __init__(self, index: int, scheduler, clientset, member):
        self.index = index
        self.scheduler = scheduler
        self.clientset = clientset  # the raw HTTPClientset (close() target)
        self.member = member
        self.errors: List[BaseException] = []
        self.alive = True
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _run(self) -> None:
        s = self.scheduler
        while not self._stop.is_set():
            try:
                if self.member is not None:
                    self.member.tick()
                if not s.run_until_idle(max_cycles=256):
                    time.sleep(0.005)
            except Exception as e:  # noqa: BLE001 - the assertion target
                self.errors.append(e)
                return

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run, name=f"shard-{self.index}", daemon=True)
        self._thread.start()

    def kill(self) -> None:
        """Simulated SIGKILL: stop driving and tear the reflectors down —
        the lease stops renewing, the queue/cache state dies unobserved."""
        self.alive = False
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
        if self.member is not None:
            self.member.stop()  # the lease now ages toward expiry
        close = getattr(self.clientset, "close", None)
        if close is not None:
            close()


class ShardPlane:
    def __init__(self, api_url: str, n_shards: int,
                 lease_duration: float = 2.0,
                 scheduler_factory: Optional[Callable] = None,
                 with_members: bool = True):
        """`scheduler_factory(clientset)` builds one shard's scheduler
        (default: TPUScheduler, single-device, modest batch). With
        ``with_members=False`` no admission partition is installed — every
        shard admits every pod, the deliberate worst case the bind-conflict
        storm test runs."""
        from ..core.apiserver import HTTPClientset
        from ..core.clientset import RetryingClientset

        if scheduler_factory is None:
            def scheduler_factory(cs):
                from ..models import TPUScheduler
                return TPUScheduler(clientset=cs, mesh=None, max_batch=64)
        self.shards: List[_ShardHandle] = []
        for i in range(n_shards):
            http_cs = HTTPClientset(api_url)
            sched = scheduler_factory(RetryingClientset(http_cs))
            member = None
            if with_members:
                member = ShardMember(sched, i, n_shards,
                                     lease_duration=lease_duration)
                member.start_renewer()  # alive through in-thread compiles
            self.shards.append(_ShardHandle(i, sched, http_cs, member))

    def start(self) -> None:
        for sh in self.shards:
            sh.start()

    def kill(self, index: int) -> None:
        self.shards[index].kill()

    def alive_shards(self) -> List[_ShardHandle]:
        return [sh for sh in self.shards if sh.alive]

    def errors(self) -> List[BaseException]:
        return [e for sh in self.shards for e in sh.errors]

    def total(self, attr: str) -> float:
        return sum(getattr(sh.scheduler, attr, 0) for sh in self.shards)

    def close(self) -> None:
        for sh in self.shards:
            if sh.alive:
                sh.kill()
