"""ShardMap: the durable lease table behind shard ownership.

One lease record per shard slot (``shard-<i>``), created and renewed
through the apiserver's ``/api/v1/leases`` surface (core/apiserver.py):
PUT is acquire-or-renew with holder-CAS semantics, expiry is computed
SERVER-side against the server's own monotonic clock (shards never compare
clocks), and every upsert rides the WAL so the holder table survives a
control-plane ``kill -9``.

Ownership is **possession-by-observation**, in the optimistic spirit of
the rest of the plane: each member renews only its OWN slot's lease, and
every refresh recomputes which EXPIRED slots this member is the ring
successor of. No adoption write exists to race over — if two members
briefly disagree during a refresh-skew window, both admit the range and
the binding subresource's 409 resolves every double-schedule. When a dead
shard returns (same slot, fresh process), its first renewal makes the slot
alive again and the adopter's next refresh drops the range automatically —
failback without a handoff protocol.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional, Set

LEASE_PREFIX = "shard-"


def lease_name(index: int) -> str:
    return f"{LEASE_PREFIX}{index}"


def _slot_of(name: str) -> Optional[int]:
    if not name.startswith(LEASE_PREFIX):
        return None
    try:
        return int(name[len(LEASE_PREFIX):])
    except ValueError:
        return None


class ShardMap:
    """A member's view of the shard lease table + the deterministic
    ownership rule every member computes identically from it."""

    def __init__(self, clientset, index: int, count: int,
                 lease_duration: float = 3.0, identity: str = "",
                 now: Callable[[], float] = time.monotonic):
        self.cs = clientset
        self.index = index
        self.count = count
        self.lease_duration = lease_duration
        self.identity = identity or f"scheduler-{lease_name(index)}"
        self.now = now
        # Startup grace: a slot with NO lease record yet may just be a peer
        # that hasn't started; it becomes adoptable only after one full
        # lease period from OUR start (a crashed peer that did start leaves
        # an expired record, which is adoptable immediately on expiry).
        self._vacant_adoptable_at = now() + lease_duration
        self.last_view: List[dict] = []

    def renew_own(self) -> bool:
        """Acquire-or-renew this member's own slot; False = CAS loss
        (another identity holds the slot — a misconfigured twin or a
        superseding replacement; the member must stop admitting)."""
        return self.cs.upsert_lease(
            lease_name(self.index), self.identity, self.lease_duration
        ) is not None

    def refresh(self) -> List[dict]:
        self.last_view = [l for l in self.cs.list_leases()
                          if _slot_of(l["name"]) is not None]
        return self.last_view

    def compute_owned(self, own_ok: bool) -> Set[int]:
        """The slots this member owns under the ring-successor rule:
        its own slot (when its lease holds), plus every expired/vacant slot
        whose first alive successor (scanning j+1, j+2, … mod count) is this
        member. Every member computes this from the same server-evaluated
        lease table, so disagreement is bounded by refresh skew — and any
        overlap is resolved by bind 409s, not by a coordination protocol."""
        alive: Set[int] = set()
        seen: Set[int] = set()
        for lease in self.last_view:
            slot = _slot_of(lease["name"])
            if slot is None or slot >= self.count:
                continue
            seen.add(slot)
            if not lease["expired"]:
                alive.add(slot)
        if own_ok:
            alive.add(self.index)
        else:
            alive.discard(self.index)
        owned: Set[int] = {self.index} if own_ok else set()
        if not own_ok:
            return owned
        vacant_ok = self.now() >= self._vacant_adoptable_at
        for j in range(self.count):
            if j in alive or j == self.index:
                continue
            if j not in seen and not vacant_ok:
                continue  # never-started peer, still inside startup grace
            for k in range(1, self.count + 1):
                succ = (j + k) % self.count
                if succ in alive:
                    if succ == self.index:
                        owned.add(j)
                    break
        return owned
