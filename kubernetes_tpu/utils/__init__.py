from .interning import Interner

__all__ = ["Interner"]
