"""String interning codebooks.

Everything string-ish (label keys/values, taint keys, resource names,
namespaces, node names) must become small integer ids before it can live in
device tensors (SURVEY.md §7 step 2). An Interner is append-only: ids are
stable for the lifetime of the codebook, which is what makes incremental
device uploads sound.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional


class Interner:
    """Append-only string -> int id codebook. id 0 is reserved for MISSING."""

    MISSING = 0

    def __init__(self, name: str = ""):
        self.name = name
        self._to_id: Dict[str, int] = {}
        self._to_str: List[Optional[str]] = [None]  # index 0 = missing

    def intern(self, s: str) -> int:
        i = self._to_id.get(s)
        if i is None:
            i = len(self._to_str)
            self._to_id[s] = i
            self._to_str.append(s)
        return i

    def lookup(self, s: str) -> int:
        """Return existing id or MISSING (never allocates)."""
        return self._to_id.get(s, self.MISSING)

    def string(self, i: int) -> Optional[str]:
        return self._to_str[i] if 0 < i < len(self._to_str) else None

    def __len__(self) -> int:
        return len(self._to_str)

    def __contains__(self, s: str) -> bool:
        return s in self._to_id
