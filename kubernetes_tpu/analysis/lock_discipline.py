"""lock-discipline checker: the apiserver/WAL locking contract (PR 2/PR 5).

Incidents this encodes (docs/ANALYSIS.md):

- PR 2 serialized all mutating verbs under one server write lock after
  check-then-act races (double bind, duplicate create) and made the WAL
  append happen under the broadcast lock BEFORE watcher fanout, so an
  event a watcher saw is always recoverable;
- the same PR deliberately moved request-body reads OUTSIDE the write
  lock — a stalled sender must not wedge the whole write plane.

Rules (scoped to core/apiserver.py + core/wal.py + core/watchcache.py +
kubernetes_tpu/replication/ + kubernetes_tpu/hollow/):

- ``verb-write-lock``: every mutating HTTP verb handler (do_POST/do_PUT/
  do_DELETE) either takes ``_write_lock`` itself or only delegates to a
  method that does;
- ``wal-under-broadcast-lock``: every ``persistence.append(...)`` — and
  every call to the frame-append primitive ``_repl_append``, whose
  contract is caller-holds-the-lock — is lexically inside a
  ``with ..._lock:`` region;
- ``wal-before-fanout``: in a function that both WAL-appends and fans out
  to ``_watchers``, the append precedes the fanout loop and the fanout
  itself runs under the broadcast lock (this is what makes a follower's
  ``apply_frame`` crash-consistent: an event a LOCAL watcher saw is
  already in the local WAL);
- ``repl-apply-write-lock``: the replication mutators that rewrite store
  state outside a verb handler (``apply_frame``, ``install_snapshot``,
  ``promote``, ``demote``) must take ``_write_lock`` — they race verb
  handlers on a promoted replica otherwise;
- ``no-blocking-read-under-lock``: no blocking socket/request read
  (``_read_body``, ``rfile.read``, ``recv``, ``accept``, ``readline``,
  ``getresponse``, ``urlopen``) happens while any lock is held;
- ``no-blocking-send-under-lock``: no blocking socket send
  (``sendall``, ``wfile.write``) happens while any lock is held — the
  replication ship endpoint streams to followers with arbitrary
  backpressure, and one stalled follower socket must never wedge the
  broadcast/write plane (PR 9; the ship loop drains a per-follower
  queue instead);
- ``no-render-under-write-lock``: metrics exposition
  (``expose_metrics``/``.expose``) never runs while holding the write
  lock — series rendering iterates every label set and a scrape that
  serializes against the write plane stalls binds for the whole render
  (PR 8: expose paths snapshot-copy instead; ROADMAP notes
  ``/metrics/resources`` contending with the write plane);
- ``no-read-serving-under-write-lock``: the watch-cache read plane
  (core/watchcache.py — ``list_wire``/``read_summary``/``get_many``/
  ``events_since``/``render_resources``, plus the paged-LIST
  continuation path: ``list_page`` page serving and ``mint_continue``
  token minting) must never be called with ``_write_lock`` held — the
  whole point of the cache is a read plane that does not contend with
  binds, and a 50k-node paged list serialized against the bind plane
  would stall it once per page; the cache's MUTATORS (``note_event``/
  ``reinstall``) must run under the broadcast lock, after the WAL append
  (the frame a cached event came from must already be durable).
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set, Tuple

from .base import Checker, Finding, ModuleSource, attr_chain, register

MUTATING_VERBS = ("do_POST", "do_PUT", "do_DELETE")
BLOCKING_READ_ATTRS = {"_read_body", "recv", "recv_into", "accept",
                       "readline", "getresponse", "urlopen"}
BLOCKING_SEND_ATTRS = {"sendall"}
# Replication mutators that rewrite store state outside a verb handler —
# each must serialize on the server write lock (rule repl-apply-write-lock).
REPL_MUTATORS = ("apply_frame", "install_snapshot", "promote", "demote")
# The frame-append primitive: persistence.append lives INSIDE it (exempt
# there), and every CALL to it must be under the broadcast lock instead.
FRAME_APPEND_PRIMITIVE = "_repl_append"
# The commit→read-plane fanout primitive (watch cache install + watcher
# routing): a CALL to it is a fanout — same after-the-WAL-append +
# under-the-broadcast-lock obligations as a raw `_watchers` loop.
FANOUT_PRIMITIVE = "_fan_event"
# Watch-cache read plane (core/watchcache.py): reads must never hold the
# write lock; mutators must hold the broadcast lock (rule
# no-read-serving-under-write-lock). The paged-LIST continuation path —
# page serving (`list_page`) AND token minting (`mint_continue`) — is a
# read too: minting a token under the write lock would serialize every
# page of a 50k-node list against the bind plane.
WATCHCACHE_READS = {"list_wire", "read_summary", "get_many",
                    "events_since", "render_resources",
                    "list_page", "mint_continue"}
WATCHCACHE_MUTATORS = {"note_event", "reinstall"}


def _lock_name(expr: ast.AST) -> Optional[str]:
    """The lock attribute a `with` item acquires, e.g. '_write_lock' for
    `with server._write_lock:`. Only attr/name endings in 'lock' count."""
    chain = attr_chain(expr)
    if chain and chain[-1].endswith("lock"):
        return chain[-1]
    return None


class _FunctionScan:
    def __init__(self, fn: ast.FunctionDef):
        self.fn = fn
        self.acquires: Set[str] = set()          # lock attrs taken directly
        self.calls: Set[str] = set()             # callee terminal names
        # (lineno, locks_held) per interesting site:
        self.wal_appends: List[Tuple[int, Tuple[str, ...]]] = []
        self.raw_appends: List[Tuple[int, Tuple[str, ...]]] = []
        self.fanouts: List[Tuple[int, Tuple[str, ...]]] = []
        self.blocking_reads: List[Tuple[int, Tuple[str, ...], str]] = []
        self.blocking_sends: List[Tuple[int, Tuple[str, ...], str]] = []
        self.metric_renders: List[Tuple[int, Tuple[str, ...], str]] = []
        self.cache_reads: List[Tuple[int, Tuple[str, ...], str]] = []
        self.cache_mutations: List[Tuple[int, Tuple[str, ...], str]] = []
        self._walk(fn, ())

    def _walk(self, node: ast.AST, held: Tuple[str, ...]) -> None:
        for child in ast.iter_child_nodes(node):
            self._visit(child, held)

    def _visit(self, node: ast.AST, held: Tuple[str, ...]) -> None:
        if isinstance(node, ast.With):
            # Uniform handling wherever the With appears — including as the
            # DIRECT first statement of an outer With's body (a nested
            # `with self._write_lock: with self._lock:` must hold both).
            inner = held
            for item in node.items:
                lock = _lock_name(item.context_expr)
                if lock is not None:
                    self.acquires.add(lock)
                    inner = inner + (lock,)
                for sub in ast.walk(item.context_expr):
                    self._classify(sub, held)
            for stmt in node.body:
                self._visit(stmt, inner)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return  # nested defs are their own scan
        self._classify(node, held)
        self._walk(node, held)

    def _classify(self, node: ast.AST, held: Tuple[str, ...]) -> None:
        if isinstance(node, ast.For):
            for sub in ast.walk(node.iter):
                if ((isinstance(sub, ast.Attribute) and sub.attr == "_watchers")
                        or (isinstance(sub, ast.Name) and sub.id == "_watchers")):
                    self.fanouts.append((node.lineno, held))
        if not isinstance(node, ast.Call):
            return
        chain = attr_chain(node.func)
        if chain:
            self.calls.add(chain[-1])
        if len(chain) >= 2 and chain[-1] == "append" and chain[-2] == "persistence":
            self.wal_appends.append((node.lineno, held))
            self.raw_appends.append((node.lineno, held))
        if chain and chain[-1] == FRAME_APPEND_PRIMITIVE:
            # A call to the frame-append primitive IS a WAL append: same
            # under-the-lock + before-fanout obligations at the call site.
            self.wal_appends.append((node.lineno, held))
        if chain and chain[-1] == FANOUT_PRIMITIVE:
            # A call to the fanout primitive IS a watcher fanout (the raw
            # `_watchers` loop moved inside it): the call site keeps the
            # after-the-append + under-the-broadcast-lock obligations —
            # modeled as a fanout AND a cache mutation.
            self.fanouts.append((node.lineno, held))
            self.cache_mutations.append((node.lineno, held, FANOUT_PRIMITIVE))
        # Watch-cache calls go through a subscripted registry
        # (`self.watch_cache[kind].note_event(...)`) — attr_chain answers []
        # for non-Name bases, so resolve the TERMINAL attribute directly
        # (the method names are distinctive by design).
        term = chain[-1] if chain else (
            node.func.attr if isinstance(node.func, ast.Attribute) else None)
        if term in WATCHCACHE_READS and "_write_lock" in held:
            self.cache_reads.append((node.lineno, held, term))
        if term in WATCHCACHE_MUTATORS:
            self.cache_mutations.append((node.lineno, held, term))
        if chain and chain[-1] in BLOCKING_READ_ATTRS and held:
            self.blocking_reads.append((node.lineno, held, chain[-1]))
        if chain and chain[-1] in BLOCKING_SEND_ATTRS and held:
            self.blocking_sends.append((node.lineno, held, chain[-1]))
        # wfile.write is a response-socket send even though 'write' is
        # generic (file-handle writes under a lock — the WAL itself — are
        # deliberate and exempt).
        if (len(chain) >= 2 and chain[-1] == "write" and chain[-2] == "wfile"
                and held):
            self.blocking_sends.append((node.lineno, held, "wfile.write"))
        if (chain and chain[-1] in ("expose_metrics", "expose")
                and "_write_lock" in held):
            self.metric_renders.append((node.lineno, held, chain[-1]))
        # rfile.read is a request-body read even though 'read' is generic
        if (len(chain) >= 2 and chain[-1] == "read" and chain[-2] == "rfile"
                and held):
            self.blocking_reads.append((node.lineno, held, "rfile.read"))


@register
class LockDisciplineChecker(Checker):
    id = "lock-discipline"
    description = ("apiserver/WAL locking contract: write-lock on mutating "
                   "verbs, WAL append under the broadcast lock before "
                   "fanout, no blocking reads under a held lock")

    SCOPE = ("core/apiserver.py", "core/wal.py", "core/watchcache.py")
    SCOPE_DIRS = ("replication/", "hollow/", "controllers/", "fleet/")

    def applies_to(self, relpath: str) -> bool:
        if any(relpath == p or relpath.endswith("/" + p)
               for p in self.SCOPE):
            return True
        return any(("/" + d) in relpath or relpath.startswith(d)
                   for d in self.SCOPE_DIRS)

    def check(self, mod: ModuleSource) -> List[Finding]:
        out: List[Finding] = []
        fns: List[ast.FunctionDef] = [
            n for n in ast.walk(mod.tree)
            if isinstance(n, ast.FunctionDef)]
        # One scan PER DEF, not per name: the same file defines e.g.
        # upsert_lease on both APIServer (locks) and HTTPClientset (a REST
        # call) — keying by name would silently drop one of them.
        scans: List[_FunctionScan] = [_FunctionScan(fn) for fn in fns]

        # Functions that serialize on the write lock themselves — a verb
        # handler may delegate to one instead of taking the lock inline
        # (do_PUT's lease path delegates to upsert_lease, which CAS-es
        # under the write lock; wrapping it twice would deadlock).
        # Name-level: delegation is resolved by callee name.
        serializers = {s.fn.name for s in scans
                       if "_write_lock" in s.acquires}

        for fn, scan in zip(fns, scans):
            if fn.name in MUTATING_VERBS:
                if ("_write_lock" not in scan.acquires
                        and not (scan.calls & serializers)):
                    out.append(Finding(
                        self.id, "verb-write-lock", mod.path, fn.lineno,
                        f"mutating verb handler {fn.name} neither takes "
                        "_write_lock nor delegates to a method that does "
                        "(check-then-act races: double bind, dup create)"))
            for lineno, held in scan.wal_appends:
                if (fn.name == FRAME_APPEND_PRIMITIVE
                        and (lineno, held) in scan.raw_appends):
                    # The primitive's own persistence.append: its contract
                    # is caller-holds-the-lock, enforced at call sites.
                    continue
                if not any(lock == "_lock" for lock in held):
                    out.append(Finding(
                        self.id, "wal-under-broadcast-lock", mod.path, lineno,
                        "WAL/frame append outside a `with ..._lock:` "
                        "region — a fanned-out event could be lost on crash"))
            if fn.name in REPL_MUTATORS and "_write_lock" not in scan.acquires:
                out.append(Finding(
                    self.id, "repl-apply-write-lock", mod.path, fn.lineno,
                    f"replication mutator {fn.name} does not take "
                    "_write_lock — on a promoted replica it races the "
                    "mutating verb handlers over the same store"))
            for lineno, held, what in scan.blocking_sends:
                out.append(Finding(
                    self.id, "no-blocking-send-under-lock", mod.path, lineno,
                    f"blocking socket send ({what}) under held lock(s) "
                    f"{'/'.join(held)} — one stalled follower/watch socket "
                    "wedges the broadcast/write plane; drain a per-stream "
                    "queue outside the lock instead"))
            if scan.wal_appends and scan.fanouts:
                first_fanout = min(l for l, _ in scan.fanouts)
                first_append = min(l for l, _ in scan.wal_appends)
                if first_append > first_fanout:
                    out.append(Finding(
                        self.id, "wal-before-fanout", mod.path, first_fanout,
                        f"watcher fanout in {fn.name} precedes the WAL "
                        "append — an event a watcher saw must already be "
                        "durable"))
                for lineno, held in scan.fanouts:
                    if not any(lock == "_lock" for lock in held):
                        out.append(Finding(
                            self.id, "wal-before-fanout", mod.path, lineno,
                            f"watcher fanout in {fn.name} outside the "
                            "broadcast lock — events could interleave with "
                            "backlog/WAL ordering"))
            for lineno, held, what in scan.blocking_reads:
                out.append(Finding(
                    self.id, "no-blocking-read-under-lock", mod.path, lineno,
                    f"blocking read ({what}) under held lock(s) "
                    f"{'/'.join(held)} — a stalled sender wedges every "
                    "writer (PR 2 keeps body reads outside the write lock)"))
            for lineno, held, what in scan.metric_renders:
                out.append(Finding(
                    self.id, "no-render-under-write-lock", mod.path, lineno,
                    f"metrics render ({what}) under held lock(s) "
                    f"{'/'.join(held)} — a scrape serialized against the "
                    "write plane stalls binds for the whole render; expose "
                    "paths snapshot-copy series data instead"))
            for lineno, held, what in scan.cache_reads:
                out.append(Finding(
                    self.id, "no-read-serving-under-write-lock", mod.path,
                    lineno,
                    f"watch-cache read ({what}) under held lock(s) "
                    f"{'/'.join(held)} — the read plane exists so that "
                    "list/resume/metrics reads never contend with the "
                    "write plane; serve under the cache's own lock only"))
            cache_mutations = scan.cache_mutations
            if fn.name == FANOUT_PRIMITIVE:
                # The fanout primitive OWNS the raw note_event + watcher
                # loop; its caller-holds-the-broadcast-lock contract is
                # enforced at call sites (same shape as _repl_append).
                cache_mutations = []
            for lineno, held, what in cache_mutations:
                if not any(lock == "_lock" for lock in held):
                    out.append(Finding(
                        self.id, "no-read-serving-under-write-lock",
                        mod.path, lineno,
                        f"watch-cache mutation ({what}) outside the "
                        "broadcast lock — cache/ring order must be commit "
                        "order, or a resumed watcher replays a different "
                        "history than the WAL holds"))
            if scan.wal_appends and cache_mutations:
                first_mut = min(l for l, _h, _w in cache_mutations)
                first_append = min(l for l, _ in scan.wal_appends)
                if first_mut < first_append:
                    out.append(Finding(
                        self.id, "no-read-serving-under-write-lock",
                        mod.path, first_mut,
                        f"watch-cache mutation in {fn.name} precedes the "
                        "WAL append — a cached event a reader served must "
                        "already be durable"))
        return out
