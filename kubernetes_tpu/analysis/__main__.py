"""CLI: ``python -m kubernetes_tpu.analysis [--json] [--root DIR]
[--checker ID ...]``.

Scans the package tree (or ``--root``) with every registered checker and
exits nonzero on any finding OR any stale allowlist entry — so it gates CI
exactly like the tier-1 wrapper test.
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys

from .base import PKG_ROOT, all_checkers, analyze, checker_by_id


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m kubernetes_tpu.analysis")
    ap.add_argument("--root", default=None,
                    help="directory tree to scan (default: the installed "
                         "kubernetes_tpu package)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable report on stdout")
    ap.add_argument("--checker", action="append", default=None,
                    metavar="ID", help="run only the named checker(s)")
    ap.add_argument("--list-checkers", action="store_true")
    args = ap.parse_args(argv)

    if args.list_checkers:
        for c in all_checkers():
            print(f"{c.id}: {c.description}")
        return 0

    checkers = ([checker_by_id(cid) for cid in args.checker]
                if args.checker else None)
    root = pathlib.Path(args.root).resolve() if args.root else PKG_ROOT
    report = analyze(root=root, checkers=checkers)

    if args.as_json:
        print(json.dumps(report.to_json(), indent=2))
    else:
        for f in report.findings:
            print(str(f))
        for a in report.unused_allows:
            print(f"stale allowlist entry: {a.checker}:{a.path}:{a.line} "
                  f"({a.reason}) — nothing left to suppress, delete it")
        n = len(report.findings)
        print(f"{report.files_scanned} files scanned, {n} finding(s), "
              f"{len(report.suppressed)} suppressed, "
              f"{len(report.unused_allows)} stale allowlist entr(y/ies)")
    return 0 if report.clean else 1


if __name__ == "__main__":
    sys.exit(main())
