"""sharding-discipline checker: jits that touch sharded state pin their
shardings.

Incident class (ISSUE 15, the mesh-first device plane): a mesh session's
kernel trace keys on its inputs' COMMITTED placements. Every jit that
rewrites a piece of sharded session state — the dirty-row scatter, the
carry patch — must pin ``out_shardings`` (and/or ``in_shardings``) to the
session's committed shardings, or XLA hands back GSPMD-chosen placements:
everything still computes correctly, every test still passes, and the next
dispatch silently RETRACES the session kernel (~1 min of XLA compile inside
the measured window per occurrence). That placement-drift-then-retrace
failure mode is exactly what kept mesh sessions on the full-rebuild path
before the pinned patch seam landed (ops/device_state.py _sharded_scatter,
ops/kernel.py patch_carry_rows_pinned).

Rule (``bare-jit-on-sharded-state``): inside the sharded seam — any
function that takes a ``sharded_state``/``out_shardings`` parameter, or
that passes ``sharded_state=`` to a callee — a ``jax.jit``/``jit``/
``pjit`` call must carry an ``out_shardings`` or ``in_shardings`` keyword.
jits wrapping a ``shard_map(...)`` expression are exempt: shard_map's
in/out_specs ARE the pinned placement. (shard_map BODIES additionally join
the jit-purity and index-dtype scan scopes — enforced by those checkers
via jit_purity.jit_reachable_functions recognizing shard_map wrapping.)
"""

from __future__ import annotations

import ast
from typing import List

from .base import Checker, Finding, ModuleSource, attr_chain, register

SEAM_PARAMS = frozenset({"sharded_state", "out_shardings", "in_shardings"})


def _is_jit_call(node: ast.Call) -> bool:
    chain = attr_chain(node.func)
    return bool(chain) and chain[-1] in ("jit", "pjit")


def _wraps_shard_map(node: ast.Call) -> bool:
    """jax.jit(shard_map(...), ...): the specs pin the placement."""
    if not node.args:
        return False
    a0 = node.args[0]
    if isinstance(a0, ast.Call):
        chain = attr_chain(a0.func)
        return bool(chain) and chain[-1] == "shard_map"
    return False


def _in_sharded_seam(fn: ast.FunctionDef) -> bool:
    """The function's signature or body handles sharded session state."""
    args = fn.args
    names = {a.arg for a in (args.args + args.kwonlyargs
                             + args.posonlyargs)}
    if names & SEAM_PARAMS:
        return True
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            for kw in node.keywords:
                if kw.arg == "sharded_state":
                    return True
    return False


@register
class ShardingDisciplineChecker(Checker):
    id = "sharding-discipline"
    description = ("any jit compiled against sharded session state must "
                   "pin out_shardings/in_shardings (or wrap a shard_map) — "
                   "an unpinned jit hands back GSPMD-chosen placements and "
                   "the session kernel silently retraces on next dispatch")

    def applies_to(self, relpath: str) -> bool:
        return relpath.startswith(("ops/", "parallel/", "models/"))

    def check(self, mod: ModuleSource) -> List[Finding]:
        out: List[Finding] = []
        if mod.tree is None:
            return out
        for fn in ast.walk(mod.tree):
            if not isinstance(fn, ast.FunctionDef):
                continue
            if not _in_sharded_seam(fn):
                continue
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call) or not _is_jit_call(node):
                    continue
                if _wraps_shard_map(node):
                    continue
                kws = {kw.arg for kw in node.keywords}
                if kws & {"out_shardings", "in_shardings"}:
                    continue
                out.append(Finding(
                    self.id, "bare-jit-on-sharded-state", mod.path,
                    node.lineno,
                    "bare jax.jit inside the sharded-state seam "
                    f"(function {fn.name!r} handles sharded_state/"
                    "out_shardings) — pin out_shardings/in_shardings to "
                    "the session's committed placement or the next "
                    "dispatch retraces the session kernel"))
        return out
