"""deschedule-discipline checker: descheduler moves are scored and intended.

Incident class (ISSUE 20): the descheduler's whole value proposition is
that it only *improves* placements. An eviction call site in a
descheduler module that is not downstream of the scored-improvement
gate is a churn generator — it will happily evict a pod into an equal
or worse seat, and two near-balanced nodes will trade the same pod
forever (the ping-pong the hysteresis floor exists to break). And a
move emitted without the deterministic intent record breaks the
standby-replay contract: a takeover mid-wave re-plans the wave, and
only identical ``uid@node`` intents let the apiserver ledger absorb the
duplicates.

Rule ``move-without-scored-gate``: in a descheduler module under
``controllers/``, every function that emits an eviction — the funnel
verbs ``.enqueue(...)`` / ``.evict_pod(...)`` / ``.delete_pod(...)`` —
must sit on a same-module call-graph slice that contains BOTH

- the scored-improvement gate (``clears_hysteresis(...)``), and
- the deterministic intent source (``intent_for(...)``).

This COMPOSES with ``eviction-discipline`` (which covers all of
``controllers/``): the funnel checker guarantees evictions are
throttled and idempotent; this one guarantees a descheduler's are also
*justified by score*. Slice semantics are identical (own def, callee
closure, or a caller whose closure holds both the call site and the
sinks — the ``reconcile_once → _emit`` shape, where the gate runs one
frame above the intent stamp).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set, Tuple

from .base import Checker, Finding, ModuleSource, attr_chain, register

SCOPE_DIR = "controllers/"

EMIT_VERBS = {"enqueue", "evict_pod", "delete_pod"}
GATE_SINKS = {"clears_hysteresis"}
INTENT_SINKS = {"intent_for"}


def _fn_facts(fn: ast.AST) -> Tuple[List[int], bool, bool, Set[str]]:
    """(emit-call linenos, has_gate, has_intent, same-module callee names)
    for one def."""
    emits: List[int] = []
    has_gate = False
    has_intent = False
    calls: Set[str] = set()
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if isinstance(func, ast.Attribute):
            if func.attr in EMIT_VERBS:
                emits.append(node.lineno)
            if func.attr in GATE_SINKS:
                has_gate = True
            if func.attr in INTENT_SINKS:
                has_intent = True
        elif isinstance(func, ast.Name):
            if func.id in GATE_SINKS:
                has_gate = True
            if func.id in INTENT_SINKS:
                has_intent = True
        chain = attr_chain(func)
        if chain and (len(chain) == 1
                      or (len(chain) == 2 and chain[0] == "self")):
            calls.add(chain[-1])
    return emits, has_gate, has_intent, calls


@register
class DescheduleDisciplineChecker(Checker):
    id = "deschedule-discipline"
    description = ("descheduler eviction call sites stay on a call-graph "
                   "slice containing both the scored-improvement gate "
                   "(clears_hysteresis) and the deterministic intent "
                   "source (intent_for)")

    def applies_to(self, relpath: str) -> bool:
        in_scope = (relpath.startswith(SCOPE_DIR)
                    or ("/" + SCOPE_DIR) in relpath)
        name = relpath.rsplit("/", 1)[-1]
        return in_scope and "deschedul" in name

    def check(self, mod: ModuleSource) -> List[Finding]:
        tree = mod.tree
        if tree is None:
            return []
        defs: List[Tuple[str, List[int], bool, bool, Set[str]]] = []
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs.append((node.name, *_fn_facts(node)))
        name_gate: Dict[str, bool] = {}
        name_intent: Dict[str, bool] = {}
        name_calls: Dict[str, Set[str]] = {}
        for name, _e, gate, intent, calls in defs:
            name_gate[name] = name_gate.get(name, False) or gate
            name_intent[name] = name_intent.get(name, False) or intent
            name_calls.setdefault(name, set()).update(calls)
        reach_memo: Dict[str, Set[str]] = {}

        def reach(name: str) -> Set[str]:
            got = reach_memo.get(name)
            if got is not None:
                return got
            reach_memo[name] = out = set()
            stack = [name]
            while stack:
                for callee in name_calls.get(stack.pop(), ()):
                    if callee not in out and callee in name_calls:
                        out.add(callee)
                        stack.append(callee)
            return out

        def slice_ok(names: Set[str]) -> bool:
            return (any(name_gate.get(n, False) for n in names)
                    and any(name_intent.get(n, False) for n in names))

        def def_covered(name: str, calls: Set[str]) -> bool:
            down = {name}
            for c in calls:
                if c in name_calls:
                    down.add(c)
                    down |= reach(c)
            if slice_ok(down):
                return True
            for g, _e, _g2, _i, _c in defs:
                gr = reach(g)
                if name in gr and slice_ok(gr | {g}):
                    return True
            return False

        out: List[Finding] = []
        for name, emits, _gate, _intent, calls in defs:
            if not emits or def_covered(name, calls):
                continue
            for line in emits:
                out.append(Finding(
                    self.id, "move-without-scored-gate", mod.path, line,
                    f"{name}() emits a descheduler eviction but no "
                    "call-graph slice through it clears the scored-"
                    "improvement gate (clears_hysteresis) AND mints the "
                    "deterministic intent (intent_for) — an unjustified "
                    "move: churn instead of repair, and unreplayable "
                    "across a takeover"))
        return out
