"""thread-hygiene checker: no thread may outlive shutdown unnoticed.

Incident (PR 5): the apiserver's pooled keep-alive connections kept DEAD
server handler threads alive across a restart-in-place — the port could
not rebind for >20s and the restarted process served stale state. The
general invariant since PR 1's chaos suite: every ``threading.Thread``
this package starts is either a daemon (dies with the process, by
declaration) or provably joined in a shutdown path in the same module.

Rule ``daemon-or-joined``: a ``threading.Thread(...)`` construction must
pass ``daemon=True``, or the object it is bound to must have ``.join(``
called somewhere in the module (the shutdown path). An unbound,
non-daemon ``Thread(...).start()`` is always a finding — nothing can ever
join it.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from .base import (Checker, Finding, ModuleSource, attr_chain, build_parents,
                   register)


def _is_thread_ctor(call: ast.Call) -> bool:
    chain = attr_chain(call.func)
    return (chain[-2:] == ["threading", "Thread"]
            or chain == ["Thread"])


def _bound_name(parents, call: ast.Call) -> Optional[str]:
    """The terminal name the Thread object is assigned to: 'x' for
    `x = Thread(...)`, '_thread' for `self._thread = Thread(...)`; None
    when the object is not bound (e.g. `Thread(...).start()`)."""
    parent = parents.get(call)
    if isinstance(parent, ast.Assign) and len(parent.targets) == 1:
        t = parent.targets[0]
        if isinstance(t, ast.Name):
            return t.id
        if isinstance(t, ast.Attribute):
            return t.attr
    return None


@register
class ThreadHygieneChecker(Checker):
    id = "thread-hygiene"
    description = ("every threading.Thread is daemon=True or joined in a "
                   "shutdown path in the same module")

    def check(self, mod: ModuleSource) -> List[Finding]:
        out: List[Finding] = []
        tree = mod.tree
        parents = build_parents(tree)
        joined: Set[str] = set()
        for node in ast.walk(tree):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "join"):
                chain = attr_chain(node.func.value)
                if chain:
                    joined.add(chain[-1])
                # `for t in self._threads: t.join()` — the loop variable's
                # iterable names the real container; credit both.
                stmt = parents.get(node)
                while stmt is not None and not isinstance(stmt, ast.For):
                    stmt = parents.get(stmt)
                if isinstance(stmt, ast.For):
                    it = attr_chain(stmt.iter)
                    if it:
                        joined.add(it[-1])
        appended_to: Set[str] = set()  # thread appended to a joined list
        credited_ctors: Set[int] = set()  # inline Thread() in such an append
        for node in ast.walk(tree):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "append"):
                container = attr_chain(node.func.value)
                if container and container[-1] in joined:
                    for arg in node.args:
                        chain = attr_chain(arg)
                        if chain:
                            appended_to.add(chain[-1])
                        elif isinstance(arg, ast.Call) and _is_thread_ctor(arg):
                            # threads.append(Thread(...)) in one line
                            credited_ctors.add(id(arg))
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call) and _is_thread_ctor(node)):
                continue
            daemon = next((kw for kw in node.keywords if kw.arg == "daemon"),
                          None)
            if (daemon is not None and isinstance(daemon.value, ast.Constant)
                    and daemon.value.value is True):
                continue
            if id(node) in credited_ctors:
                continue
            name = _bound_name(parents, node)
            if name is not None and (name in joined or name in appended_to):
                continue
            what = (f"thread bound to {name!r}" if name
                    else "unbound Thread(...)")
            out.append(Finding(
                self.id, "daemon-or-joined", mod.path, node.lineno,
                f"{what} is neither daemon=True nor joined in this module "
                "— it can outlive shutdown and serve dead state (PR 5 "
                "restart-in-place incident)"))
        return out
