"""Project invariant analyzer (docs/ANALYSIS.md).

AST-based static analysis for the invariants earlier PRs paid for in
incidents: index-dtype pinning (s64/s32 GSPMD miscompiles), apiserver/WAL
lock discipline, jit purity, thread hygiene, metrics discipline. Run it
with ``python -m kubernetes_tpu.analysis`` (nonzero exit on findings) or
through the tier-1 wrapper ``tests/test_static_analysis.py``.
"""

from .allowlist import ALLOWLIST, Allow, validate_allowlist
from .base import (Checker, Finding, ModuleSource, Report, all_checkers,
                   analyze, check_source, checker_by_id, register)

__all__ = [
    "ALLOWLIST", "Allow", "Checker", "Finding", "ModuleSource", "Report",
    "all_checkers", "analyze", "check_source", "checker_by_id", "register",
    "validate_allowlist",
]
