"""reconcile-discipline checker: controller pod creates stay exactly-once.

Incident class (ISSUE 17): the workload controllers are HA — two
controller-manager processes race a lease, and the loser's informers are
WARM, one kill9 away from running the same reconcile against the same
desired state. The construction that keeps their creates exactly-once is
source-visible and this rule pins it: every pod a controller mints is
named by a pure function of desired state (``replica_name`` /
``gang_member_name``), and every create flows through a seam that treats
HTTP 409 AlreadyExists as success ("the other actor — or my own previous
incarnation — already did this"). A create site missing either half is
the duplicate-pod storm waiting for a failover: random or clock-derived
names make the races semantic collisions invisible (two actors mint
DIFFERENT pods for the same ordinal), and a 409-is-error create turns
the benign collision into a crash-looping reconciler.

Rule ``create-outside-seam``: in ``controllers/``, every function that
calls a pod-create verb (``.create_pod(...)``) must sit on a same-module
call-graph slice that contains BOTH

- a deterministic-name source (``replica_name(...)`` /
  ``gang_member_name(...)``), and
- a create-409-is-success handler (an ``except`` arm comparing
  ``.code`` against 409).

"Slice" follows eviction_discipline's shape: the sinks may live in the
calling function itself, in its same-module callee closure, or in a
caller whose callee closure contains both the call site and the sinks
(the ``_mint → _create_pod`` shape, where the name is derived one frame
above the 409 handling). Both must appear in ONE slice — deterministic
names without 409-tolerance still crash the second actor, and
409-tolerance over random names still duplicates pods.

(Voluntary pod REMOVAL in controllers/ is covered separately: the
server-side PDB precondition guards ``delete_pod_voluntary``, and the
eviction funnel rule guards ``delete_pod``/``evict_pod``.)
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set, Tuple

from .base import Checker, Finding, ModuleSource, attr_chain, register

SCOPE_DIR = "controllers/"

CREATE_VERBS = {"create_pod"}
NAME_SINKS = {"replica_name", "gang_member_name"}


def _has_409_handler(fn: ast.AST) -> bool:
    """True when the def contains, inside an except arm, a comparison of
    some ``<e>.code`` against 409 — the create-409-is-success seam."""
    for node in ast.walk(fn):
        if not isinstance(node, ast.ExceptHandler):
            continue
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Compare):
                continue
            sides = [sub.left, *sub.comparators]
            has_code = any(isinstance(s, ast.Attribute) and s.attr == "code"
                           for s in sides)
            has_409 = any(isinstance(s, ast.Constant) and s.value == 409
                          for s in sides)
            if has_code and has_409:
                return True
    return False


def _fn_facts(fn: ast.AST) -> Tuple[List[int], bool, bool, Set[str]]:
    """(create-call linenos, has_name_sink, has_409, same-module callee
    names) for one def."""
    creates: List[int] = []
    has_name = False
    calls: Set[str] = set()
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if isinstance(func, ast.Attribute):
            if func.attr in CREATE_VERBS:
                creates.append(node.lineno)
            if func.attr in NAME_SINKS:
                has_name = True
        elif isinstance(func, ast.Name) and func.id in NAME_SINKS:
            has_name = True
        chain = attr_chain(func)
        if chain and (len(chain) == 1
                      or (len(chain) == 2 and chain[0] == "self")):
            calls.add(chain[-1])
    return creates, has_name, _has_409_handler(fn), calls


@register
class ReconcileDisciplineChecker(Checker):
    id = "reconcile-discipline"
    description = ("controllers/ pod create call sites stay on a "
                   "call-graph slice containing both a deterministic "
                   "name source (replica_name/gang_member_name) and a "
                   "create-409-is-success handler")

    def applies_to(self, relpath: str) -> bool:
        return relpath.startswith(SCOPE_DIR) or ("/" + SCOPE_DIR) in relpath

    def check(self, mod: ModuleSource) -> List[Finding]:
        tree = mod.tree
        if tree is None:
            return []
        defs: List[Tuple[str, List[int], bool, bool, Set[str]]] = []
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs.append((node.name, *_fn_facts(node)))
        name_det: Dict[str, bool] = {}
        name_409: Dict[str, bool] = {}
        name_calls: Dict[str, Set[str]] = {}
        for name, _c, det, tol, calls in defs:
            name_det[name] = name_det.get(name, False) or det
            name_409[name] = name_409.get(name, False) or tol
            name_calls.setdefault(name, set()).update(calls)
        reach_memo: Dict[str, Set[str]] = {}

        def reach(name: str) -> Set[str]:
            got = reach_memo.get(name)
            if got is not None:
                return got
            reach_memo[name] = out = set()
            stack = [name]
            while stack:
                for callee in name_calls.get(stack.pop(), ()):
                    if callee not in out and callee in name_calls:
                        out.add(callee)
                        stack.append(callee)
            return out

        def slice_ok(names: Set[str]) -> bool:
            return (any(name_det.get(n, False) for n in names)
                    and any(name_409.get(n, False) for n in names))

        def def_covered(name: str, calls: Set[str]) -> bool:
            down = {name}
            for c in calls:
                if c in name_calls:
                    down.add(c)
                    down |= reach(c)
            if slice_ok(down):
                return True
            for g, _c, _d, _t, _cl in defs:
                gr = reach(g)
                if name in gr and slice_ok(gr | {g}):
                    return True
            return False

        out: List[Finding] = []
        for name, creates, _det, _tol, calls in defs:
            if not creates or def_covered(name, calls):
                continue
            for line in creates:
                out.append(Finding(
                    self.id, "create-outside-seam", mod.path, line,
                    f"{name}() creates a pod but no call-graph slice "
                    "through it derives a deterministic name "
                    "(replica_name/gang_member_name) AND treats create-"
                    "409 as success — a racy create: two HA reconcilers "
                    "(or one across a kill9 failover) duplicate pods "
                    "instead of colliding benignly"))
        return out
