"""shed-discipline checker: the overload plane's three contracts.

Incident class (PR 14): flow-control shedding (core/flowcontrol.py) only
protects the plane if three invariants hold everywhere, and each is a
one-line mistake away from silently rotting:

- ``429-without-retry-after`` — every 429 reply must carry a
  ``Retry-After`` header (``_json(429, ..., retry_after=...)``). A bare
  429 turns the polite shed contract into a blind retry storm: clients
  fall back to their generic exponential schedule, re-synchronize, and
  hammer the very server that is trying to shed load.

- ``shed-under-write-lock`` — flow-control admission (``_flow_admit`` /
  ``flowcontrol.admit``) must never run lexically under a held
  ``_write_lock``. The whole point of admission is to reject overload
  BEFORE it can contend for the write plane; admitting under the lock
  would make every shed serialize behind the writes it was supposed to
  protect.

- ``retry-after-parse-outside-backoff`` — the ``"Retry-After"`` header is
  *parsed* in exactly one place: :func:`core.backoff.retry_after_of`.
  Any other module reading it means a client retry loop grew its own 429
  handling beside the shared backoff stack — a loop that will not get the
  decorrelated jitter, the cap, or future policy fixes. Producers
  (core/apiserver.py setting the header, core/flowcontrol.py computing
  it) are exempt; everyone else routes through core/backoff.py.
"""

from __future__ import annotations

import ast
from typing import List, Tuple

from .base import Checker, Finding, ModuleSource, attr_chain, register

# Rules 1+2 scope: where 429s are produced and admission runs.
SHED_MODULES: Tuple[str, ...] = (
    "core/apiserver.py",
    "core/flowcontrol.py",
)
# Rule 3: modules allowed to mention the Retry-After header literally —
# the one parser seam plus the two producers.
RETRY_AFTER_SEAMS: Tuple[str, ...] = (
    "core/backoff.py",
    "core/flowcontrol.py",
    "core/apiserver.py",
)

ADMIT_NAMES = frozenset({"admit", "_flow_admit"})


def _is_write_lock_with(node: ast.With) -> bool:
    for item in node.items:
        chain = attr_chain(item.context_expr)
        if chain and chain[-1] == "_write_lock":
            return True
    return False


@register
class ShedDisciplineChecker(Checker):
    id = "shed-discipline"
    description = ("flow-control shed contracts: 429 replies carry "
                   "Retry-After, admission never runs under _write_lock, "
                   "and Retry-After parsing lives only in core/backoff.py "
                   "(client retry loops route through the shared stack)")

    def applies_to(self, relpath: str) -> bool:
        return relpath.endswith(".py")

    def check(self, mod: ModuleSource) -> List[Finding]:
        out: List[Finding] = []
        fixture = mod.path.startswith("<")
        in_shed_scope = mod.path in SHED_MODULES or fixture
        if in_shed_scope:
            out.extend(self._check_429_envelope(mod))
            out.extend(self._check_admit_under_lock(mod))
        if (mod.path not in RETRY_AFTER_SEAMS
                and not mod.path.startswith("analysis/")):
            # analysis/ names the literal to describe the rule itself.
            out.extend(self._check_retry_after_literal(mod))
        return out

    def _check_429_envelope(self, mod: ModuleSource) -> List[Finding]:
        out: List[Finding] = []
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            chain = attr_chain(node.func)
            if not chain or chain[-1] != "_json":
                continue
            if not (node.args and isinstance(node.args[0], ast.Constant)
                    and node.args[0].value == 429):
                continue
            if any(kw.arg == "retry_after" for kw in node.keywords):
                continue
            out.append(Finding(
                self.id, "429-without-retry-after", mod.path, node.lineno,
                "429 reply without a Retry-After header — a shed must name "
                "its horizon (retry_after=...) or clients re-synchronize "
                "into a retry storm instead of backing off past it"))
        return out

    def _check_admit_under_lock(self, mod: ModuleSource) -> List[Finding]:
        out: List[Finding] = []
        for wnode in ast.walk(mod.tree):
            if not isinstance(wnode, ast.With) or \
                    not _is_write_lock_with(wnode):
                continue
            for node in ast.walk(wnode):
                if not isinstance(node, ast.Call):
                    continue
                chain = attr_chain(node.func)
                if not chain or chain[-1] not in ADMIT_NAMES:
                    continue
                if chain[-1] == "admit" and "flowcontrol" not in chain:
                    continue  # some other object's admit()
                out.append(Finding(
                    self.id, "shed-under-write-lock", mod.path, node.lineno,
                    f"{'.'.join(chain)}(...) under _write_lock — admission "
                    "must reject overload BEFORE the write plane; a shed "
                    "that waits on the lock protects nothing"))
        return out

    def _check_retry_after_literal(self, mod: ModuleSource) -> List[Finding]:
        out: List[Finding] = []
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Constant) and node.value == "Retry-After":
                out.append(Finding(
                    self.id, "retry-after-parse-outside-backoff", mod.path,
                    node.lineno,
                    '"Retry-After" handled outside core/backoff.py — client '
                    "retry loops on the 429 surface must route through "
                    "retry_call/retry_after_of so they inherit the "
                    "decorrelated jitter and the cap"))
        return out
