"""wire-discipline checker: hot wire surfaces route through core/wire.py.

Incident class (PR 13): the whole point of the binary wire refactor is
that every hot surface — WAL records, the replication ship stream,
snapshot bootstrap pages, watch events, bulk bindings, paged LIST — speaks
the NEGOTIATED codec. A stray ``json.dumps``/``json.loads`` on one of
those modules silently pins that path to the JSON plane: everything still
works, every test still passes, and the byte savings quietly disappear for
that surface (exactly the regression class that is invisible without the
per-surface ``apiserver_wire_bytes_total`` counters).

Rule (``json-on-wire-surface``): inside the hot wire modules
(core/apiserver.py, core/watchcache.py, core/wal.py,
replication/follower.py), no direct calls to ``json.dumps`` /
``json.loads`` / ``json.dump`` / ``json.load`` — encode/decode must route
through :mod:`kubernetes_tpu.core.wire` (``wire.encode`` / ``wire.decode``
/ ``read_event`` / ``scan`` for the negotiated plane, ``wire.jdumps`` /
``wire.jloads`` for the deliberate JSON debug/compat surfaces, so the
deliberate ones are grep-able and reviewed at the seam). Import aliases
(``import json as _json``, ``from json import dumps``) are resolved;
core/wire.py itself is the seam and exempt.

Rule (``delta-base-under-cache-lock``, PR 18): the delta plane's two
thread-discipline invariants, both of which fail SILENTLY at runtime
(a torn base read mints a patch against a state no receiver holds; a
session intern table touched from the broadcast path corrupts every
frame after it on that stream):

- in core/watchcache.py, ``mint_delta`` / ``materialize_delta`` may read
  ``self._objects`` / ``self._obj_rv`` only lexically inside a
  ``with self._lock:`` block — the base handed to ``diff_obj`` /
  ``apply_patch`` must be the snapshot's state at one instant;
- in every hot module, fanout-path functions (``_broadcast``,
  ``_fan_event``, ``_repl_append``, ``_ship_fanout``, ``_route_to``,
  ``note_event``, ``route``) must not construct a
  ``wire.SessionEncoder`` or call ``.session_bytes(...)`` — per-stream
  encoder state belongs to the stream's consumer thread, where
  ``encode_stream_item`` runs, never under the broadcast lock.
"""

from __future__ import annotations

import ast
from typing import List, Set, Tuple

from .base import (Checker, Finding, ModuleSource, attr_chain,
                   build_parents, register)

HOT_MODULES: Tuple[str, ...] = (
    "core/apiserver.py",
    "core/watchcache.py",
    "core/wal.py",
    "replication/follower.py",
)
SEAM = "core/wire.py"
VERBS = frozenset({"dumps", "loads", "dump", "load"})
# Delta minting/materialization: the snapshot reads that must happen
# under the watch cache's own lock.
DELTA_FUNCS = frozenset({"mint_delta", "materialize_delta"})
DELTA_BASES = frozenset({"_objects", "_obj_rv"})
# Fanout-path functions (run under, or called from under, the broadcast
# lock): per-stream session encoder state is off limits here.
FANOUT_FUNCS = frozenset({"_broadcast", "_fan_event", "_repl_append",
                          "_ship_fanout", "_route_to", "note_event",
                          "route"})


def _under_self_lock(parents, node: ast.AST, fn: ast.AST) -> bool:
    """True when ``node`` sits lexically inside a ``with self._lock:``
    block within ``fn`` (ancestor walk stops at the function boundary)."""
    cur = parents.get(node)
    while cur is not None and cur is not fn:
        if isinstance(cur, ast.With):
            for item in cur.items:
                if attr_chain(item.context_expr) == ["self", "_lock"]:
                    return True
        cur = parents.get(cur)
    return False


def _json_aliases(tree: ast.AST) -> Tuple[Set[str], Set[str]]:
    """(names bound to the json MODULE, names bound to a json VERB) — any
    ``import json [as x]`` / ``from json import dumps [as y]`` anywhere in
    the module (function-local imports included)."""
    mod_names: Set[str] = set()
    verb_names: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name == "json":
                    mod_names.add(alias.asname or "json")
        elif isinstance(node, ast.ImportFrom) and node.module == "json":
            for alias in node.names:
                if alias.name in VERBS:
                    verb_names.add(alias.asname or alias.name)
    return mod_names, verb_names


@register
class WireDisciplineChecker(Checker):
    id = "wire-discipline"
    description = ("hot wire surfaces (apiserver/watchcache/wal/follower) "
                   "never call json.dumps/loads directly — encode/decode "
                   "routes through the core/wire.py codec seam so the "
                   "negotiated binary plane cannot silently regress to "
                   "JSON on one surface")

    def applies_to(self, relpath: str) -> bool:
        return relpath in HOT_MODULES

    def check(self, mod: ModuleSource) -> List[Finding]:
        if mod.path == SEAM:
            return []
        if mod.path not in HOT_MODULES and not mod.path.startswith("<"):
            return []
        mod_names, verb_names = _json_aliases(mod.tree)
        out: List[Finding] = []
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            verb = None
            chain = attr_chain(node.func)
            if (len(chain) >= 2 and chain[-1] in VERBS
                    and chain[-2] in (mod_names or {"json"})):
                verb = chain[-1]
            elif (isinstance(node.func, ast.Name)
                    and node.func.id in verb_names):
                verb = node.func.id
            if verb is None:
                continue
            out.append(Finding(
                self.id, "json-on-wire-surface", mod.path, node.lineno,
                f"json.{verb}(...) on a hot wire surface — route through "
                "the core/wire.py codec seam (wire.encode/decode for the "
                "negotiated plane, wire.jdumps/jloads for deliberate "
                "JSON debug surfaces) so the binary plane cannot "
                "silently regress on this path"))
        out.extend(self._check_delta_discipline(mod))
        return out

    def _check_delta_discipline(self, mod: ModuleSource) -> List[Finding]:
        """The ``delta-base-under-cache-lock`` sub-rule (module docstring):
        snapshot reads in mint/materialize stay under ``self._lock``;
        session encoder state never appears in fanout-path functions."""
        out: List[Finding] = []
        parents = build_parents(mod.tree)
        for fn in ast.walk(mod.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if fn.name in DELTA_FUNCS:
                for node in ast.walk(fn):
                    if not isinstance(node, ast.Attribute):
                        continue
                    chain = attr_chain(node)
                    if (len(chain) == 2 and chain[0] == "self"
                            and chain[1] in DELTA_BASES
                            and not _under_self_lock(parents, node, fn)):
                        out.append(Finding(
                            self.id, "delta-base-under-cache-lock",
                            mod.path, node.lineno,
                            f"self.{chain[1]} read in {fn.name}() outside "
                            "`with self._lock:` — the delta base must be "
                            "the snapshot's state at one instant; a torn "
                            "read mints a patch no receiver's cache "
                            "matches (silent divergence)"))
            if fn.name in FANOUT_FUNCS:
                for node in ast.walk(fn):
                    if not isinstance(node, ast.Call):
                        continue
                    chain = attr_chain(node.func)
                    if not chain:
                        continue
                    if chain[-1] == "SessionEncoder":
                        out.append(Finding(
                            self.id, "delta-base-under-cache-lock",
                            mod.path, node.lineno,
                            f"SessionEncoder constructed in {fn.name}() — "
                            "per-stream intern state belongs to the "
                            "stream's consumer thread (where "
                            "encode_stream_item runs), never the "
                            "broadcast/fanout path"))
                    elif chain[-1] == "session_bytes":
                        out.append(Finding(
                            self.id, "delta-base-under-cache-lock",
                            mod.path, node.lineno,
                            f"session_bytes(...) called in {fn.name}() — "
                            "session frames mutate the per-connection "
                            "intern table and may only be encoded on the "
                            "stream's consumer thread, never the "
                            "broadcast/fanout path"))
        return out
