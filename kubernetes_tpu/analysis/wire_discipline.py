"""wire-discipline checker: hot wire surfaces route through core/wire.py.

Incident class (PR 13): the whole point of the binary wire refactor is
that every hot surface — WAL records, the replication ship stream,
snapshot bootstrap pages, watch events, bulk bindings, paged LIST — speaks
the NEGOTIATED codec. A stray ``json.dumps``/``json.loads`` on one of
those modules silently pins that path to the JSON plane: everything still
works, every test still passes, and the byte savings quietly disappear for
that surface (exactly the regression class that is invisible without the
per-surface ``apiserver_wire_bytes_total`` counters).

Rule (``json-on-wire-surface``): inside the hot wire modules
(core/apiserver.py, core/watchcache.py, core/wal.py,
replication/follower.py), no direct calls to ``json.dumps`` /
``json.loads`` / ``json.dump`` / ``json.load`` — encode/decode must route
through :mod:`kubernetes_tpu.core.wire` (``wire.encode`` / ``wire.decode``
/ ``read_event`` / ``scan`` for the negotiated plane, ``wire.jdumps`` /
``wire.jloads`` for the deliberate JSON debug/compat surfaces, so the
deliberate ones are grep-able and reviewed at the seam). Import aliases
(``import json as _json``, ``from json import dumps``) are resolved;
core/wire.py itself is the seam and exempt.
"""

from __future__ import annotations

import ast
from typing import List, Set, Tuple

from .base import Checker, Finding, ModuleSource, attr_chain, register

HOT_MODULES: Tuple[str, ...] = (
    "core/apiserver.py",
    "core/watchcache.py",
    "core/wal.py",
    "replication/follower.py",
)
SEAM = "core/wire.py"
VERBS = frozenset({"dumps", "loads", "dump", "load"})


def _json_aliases(tree: ast.AST) -> Tuple[Set[str], Set[str]]:
    """(names bound to the json MODULE, names bound to a json VERB) — any
    ``import json [as x]`` / ``from json import dumps [as y]`` anywhere in
    the module (function-local imports included)."""
    mod_names: Set[str] = set()
    verb_names: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name == "json":
                    mod_names.add(alias.asname or "json")
        elif isinstance(node, ast.ImportFrom) and node.module == "json":
            for alias in node.names:
                if alias.name in VERBS:
                    verb_names.add(alias.asname or alias.name)
    return mod_names, verb_names


@register
class WireDisciplineChecker(Checker):
    id = "wire-discipline"
    description = ("hot wire surfaces (apiserver/watchcache/wal/follower) "
                   "never call json.dumps/loads directly — encode/decode "
                   "routes through the core/wire.py codec seam so the "
                   "negotiated binary plane cannot silently regress to "
                   "JSON on one surface")

    def applies_to(self, relpath: str) -> bool:
        return relpath in HOT_MODULES

    def check(self, mod: ModuleSource) -> List[Finding]:
        if mod.path == SEAM:
            return []
        if mod.path not in HOT_MODULES and not mod.path.startswith("<"):
            return []
        mod_names, verb_names = _json_aliases(mod.tree)
        out: List[Finding] = []
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            verb = None
            chain = attr_chain(node.func)
            if (len(chain) >= 2 and chain[-1] in VERBS
                    and chain[-2] in (mod_names or {"json"})):
                verb = chain[-1]
            elif (isinstance(node.func, ast.Name)
                    and node.func.id in verb_names):
                verb = node.func.id
            if verb is None:
                continue
            out.append(Finding(
                self.id, "json-on-wire-surface", mod.path, node.lineno,
                f"json.{verb}(...) on a hot wire surface — route through "
                "the core/wire.py codec seam (wire.encode/decode for the "
                "negotiated plane, wire.jdumps/jloads for deliberate "
                "JSON debug surfaces) so the binary plane cannot "
                "silently regress on this path"))
        return out
