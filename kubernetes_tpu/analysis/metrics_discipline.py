"""metrics-discipline checker: every series used is declared, correctly.

Incident class (PR 3/PR 5 satellites): new subsystems wired counters into
hot paths and the metrics-parity test only caught them when someone
remembered to extend its allowlist — an attribute typo (`metrics.X.inc`
for an undeclared X) raises AttributeError at RUNTIME, on the first hit
of a path that tests may never drive (e.g. a failover branch). Label
mistakes are worse: a wrong positional count silently mis-keys the series
(`inc("a")` on a 2-label counter buckets under a truncated key).

Rules (usages matched: ``<...>.metrics.<attr>.inc/observe/set(...)``,
bare ``metrics.<attr>...``, and simple aliases — ``m = self.metrics`` /
``pet = self.metrics.plugin_evaluation_total`` — resolved through the
enclosing function scopes; declarations parsed from
``core/metrics.py SchedulerMetrics.__init__``):

- ``undeclared-metric``: the attribute is not declared in core/metrics.py;
- ``metric-verb-mismatch``: ``inc`` on a non-Counter, ``observe`` on a
  non-Histogram, ``set`` on a non-Gauge;
- ``label-arity``: the positional argument count at the call site does not
  match the declared label tuple (inc takes exactly the labels; observe/
  set take value-then-labels);
- ``label-cardinality``: a series declares more than MAX_LABELS label
  dimensions (cardinality explodes multiplicatively; the reference's
  worst series carries 3).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Dict, List, Optional, Set, Tuple

from .base import (PKG_ROOT, Checker, Finding, ModuleSource, attr_chain,
                   build_parents, register)

MAX_LABELS = 3
VERB_TO_KIND = {"inc": "Counter", "observe": "Histogram", "set": "Gauge"}
METRIC_CLASSES = ("Counter", "Gauge", "Histogram")
METRICS_MODULE = "core/metrics.py"


@dataclass(frozen=True)
class Declaration:
    attr: str
    kind: str                               # Counter | Gauge | Histogram
    series: Optional[str]                   # prometheus name, if constant
    labels: Optional[Tuple[str, ...]]       # None = not statically known
    line: int


def parse_declarations(source: str) -> Dict[str, Declaration]:
    """``self.<attr> = r(Counter(name, help, (labels...)))`` assignments in
    SchedulerMetrics.__init__ (the registration wrapper ``r``/``register``
    is unwrapped)."""
    decls: Dict[str, Declaration] = {}
    tree = ast.parse(source)
    for cls in ast.walk(tree):
        if not (isinstance(cls, ast.ClassDef)
                and cls.name == "SchedulerMetrics"):
            continue
        init = next((f for f in cls.body if isinstance(f, ast.FunctionDef)
                     and f.name == "__init__"), None)
        if init is None:
            continue
        for node in ast.walk(init):
            if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Attribute)):
                continue
            value = node.value
            # unwrap r(...) / self.registry.register(...)
            if (isinstance(value, ast.Call) and value.args
                    and attr_chain(value.func)[-1:] in (["r"], ["register"])):
                value = value.args[0]
            if not isinstance(value, ast.Call):
                continue
            chain = attr_chain(value.func)
            if not chain or chain[-1] not in METRIC_CLASSES:
                continue
            series = (value.args[0].value
                      if value.args and isinstance(value.args[0], ast.Constant)
                      else None)
            labels: Optional[Tuple[str, ...]] = ()
            label_node = None
            if len(value.args) >= 3:
                label_node = value.args[2]
            for kw in value.keywords:
                if kw.arg == "label_names":
                    label_node = kw.value
            if label_node is not None:
                if (isinstance(label_node, (ast.Tuple, ast.List))
                        and all(isinstance(e, ast.Constant)
                                for e in label_node.elts)):
                    labels = tuple(e.value for e in label_node.elts)
                else:
                    labels = None  # dynamic; arity not statically checkable
            decls[node.targets[0].attr] = Declaration(
                attr=node.targets[0].attr, kind=chain[-1], series=series,
                labels=labels, line=node.lineno)
    return decls


def _scope_aliases(fn: ast.AST) -> Tuple[Set[str], Dict[str, str]]:
    """Aliases bound by simple assignment anywhere under `fn` (nested defs
    read them by closure): names bound to a metrics OBJECT and names bound
    to one declared metric."""
    obj_aliases: Set[str] = set()
    metric_aliases: Dict[str, str] = {}
    # Module scope: only module-LEVEL assignments define module aliases —
    # walking the whole tree would leak every function's locals into it.
    nodes = (ast.iter_child_nodes(fn) if isinstance(fn, ast.Module)
             else ast.walk(fn))
    for node in nodes:
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)):
            continue
        chain = attr_chain(node.value)
        if not chain:
            continue
        if chain[-1] == "metrics":
            obj_aliases.add(node.targets[0].id)
        elif len(chain) >= 2 and chain[-2] == "metrics":
            metric_aliases[node.targets[0].id] = chain[-1]
    return obj_aliases, metric_aliases


@register
class MetricsDisciplineChecker(Checker):
    id = "metrics-discipline"
    description = ("every metrics.<attr>.inc/observe/set call targets a "
                   "series declared in core/metrics.py with matching verb "
                   "and label arity; declarations stay under the label-"
                   "cardinality bound")

    def __init__(self, declarations: Optional[Dict[str, Declaration]] = None):
        self._decls = declarations

    @property
    def declarations(self) -> Dict[str, Declaration]:
        if self._decls is None:
            self._decls = parse_declarations(
                (PKG_ROOT / METRICS_MODULE).read_text())
        return self._decls

    def check(self, mod: ModuleSource) -> List[Finding]:
        out: List[Finding] = []
        if mod.path.endswith(METRICS_MODULE) or mod.path == "metrics.py":
            # Declaration-side rule: the cardinality bound.
            for d in parse_declarations(mod.source).values():
                if d.labels is not None and len(d.labels) > MAX_LABELS:
                    out.append(Finding(
                        self.id, "label-cardinality", mod.path, d.line,
                        f"series {d.series or d.attr} declares "
                        f"{len(d.labels)} label dimensions (bound: "
                        f"{MAX_LABELS}) — cardinality multiplies per "
                        "dimension"))
            return out
        decls = self.declarations
        parents = build_parents(mod.tree)
        alias_cache: Dict[ast.AST, Tuple[Set[str], Dict[str, str]]] = {}

        def resolve_attr(call: ast.Call) -> Optional[str]:
            base = call.func.value
            if isinstance(base, ast.Attribute):
                root = attr_chain(base.value)
                if root and root[-1] == "metrics":
                    return base.attr
            # Alias forms, nearest enclosing function scope first.
            scope: Optional[ast.AST] = parents.get(call)
            while scope is not None:
                if isinstance(scope, (ast.FunctionDef, ast.Module)):
                    if scope not in alias_cache:
                        alias_cache[scope] = _scope_aliases(scope)
                    obj_aliases, metric_aliases = alias_cache[scope]
                    if isinstance(base, ast.Attribute):
                        root = attr_chain(base.value)
                        if root and root[-1] in obj_aliases:
                            return base.attr
                    elif (isinstance(base, ast.Name)
                          and base.id in metric_aliases):
                        return metric_aliases[base.id]
                scope = parents.get(scope)
            return None

        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in VERB_TO_KIND):
                continue
            metric_attr = resolve_attr(node)
            if metric_attr is None:
                continue
            verb = node.func.attr
            decl = decls.get(metric_attr)
            if decl is None:
                out.append(Finding(
                    self.id, "undeclared-metric", mod.path, node.lineno,
                    f"metrics.{metric_attr}.{verb}(...) targets a series "
                    "not declared in core/metrics.py SchedulerMetrics — "
                    "AttributeError on first hit of this path"))
                continue
            if VERB_TO_KIND[verb] != decl.kind:
                out.append(Finding(
                    self.id, "metric-verb-mismatch", mod.path, node.lineno,
                    f"metrics.{metric_attr} is a {decl.kind} but is called "
                    f"with .{verb}() ({VERB_TO_KIND[verb]} verb)"))
                continue
            if decl.labels is None:
                continue
            if any(isinstance(a, ast.Starred) for a in node.args):
                continue  # *labels splat: arity not statically known
            npos = len(node.args)
            expected = (len(decl.labels) if verb == "inc"
                        else 1 + len(decl.labels))
            if npos != expected:
                shape = ("(*labels)" if verb == "inc" else "(value, *labels)")
                out.append(Finding(
                    self.id, "label-arity", mod.path, node.lineno,
                    f"metrics.{metric_attr}.{verb}{shape} declared with "
                    f"labels {decl.labels!r} expects {expected} positional "
                    f"arg(s), call passes {npos} — mis-keyed series"))
        return out
