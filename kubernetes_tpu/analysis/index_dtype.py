"""index-dtype checker: the s64/s32 GSPMD miscompile class (PR 2/PR 3).

Incident: under x64, a bare ``jnp.arange`` (or any index producer
defaulting to int64) fed into scatter/gather index tuples mixes s64
indices with the GSPMD partitioner's s32 offset math; this environment's
XLA miscompiles the comparison ("compare(s64, s32) after
spmd-partitioning"). PR 2 pinned every index producer in ops/ to int32 and
added a regex guard; this checker is the AST upgrade — immune to parens in
strings/comments — and extends the scan from ops/ + models/ to the whole
package (delta-patch row vectors, shard bookkeeping, and mesh code all
build index operands too).

Rules:

- ``arange-dtype``: every ``jnp.arange(...)`` passes an explicit ``dtype=``;
- ``argmax-cast``: argmax/argmin/argsort/nonzero/searchsorted results are
  cast to int32 within the same statement;
- ``asarray-index-dtype``: ``jnp.asarray`` of an index-named vector
  (idx/rows/dirty/...) pins int32 in the call.
"""

from __future__ import annotations

import ast
from typing import List

from .base import (Checker, Finding, ModuleSource, attr_chain, build_parents,
                   nearest_statement, register, statement_unit)

ARG_PRODUCERS = ("argmax", "argmin", "argsort", "nonzero", "searchsorted")
INDEXY_NAMES = ("idx", "rows", "dirty", "rows_idx", "prows", "dirty_rows")


def _is_jnp_call(call: ast.Call, attr: str) -> bool:
    chain = attr_chain(call.func)
    return (len(chain) >= 2 and chain[-1] == attr
            and (chain[-2] == "jnp" or chain[-3:-1] == ["jax", "numpy"]))


def _mentions_int32(nodes) -> bool:
    for n in nodes:
        if isinstance(n, ast.Attribute) and n.attr == "int32":
            return True
        if isinstance(n, ast.Name) and n.id == "int32":
            return True
        if isinstance(n, ast.Constant) and n.value == "int32":
            return True
    return False


@register
class IndexDtypeChecker(Checker):
    id = "index-dtype"
    description = ("jnp index producers must pin int32 (s64/s32 GSPMD "
                   "miscompile class)")

    def check(self, mod: ModuleSource) -> List[Finding]:
        out: List[Finding] = []
        tree = mod.tree
        parents = build_parents(tree)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            if _is_jnp_call(node, "arange"):
                if not any(kw.arg == "dtype" for kw in node.keywords):
                    out.append(Finding(
                        self.id, "arange-dtype", mod.path, node.lineno,
                        "jnp.arange without an explicit dtype (defaults to "
                        "int64 under x64; pin int32 for index producers)"))
                continue
            for prod in ARG_PRODUCERS:
                if _is_jnp_call(node, prod):
                    stmt = nearest_statement(parents, node)
                    unit = statement_unit(stmt) if stmt is not None else [node]
                    if not _mentions_int32(unit):
                        out.append(Finding(
                            self.id, "argmax-cast", mod.path, node.lineno,
                            f"jnp.{prod} without an int32 cast in the same "
                            "statement (int64 default rides into index "
                            "tuples)"))
                    break
            else:
                if _is_jnp_call(node, "asarray") and node.args:
                    first = node.args[0]
                    # sorted(<name>) wrapping keeps the index-vector shape
                    if (isinstance(first, ast.Call)
                            and isinstance(first.func, ast.Name)
                            and first.func.id == "sorted" and first.args):
                        first = first.args[0]
                    if (isinstance(first, ast.Name)
                            and first.id in INDEXY_NAMES
                            and not _mentions_int32(ast.walk(node))):
                        out.append(Finding(
                            self.id, "asarray-index-dtype", mod.path,
                            node.lineno,
                            f"jnp.asarray({first.id}, ...) builds an index "
                            "vector without an explicit int32 dtype"))
        return out
