"""hint-freshness checker: NodeInfo-accounting mutations must be visible
to the score-hint cache.

Incident class (ISSUE 12): the score-hint fast path (models/score_hints.py)
binds identical replicas host-side off a per-node walk state whose
freshness is EVENT-DRIVEN — it survives exactly the changes the journal
classification records (core/cache.py EventJournal) plus the counters its
serve() fences (``attempts``, ``state_unwinds``, ``reconcile_unwinds``, the
conflict hook). A code path that mutates the cache's NodeInfo accounting
(``cache.assume_pod`` / ``forget_pod`` / ``add_pod`` / ``remove_pod`` /
``update_pod``) WITHOUT being on that call graph would silently stale the
hint: the walker keeps serving placements computed against rows that no
longer reflect the cluster — the exact bug class the always-dispatch
oracle can never hit, and the hardest to catch in review because the
mutation looks innocent locally.

Rule ``accounting-outside-invalidation-graph``: in the scheduler layers
(``core/scheduler.py``, ``models/``), every function that calls a cache
NodeInfo-accounting mutator must be on the hint-invalidation call graph —
i.e. some same-module call-graph slice containing the mutation also
contains an invalidation sink:

- a journal record (``_record_event`` / ``_record_pod_event``), or
- a serve-fence counter bump (``attempts`` / ``state_unwinds`` /
  ``reconcile_unwinds`` assignment), or
- an explicit hint-cache call (``_hints.<anything>`` /
  ``_note_bind_conflict``, the per-node conflict hook).

"Slice" is computed over the module's own call graph (bare/self method
calls), in both directions: the sink may live in the mutating function, in
a transitive callee, or in a caller whose callee closure contains both the
mutation and a sink (the ``process_one → scheduling_cycle`` shape, where
the attempt counter bumps one frame above the assume).

Snapshot what-if mutations (``snapshot.assume_pod`` — gang simulations)
are exempt by construction: the chain is matched on a ``cache`` base.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set

from .base import (Checker, Finding, ModuleSource, attr_chain, register)

SCOPE = ("core/scheduler.py", "models/")

MUTATORS = {"assume_pod", "forget_pod", "add_pod", "remove_pod",
            "update_pod"}
SINK_CALLS = {"_record_event", "_record_pod_event", "_note_bind_conflict"}
SINK_COUNTERS = {"attempts", "state_unwinds", "reconcile_unwinds"}
HINT_ATTRS = {"_hints"}


def _is_accounting_mutation(call: ast.Call) -> bool:
    if not isinstance(call.func, ast.Attribute):
        return False
    chain = attr_chain(call.func)
    return (len(chain) >= 2 and chain[-1] in MUTATORS
            and "cache" in chain[:-1])


def _fn_facts(fn: ast.AST):
    """(mutation linenos, has_sink, called same-module names) for one def."""
    mutations: List[int] = []
    has_sink = False
    calls: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            if _is_accounting_mutation(node):
                mutations.append(node.lineno)
            chain = attr_chain(node.func)
            if chain:
                if chain[-1] in SINK_CALLS:
                    has_sink = True
                if any(part in HINT_ATTRS for part in chain[:-1]):
                    has_sink = True  # self._hints.<anything>(...)
                # candidate same-module call: bare f() or self.f(...)
                if (len(chain) == 1
                        or (len(chain) == 2 and chain[0] == "self")):
                    calls.add(chain[-1])
        elif isinstance(node, (ast.AugAssign, ast.Assign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                tc = attr_chain(t)
                if tc and tc[-1] in SINK_COUNTERS:
                    has_sink = True
    return mutations, has_sink, calls


@register
class HintFreshnessChecker(Checker):
    id = "hint-freshness"
    description = ("cache NodeInfo-accounting mutations stay on the "
                   "score-hint invalidation call graph (journal record, "
                   "serve-fence counter, or hint-cache call in the same "
                   "call-graph slice)")

    def applies_to(self, relpath: str) -> bool:
        return relpath.startswith(SCOPE[1]) or relpath == SCOPE[0]

    def check(self, mod: ModuleSource) -> List[Finding]:
        tree = mod.tree
        if tree is None:
            return []
        # EVERY def is scanned individually (lock-discipline's lesson:
        # duplicate method names across classes — Handle vs Scheduler
        # delegates — must not shadow each other). Call-graph edges stay
        # name-level (a `self.f()` cannot be resolved to one class here),
        # so per-NAME facts merge each name's defs: calls union, sink OR.
        defs: List = []  # (name, mutations, has_sink, calls)
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                mutations, has_sink, calls = _fn_facts(node)
                defs.append((node.name, mutations, has_sink, calls))
        name_sink: Dict[str, bool] = {}
        name_calls: Dict[str, Set[str]] = {}
        for name, _m, sink, calls in defs:
            name_sink[name] = name_sink.get(name, False) or sink
            name_calls.setdefault(name, set()).update(calls)
        # reach(name): same-module callee-name closure
        reach_memo: Dict[str, Set[str]] = {}

        def reach(name: str) -> Set[str]:
            got = reach_memo.get(name)
            if got is not None:
                return got
            reach_memo[name] = out = set()
            stack = [name]
            while stack:
                for callee in name_calls.get(stack.pop(), ()):
                    if callee not in out and callee in name_calls:
                        out.add(callee)
                        stack.append(callee)
            return out

        def closure_has_sink(names) -> bool:
            return any(name_sink.get(n, False) for n in names)

        def def_covered(name: str, own_sink: bool, calls: Set[str]) -> bool:
            if own_sink:
                return True
            # callee direction, seeded from THIS def's own call set
            down: Set[str] = set()
            for c in calls:
                if c in name_calls:
                    down.add(c)
                    down |= reach(c)
            if closure_has_sink(down):
                return True
            # caller direction: a function whose callee-name closure
            # contains this def's NAME and a sink covers the mutation
            for g, _m, g_sink, _c in defs:
                gr = reach(g)
                if name in gr and (g_sink or closure_has_sink(gr)):
                    return True
            return False

        out: List[Finding] = []
        for name, mutations, own_sink, calls in defs:
            if not mutations or def_covered(name, own_sink, calls):
                continue
            for line in mutations:
                out.append(Finding(
                    self.id, "accounting-outside-invalidation-graph",
                    mod.path, line,
                    f"{name}() mutates cache NodeInfo accounting but no "
                    "call-graph slice through it records a journal event, "
                    "bumps a serve-fence counter (attempts/state_unwinds/"
                    "reconcile_unwinds), or touches the hint cache — a "
                    "live score hint would keep serving stale rows"))
        return out
