"""span-discipline checker: the span/metric instrumentation contract
(PR 8, the telemetry tentpole — docs/OBSERVABILITY.md).

The metrics-discipline checker covers declaration/verb/arity mistakes;
this one covers the two failure modes the span subsystem (core/spans.py)
adds:

- a LIVE span (``start_span``) that is not ended on every path leaks an
  open span: the ring never sees it, the stage silently vanishes from
  p50/p99, and the per-pod chain-completeness gate reads as a mystery gap.
  Record-complete spans (``record``/``event``) and scoped spans
  (``with tracer.span(...)``) are immune by construction — which is why
  they are the default API;
- a span or metric call inside JIT-REACHABLE code is a host-state write
  under trace: it records once at trace time, then never again (the
  jit-purity incident class, composed here via the same reachability
  walker — a tracer call one helper below a kernel is the same bug).

Rules:

- ``span-unended``: ``x = <...>.start_span(...)`` with NO matching
  ``x.end(...)`` / ``<tracer>.end(x)`` in the same function;
- ``span-end-unguarded``: the end call exists but none is inside a
  ``finally`` block (an exception between start and end leaks the span) —
  with/try coverage is the contract;
- ``span-in-jit``: a ``...tracer.<verb>(...)`` / ``...metrics.<attr>.
  inc|observe|set(...)`` call lexically inside a jit-reachable function
  (reachability shared with jit-purity: decorated, jit(fn)-wrapped, or
  transitively called same-module).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set

from .base import (Checker, Finding, ModuleSource, attr_chain, build_parents,
                   register)
from .jit_purity import jit_reachable_functions

TRACER_VERBS = {"record", "event", "span", "start_span", "end",
                "context_for", "proc_ctx"}
METRIC_VERBS = {"inc", "observe", "set"}


def _is_start_span(call: ast.Call) -> bool:
    chain = attr_chain(call.func)
    return bool(chain) and chain[-1] == "start_span"


def _in_finally(node: ast.AST, parents: Dict[ast.AST, ast.AST],
                stop: ast.AST) -> bool:
    """Is `node` lexically inside some Try's finalbody (searching up to the
    enclosing function `stop`)?"""
    child = node
    parent = parents.get(node)
    while parent is not None and child is not stop:
        if isinstance(parent, ast.Try):
            for stmt in parent.finalbody:
                if child is stmt or any(child is n for n in ast.walk(stmt)):
                    return True
        child, parent = parent, parents.get(parent)
    return False


@register
class SpanDisciplineChecker(Checker):
    id = "span-discipline"
    description = ("live spans (start_span) must be ended on all paths "
                   "(with/try coverage); no span or metric call may appear "
                   "inside jit-reachable code")

    def check(self, mod: ModuleSource) -> List[Finding]:
        out: List[Finding] = []
        tree = mod.tree
        parents = build_parents(tree)
        out.extend(self._check_unended(mod, tree, parents))
        out.extend(self._check_jit(mod, tree))
        return out

    # -- span-unended / span-end-unguarded ---------------------------------

    def _check_unended(self, mod: ModuleSource, tree: ast.AST,
                       parents: Dict[ast.AST, ast.AST]) -> List[Finding]:
        out: List[Finding] = []
        fns = [n for n in ast.walk(tree) if isinstance(n, ast.FunctionDef)]
        for fn in fns:
            # starts bound to a name in THIS function (nested defs are their
            # own scope pass — same convention as the donation walker).
            starts: List[ast.Assign] = []
            with_items: Set[ast.Call] = set()
            for node in ast.walk(fn):
                if isinstance(node, ast.With):
                    for item in node.items:
                        if isinstance(item.context_expr, ast.Call):
                            with_items.add(item.context_expr)
            for node in ast.walk(fn):
                if (isinstance(node, ast.Assign) and len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Name)
                        and isinstance(node.value, ast.Call)
                        and _is_start_span(node.value)
                        and node.value not in with_items):
                    starts.append(node)
            if not starts:
                continue
            # end sites: <name>.end(...) or <...>.end(<name>)
            ends: Dict[str, List[ast.Call]] = {}
            for node in ast.walk(fn):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr == "end"):
                    continue
                base = attr_chain(node.func.value)
                if base and len(base) == 1:
                    ends.setdefault(base[0], []).append(node)
                for arg in node.args:
                    if isinstance(arg, ast.Name):
                        ends.setdefault(arg.id, []).append(node)
            for start in starts:
                name = start.targets[0].id
                end_calls = ends.get(name, [])
                if not end_calls:
                    out.append(Finding(
                        self.id, "span-unended", mod.path, start.lineno,
                        f"`{name} = ...start_span(...)` is never ended in "
                        f"{fn.name} — the span leaks and its stage vanishes "
                        "from latency percentiles (use record()/with "
                        "tracer.span() or end() under finally)"))
                elif not any(_in_finally(c, parents, fn) for c in end_calls):
                    out.append(Finding(
                        self.id, "span-end-unguarded", mod.path, start.lineno,
                        f"`{name}` (start_span in {fn.name}) is ended only "
                        "on the straight-line path — an exception between "
                        "start and end leaks the span; end it in a finally "
                        "block or use `with tracer.span(...)`"))
        return out

    # -- span-in-jit -------------------------------------------------------

    def _check_jit(self, mod: ModuleSource, tree: ast.AST) -> List[Finding]:
        out: List[Finding] = []
        for fn in jit_reachable_functions(tree):
            for node in ast.walk(fn):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)):
                    continue
                chain = attr_chain(node.func)
                if not chain:
                    continue
                verb = chain[-1]
                if verb in TRACER_VERBS and "tracer" in chain[:-1]:
                    out.append(Finding(
                        self.id, "span-in-jit", mod.path, node.lineno,
                        f"tracer.{verb}(...) inside jit-reachable "
                        f"{fn.name}: span recording is a host-state write "
                        "under trace — it fires once at trace time, never "
                        "per call"))
                elif verb in METRIC_VERBS and "metrics" in chain[:-1]:
                    out.append(Finding(
                        self.id, "span-in-jit", mod.path, node.lineno,
                        f"metrics call {'.'.join(chain)}(...) inside "
                        f"jit-reachable {fn.name}: the observation is baked "
                        "in at trace time, not recorded per call"))
        return out
