"""eviction-discipline checker: controllers never evict outside the funnel.

Incident class (ISSUE 16): the node-lifecycle controller drains unreachable
nodes through ONE funnel — `RateLimitedEvictor.run_once` takes a token from
the zone's bucket (the rate limiter) and `_evict_one` stamps the
deterministic intent id (the idempotency record) before calling the
apiserver's eviction subresource. A pod delete/evict call site in
``controllers/`` that bypasses that funnel is the mass-eviction storm
waiting to happen: no zone throttle (a partitioned rack becomes 500
simultaneous "evictions"), and no intent ledger (a controller restart
mid-wave re-evicts pods the dead incarnation already drained — the
exactly-once contract silently becomes at-least-once).

Rule ``eviction-outside-funnel``: in ``controllers/``, every function that
calls a pod-removal verb (``.delete_pod(...)`` / ``.evict_pod(...)``) must
sit on a same-module call-graph slice that contains BOTH

- a rate-limiter grant (``.try_take(...)``), and
- an idempotent intent record (``intent_for(...)``).

"Slice" follows hint_freshness's shape: the sinks may live in the calling
function itself, in its same-module callee closure, or in a caller whose
callee closure contains both the call site and the sinks (the
``run_once → _evict_one`` shape, where the token is taken one frame above
the intent stamp). Both sinks must appear in ONE slice — a limiter with no
ledger rate-limits the double-evictions, it doesn't prevent them.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set, Tuple

from .base import Checker, Finding, ModuleSource, attr_chain, register

SCOPE_DIR = "controllers/"

REMOVAL_VERBS = {"delete_pod", "evict_pod"}
LIMITER_SINKS = {"try_take"}
INTENT_SINKS = {"intent_for"}


def _fn_facts(fn: ast.AST) -> Tuple[List[int], bool, bool, Set[str]]:
    """(removal-call linenos, has_limiter, has_intent, same-module callee
    names) for one def."""
    removals: List[int] = []
    has_limiter = False
    has_intent = False
    calls: Set[str] = set()
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if isinstance(func, ast.Attribute):
            # Attribute-name match, not attr_chain: the limiter grant is
            # `self._buckets[zone].try_take()` — a subscript base, which
            # attr_chain refuses — and the removal verbs ride whatever
            # clientset spelling the controller holds.
            if func.attr in REMOVAL_VERBS:
                removals.append(node.lineno)
            if func.attr in LIMITER_SINKS:
                has_limiter = True
            if func.attr in INTENT_SINKS:
                has_intent = True
        elif isinstance(func, ast.Name):
            if func.id in INTENT_SINKS:
                has_intent = True
            if func.id in LIMITER_SINKS:
                has_limiter = True
        chain = attr_chain(func)
        if chain and (len(chain) == 1
                      or (len(chain) == 2 and chain[0] == "self")):
            calls.add(chain[-1])
    return removals, has_limiter, has_intent, calls


@register
class EvictionDisciplineChecker(Checker):
    id = "eviction-discipline"
    description = ("controllers/ pod delete/evict call sites stay on a "
                   "call-graph slice containing both the rate-limiter "
                   "grant (try_take) and the idempotent intent record "
                   "(intent_for)")

    def applies_to(self, relpath: str) -> bool:
        return relpath.startswith(SCOPE_DIR) or ("/" + SCOPE_DIR) in relpath

    def check(self, mod: ModuleSource) -> List[Finding]:
        tree = mod.tree
        if tree is None:
            return []
        # Per-def facts, merged per NAME for call-graph edges (name-level
        # resolution, same caveat as hint_freshness: `self.f()` cannot be
        # pinned to one class here).
        defs: List[Tuple[str, List[int], bool, bool, Set[str]]] = []
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs.append((node.name, *_fn_facts(node)))
        name_limiter: Dict[str, bool] = {}
        name_intent: Dict[str, bool] = {}
        name_calls: Dict[str, Set[str]] = {}
        for name, _r, lim, intent, calls in defs:
            name_limiter[name] = name_limiter.get(name, False) or lim
            name_intent[name] = name_intent.get(name, False) or intent
            name_calls.setdefault(name, set()).update(calls)
        reach_memo: Dict[str, Set[str]] = {}

        def reach(name: str) -> Set[str]:
            got = reach_memo.get(name)
            if got is not None:
                return got
            reach_memo[name] = out = set()
            stack = [name]
            while stack:
                for callee in name_calls.get(stack.pop(), ()):
                    if callee not in out and callee in name_calls:
                        out.add(callee)
                        stack.append(callee)
            return out

        def slice_ok(names: Set[str]) -> bool:
            return (any(name_limiter.get(n, False) for n in names)
                    and any(name_intent.get(n, False) for n in names))

        def def_covered(name: str, calls: Set[str]) -> bool:
            # own def + callee closure
            down = {name}
            for c in calls:
                if c in name_calls:
                    down.add(c)
                    down |= reach(c)
            if slice_ok(down):
                return True
            # caller direction: a def whose callee closure contains this
            # def's NAME gives the slice {caller} ∪ reach(caller)
            for g, _r, _l, _i, _c in defs:
                gr = reach(g)
                if name in gr and slice_ok(gr | {g}):
                    return True
            return False

        out: List[Finding] = []
        for name, removals, _lim, _intent, calls in defs:
            if not removals or def_covered(name, calls):
                continue
            for line in removals:
                out.append(Finding(
                    self.id, "eviction-outside-funnel", mod.path, line,
                    f"{name}() deletes/evicts a pod but no call-graph "
                    "slice through it takes a rate-limiter token "
                    "(try_take) AND records an idempotent intent "
                    "(intent_for) — a naked eviction: unthrottled under "
                    "zone disruption and replayable after a controller "
                    "restart"))
        return out
