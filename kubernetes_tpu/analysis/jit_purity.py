"""jit-purity checker: device kernels must stay traceable (PR 1/PR 2).

Incidents: the device path falls back to the host Evaluator on ANY kernel
exception (PR 1's breaker), so an impure jitted function does not crash —
it silently pins the slow path. And a function that mutates Python state
under trace bakes the first call's value into the compiled executable
(classic jax footgun), which the equivalence fuzz only catches when the
divergence is visible in assignments.

Rules (any function reaching ``jax.jit``/``pjit`` — decorated directly,
via ``partial(jax.jit, ...)``, passed to a ``jit(...)`` call, or called
(transitively, same module) from such a function — helpers called from a
jitted function are traced exactly like their caller):

- ``no-global-mutation``: no ``global``/``nonlocal`` declarations inside a
  traced function;
- ``no-attr-assign``: no assignment to object attributes (mutating
  closed-over/carried Python objects under trace);
- ``no-impure-call``: no calls to impure builtins (print/open/input/exec/
  eval/breakpoint) or host-state modules (time/os/random/sys) — use
  ``jax.debug.print`` for traced debugging;
- ``donated-buffer-reuse``: an argument donated via ``donate_argnums``
  must not be read again after the call in the same scope (the buffer is
  dead; XLA may have aliased it into the output).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from .base import Checker, Finding, ModuleSource, attr_chain, register

IMPURE_BUILTINS = {"print", "open", "input", "exec", "eval", "breakpoint"}
IMPURE_MODULES = {"time", "os", "random", "sys"}


def _is_jit_expr(node: ast.AST) -> bool:
    """`jax.jit`, `jit`, `pjit`, `jax.pjit` as a bare expression."""
    chain = attr_chain(node)
    return bool(chain) and chain[-1] in ("jit", "pjit")


def _jit_wrap_target(call: ast.Call) -> Optional[str]:
    """For `jax.jit(fn, ...)` / `pjit(fn, ...)` / `shard_map(fn, ...)`:
    the wrapped function name. shard_map BODIES run under trace exactly
    like jitted functions (ISSUE 15: the explicit mesh lap kernel), so
    they join the purity scan scope — and, transitively, index-dtype's."""
    chain = attr_chain(call.func)
    is_wrap = (_is_jit_expr(call.func)
               or (bool(chain) and chain[-1] == "shard_map"))
    if is_wrap and call.args and isinstance(call.args[0], ast.Name):
        return call.args[0].id
    return None


def _decorator_is_jit(dec: ast.AST) -> bool:
    if _is_jit_expr(dec):
        return True
    if isinstance(dec, ast.Call):
        if _is_jit_expr(dec.func):  # @jax.jit(static_argnames=...)
            return True
        chain = attr_chain(dec.func)  # @partial(jax.jit, ...)
        if chain and chain[-1] == "partial" and dec.args:
            return _is_jit_expr(dec.args[0])
    return False


def _donate_argnums(call_or_dec: ast.AST) -> Optional[Set[int]]:
    """Statically-known donate_argnums of a jit(...) / partial(jax.jit, ...)
    expression; None when absent or not a constant."""
    if not isinstance(call_or_dec, ast.Call):
        return None
    for kw in call_or_dec.keywords:
        if kw.arg != "donate_argnums":
            continue
        v = kw.value
        if isinstance(v, ast.Constant) and isinstance(v.value, int):
            return {v.value}
        if isinstance(v, (ast.Tuple, ast.List)) and all(
                isinstance(e, ast.Constant) and isinstance(e.value, int)
                for e in v.elts):
            return {e.value for e in v.elts}
        return None
    return None


def jit_reachable_functions(tree: ast.AST) -> List[ast.FunctionDef]:
    """Every function def that can run UNDER TRACE: decorated with jit/
    pjit (incl. partial(jax.jit, ...)), wrapped via ``jit(fn)``, or
    transitively called (same module) from one that is. Shared with the
    span-discipline checker — span/metric calls are host-state effects and
    must never appear inside these (ISSUE 8 composition seam). The result
    is memoized ON the tree object: both checkers visit every module of
    the package, and the reachability walk is the expensive part."""
    memo = getattr(tree, "_jit_reachable_memo", None)
    if memo is not None:
        return memo
    result = _jit_reachable_uncached(tree)
    try:
        tree._jit_reachable_memo = result
    except AttributeError:
        pass  # non-Module roots (fixtures) may not accept attributes
    return result


def _jit_reachable_uncached(tree: ast.AST) -> List[ast.FunctionDef]:
    defs: Dict[str, List[ast.FunctionDef]] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef):
            defs.setdefault(node.name, []).append(node)

    jit_fns: List[ast.FunctionDef] = []
    jit_ids: Set[int] = set()  # id()-keyed membership (no O(n) list scans)

    def _add(fn: ast.FunctionDef) -> None:
        if id(fn) not in jit_ids:
            jit_ids.add(id(fn))
            jit_fns.append(fn)

    for fns in defs.values():
        for fn in fns:
            if any(_decorator_is_jit(dec) for dec in fn.decorator_list):
                _add(fn)
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            target = _jit_wrap_target(node)
            if target and target in defs:
                for f in defs[target]:
                    _add(f)

    # Transitive closure over same-module calls: a helper called from a
    # jitted function is traced exactly like its caller (kernel helpers
    # hold most of the actual math in ops/kernel.py).
    reached = {fn.name for fn in jit_fns}
    frontier = set(reached)
    while frontier:
        nxt = set()
        for name in frontier:
            for fn in defs.get(name, ()):
                for c in ast.walk(fn):
                    if isinstance(c, ast.Call):
                        chain = attr_chain(c.func)
                        if (len(chain) == 1 and chain[0] in defs
                                and chain[0] not in reached):
                            nxt.add(chain[0])
        reached |= nxt
        frontier = nxt
    for name in reached:
        for f in defs[name]:
            _add(f)
    return jit_fns


@register
class JitPurityChecker(Checker):
    id = "jit-purity"
    description = ("functions reaching jax.jit/pjit must not mutate host "
                   "state; donated buffers must not be reused after the "
                   "call")

    def check(self, mod: ModuleSource) -> List[Finding]:
        out: List[Finding] = []
        tree = mod.tree
        defs: Dict[str, List[ast.FunctionDef]] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.FunctionDef):
                defs.setdefault(node.name, []).append(node)

        donated_defs: Dict[str, Set[int]] = {}  # decorated fns w/ donation
        for name, fns in defs.items():
            for fn in fns:
                for dec in fn.decorator_list:
                    if _decorator_is_jit(dec):
                        don = _donate_argnums(dec)
                        if don:
                            donated_defs[name] = don
                        break

        for fn in jit_reachable_functions(tree):
            out.extend(self._check_purity(mod, fn))

        # Donation discipline: per enclosing scope, a name bound to
        # jit(..., donate_argnums=...) — or a call to a donation-decorated
        # def — must not have its donated args read after the call.
        scopes = [tree] + [n for n in ast.walk(tree)
                           if isinstance(n, ast.FunctionDef)]
        for scope in scopes:
            out.extend(self._check_donation(mod, scope, donated_defs))
        return out

    # -- purity -------------------------------------------------------------

    def _check_purity(self, mod: ModuleSource,
                      fn: ast.FunctionDef) -> List[Finding]:
        out: List[Finding] = []
        for node in ast.walk(fn):
            if isinstance(node, (ast.Global, ast.Nonlocal)):
                kind = "global" if isinstance(node, ast.Global) else "nonlocal"
                out.append(Finding(
                    self.id, "no-global-mutation", mod.path, node.lineno,
                    f"`{kind} {', '.join(node.names)}` inside jitted "
                    f"{fn.name}: host-state writes are baked in at trace "
                    "time, not executed per call"))
            targets = []
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                targets = [node.target]
            for t in targets:
                if isinstance(t, ast.Attribute):
                    out.append(Finding(
                        self.id, "no-attr-assign", mod.path, node.lineno,
                        f"attribute assignment inside jitted {fn.name} "
                        "mutates a Python object under trace"))
            if isinstance(node, ast.Call):
                chain = attr_chain(node.func)
                if (isinstance(node.func, ast.Name)
                        and node.func.id in IMPURE_BUILTINS):
                    out.append(Finding(
                        self.id, "no-impure-call", mod.path, node.lineno,
                        f"call to impure builtin {node.func.id}() inside "
                        f"jitted {fn.name} (use jax.debug.* for traced "
                        "debugging)"))
                elif len(chain) >= 2 and chain[0] in IMPURE_MODULES:
                    out.append(Finding(
                        self.id, "no-impure-call", mod.path, node.lineno,
                        f"call to {'.'.join(chain)} inside jitted {fn.name} "
                        "reads/writes host state under trace"))
        return out

    # -- donation -----------------------------------------------------------

    def _check_donation(self, mod: ModuleSource, scope: ast.AST,
                        donated_defs: Dict[str, Set[int]]) -> List[Finding]:
        out: List[Finding] = []
        body = scope.body if hasattr(scope, "body") else []
        donated_callables: Dict[str, Set[int]] = dict(donated_defs)
        # `g = jax.jit(f, donate_argnums=...)` bound in this scope
        for stmt in body:
            if (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)
                    and isinstance(stmt.value, ast.Call)
                    and _is_jit_expr(stmt.value.func)):
                don = _donate_argnums(stmt.value)
                if don:
                    donated_callables[stmt.targets[0].id] = don

        if not donated_callables:
            return out

        # Find calls to donated callables directly in this scope (not in
        # nested defs — those are their own scope pass).
        def iter_scope_nodes(node):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.Lambda)):
                    continue  # nested scopes get their own donation pass
                yield child
                yield from iter_scope_nodes(child)

        scope_nodes = list(iter_scope_nodes(scope))
        rebinds: Dict[str, List[int]] = {}
        loads: Dict[str, List[int]] = {}
        for n in scope_nodes:
            if isinstance(n, ast.Name):
                if isinstance(n.ctx, ast.Store):
                    rebinds.setdefault(n.id, []).append(n.lineno)
                elif isinstance(n.ctx, ast.Load):
                    loads.setdefault(n.id, []).append(n.lineno)
        for n in scope_nodes:
            if not (isinstance(n, ast.Call) and isinstance(n.func, ast.Name)
                    and n.func.id in donated_callables):
                continue
            for pos in donated_callables[n.func.id]:
                if pos >= len(n.args) or not isinstance(n.args[pos], ast.Name):
                    continue
                arg = n.args[pos].id
                # >= : `a = g(a)` rebinds the donated name on the call line
                # itself, shielding every later load.
                next_rebind = min(
                    (ln for ln in rebinds.get(arg, ()) if ln >= n.lineno),
                    default=None)
                for ln in loads.get(arg, ()):
                    if ln > n.lineno and (next_rebind is None
                                          or ln < next_rebind):
                        out.append(Finding(
                            self.id, "donated-buffer-reuse", mod.path, ln,
                            f"`{arg}` is donated to {n.func.id} (line "
                            f"{n.lineno}) but read again here — the buffer "
                            "may be aliased into the output"))
        return out
