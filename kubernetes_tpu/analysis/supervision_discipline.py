"""supervision-discipline checker: every fleet/ child spawn rides the
readiness-barrier + pipe-drain discipline.

Incident class (ISSUE 19, carried from PR 8): a conductor that spawns a
child process without (a) blocking on the child's ready line and (b)
wiring a stdout drain leaves two latent stalls — a follower that starts
"tailing" before the leader serves races the whole bring-up, and an
undrained 64KB pipe buffer wedges any chatty child mid-run (the exact
hang tests/test_faults.py PR-8 chased). Both failure modes look fine in
review because the spawn itself is one innocent line; the discipline
lives in the surrounding call graph.

Rules, over every spawn site (a ``spawn_ready(...)`` or ``Popen(...)``
call) in ``fleet/`` modules:

- ``spawn-no-barrier``: some call-graph slice through the spawning
  function must contain a readiness barrier — a ``spawn_ready`` call (it
  IS the barrier: it blocks until the child's first ready line matches)
  or a call to a wait/ready/barrier-named function (the staged bring-up's
  explicit barriers, e.g. ``_wait_shards_leased``).
- ``spawn-no-drain``: some slice must wire ``drain_pipe`` — the reader
  thread that keeps the child's stdout from filling the pipe.

"Slice" is the hint-freshness checker's notion verbatim: the module's
name-level call graph (bare/self calls) walked in both directions, so the
barrier/drain may live in the spawning function, a transitive callee, or
a caller whose callee closure contains both the spawn and the sink (the
``start → _start_shards → _spawn`` shape, where the lease barrier sits
one frame above the spawn loop).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set, Tuple

from .base import Checker, Finding, ModuleSource, attr_chain, register

SPAWN_CALLS = {"spawn_ready", "Popen"}
DRAIN_CALLS = {"drain_pipe"}
BARRIER_NAME_HINTS = ("wait", "ready", "barrier")


def _is_barrier_name(name: str) -> bool:
    low = name.lower()
    return any(h in low for h in BARRIER_NAME_HINTS)


def _fn_facts(fn: ast.AST):
    """(spawn sites, has_barrier, has_drain, called same-module names)
    for one def. A spawn site is (lineno, callee name)."""
    spawns: List[Tuple[int, str]] = []
    has_barrier = False
    has_drain = False
    calls: Set[str] = set()
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        chain = attr_chain(node.func)
        if not chain:
            continue
        name = chain[-1]
        if name in SPAWN_CALLS:
            spawns.append((node.lineno, name))
        if name in DRAIN_CALLS:
            has_drain = True
        if name == "spawn_ready" or _is_barrier_name(name):
            has_barrier = True
        if len(chain) == 1 or (len(chain) == 2 and chain[0] == "self"):
            calls.add(name)
    return spawns, has_barrier, has_drain, calls


@register
class SupervisionDisciplineChecker(Checker):
    id = "supervision-discipline"
    description = ("fleet/ child spawn sites stay on a call-graph slice "
                   "containing a readiness-barrier wait (spawn_ready or a "
                   "wait/ready/barrier-named call) AND drain_pipe wiring")

    SCOPE_DIRS = ("fleet/",)

    def applies_to(self, relpath: str) -> bool:
        return any(("/" + d) in relpath or relpath.startswith(d)
                   for d in self.SCOPE_DIRS)

    def check(self, mod: ModuleSource) -> List[Finding]:
        tree = mod.tree
        if tree is None:
            return []
        # Per-DEF scan, name-level call graph (hint-freshness's shape:
        # duplicate method names merge calls-union / sink-OR).
        defs: List = []  # (name, spawns, has_barrier, has_drain, calls)
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs.append((node.name, *_fn_facts(node)))
        name_barrier: Dict[str, bool] = {}
        name_drain: Dict[str, bool] = {}
        name_calls: Dict[str, Set[str]] = {}
        for name, _s, barrier, drain, calls in defs:
            name_barrier[name] = name_barrier.get(name, False) or barrier
            name_drain[name] = name_drain.get(name, False) or drain
            name_calls.setdefault(name, set()).update(calls)
        reach_memo: Dict[str, Set[str]] = {}

        def reach(name: str) -> Set[str]:
            got = reach_memo.get(name)
            if got is not None:
                return got
            reach_memo[name] = out = set()
            stack = [name]
            while stack:
                for callee in name_calls.get(stack.pop(), ()):
                    if callee not in out and callee in name_calls:
                        out.add(callee)
                        stack.append(callee)
            return out

        def slice_has(name: str, own: bool, calls: Set[str],
                      table: Dict[str, bool]) -> bool:
            if own:
                return True
            down: Set[str] = set()
            for c in calls:
                if c in name_calls:
                    down.add(c)
                    down |= reach(c)
            if any(table.get(n, False) for n in down):
                return True
            for g, _s, g_barrier, g_drain, _c in defs:
                gr = reach(g)
                if name in gr:
                    g_own = (g_barrier if table is name_barrier
                             else g_drain)
                    if g_own or any(table.get(n, False) for n in gr):
                        return True
            return False

        out: List[Finding] = []
        for name, spawns, own_barrier, own_drain, calls in defs:
            if not spawns:
                continue
            barrier_ok = slice_has(name, own_barrier, calls, name_barrier)
            drain_ok = slice_has(name, own_drain, calls, name_drain)
            for line, callee in spawns:
                if not barrier_ok:
                    out.append(Finding(
                        self.id, "spawn-no-barrier", mod.path, line,
                        f"{name}() spawns a child via {callee} but no "
                        "call-graph slice through it waits on a readiness "
                        "barrier (spawn_ready / a wait|ready|barrier-named "
                        "call) — the staged bring-up can race a child that "
                        "is not serving yet"))
                if not drain_ok:
                    out.append(Finding(
                        self.id, "spawn-no-drain", mod.path, line,
                        f"{name}() spawns a child via {callee} but no "
                        "call-graph slice through it wires drain_pipe — an "
                        "undrained 64KB stdout pipe wedges a chatty child "
                        "mid-run (the PR-8 stall class)"))
        return out
