"""Per-finding allowlist. Policy (docs/ANALYSIS.md):

- an entry names ONE finding — (checker, file, line) — and carries a
  mandatory one-line reason; entries without a reason fail validation;
- an entry that no longer suppresses anything is stale and fails the run
  (the engine reports it in ``unused_allowlist``), so line drift or a fix
  forces the entry to be updated or deleted, never silently carried;
- real violations get FIXED, not allowlisted: an entry is only for code
  that is deliberately, provably exempt from the invariant (e.g. genuine
  int64 quantity math whose result never indexes a scatter/gather).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Tuple


@dataclass(frozen=True)
class Allow:
    checker: str  # checker id the entry suppresses
    path: str     # package-relative path (suffix match, so "ops/kernel.py")
    line: int     # 1-based line of the finding
    reason: str   # mandatory: why this site is exempt

    def matches(self, finding) -> bool:
        return (finding.checker == self.checker
                and finding.line == self.line
                and (finding.path == self.path
                     or finding.path.endswith("/" + self.path)))


# The tree currently runs clean: every violation the checkers surfaced was
# fixed in place (see docs/ANALYSIS.md per-checker incident notes), so no
# entries are needed. Keep it that way — additions require a reason.
ALLOWLIST: Tuple[Allow, ...] = ()


def validate_allowlist(entries) -> None:
    for a in entries:
        if not isinstance(a, Allow):
            raise TypeError(f"allowlist entry {a!r} is not an Allow")
        if not a.reason or not a.reason.strip():
            raise ValueError(
                f"allowlist entry for {a.checker}:{a.path}:{a.line} has no "
                "reason — every suppression must say why")
