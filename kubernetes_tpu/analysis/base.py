"""Static-analysis core: findings, checker registry, engine, allowlist.

The analyzers exist because five PRs of scale-out work accumulated
invariants that are cheap to violate and expensive to debug (see
docs/ANALYSIS.md for the incident behind each checker). Every checker is
AST-based — no string-literal-naive paren matching — and runs over the
whole ``kubernetes_tpu`` package unless it narrows its own scope.

Contract:

- a checker emits :class:`Finding`s; the engine subtracts allowlisted ones
  (``allowlist.py`` — every entry carries a mandatory reason) and reports
  the rest;
- a stale allowlist entry (nothing left to suppress) is itself a failure:
  the tree moved, the entry must go;
- ``python -m kubernetes_tpu.analysis`` exits nonzero on any finding, so
  the tier-1 wrapper (tests/test_static_analysis.py) gates every PR.
"""

from __future__ import annotations

import ast
import pathlib
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Tuple, Type

PKG_ROOT = pathlib.Path(__file__).resolve().parent.parent


@dataclass(frozen=True)
class Finding:
    """One invariant violation at one site."""

    checker: str   # checker id, e.g. "index-dtype"
    rule: str      # sub-rule id, e.g. "arange-dtype"
    path: str      # package-relative posix path (or "<fixture>")
    line: int
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: [{self.checker}/{self.rule}] {self.message}"


class ModuleSource:
    """One parsed source file handed to every checker."""

    def __init__(self, path: str, source: str):
        self.path = path              # package-relative posix path
        self.name = path.rsplit("/", 1)[-1]
        self.source = source
        self._tree: Optional[ast.Module] = None
        self.parse_error: Optional[SyntaxError] = None

    @property
    def tree(self) -> Optional[ast.Module]:
        if self._tree is None and self.parse_error is None:
            try:
                self._tree = ast.parse(self.source, filename=self.path)
            except SyntaxError as e:
                self.parse_error = e
        return self._tree


class Checker:
    """Base class: subclasses set ``id``/``description`` and implement
    ``check``. ``applies_to`` narrows the file scope for the tree scan;
    ``check_source`` (module-level helper) bypasses it for fixtures."""

    id: str = ""
    description: str = ""

    def applies_to(self, relpath: str) -> bool:
        return True

    def check(self, mod: ModuleSource) -> List[Finding]:
        raise NotImplementedError


_REGISTRY: Dict[str, Type[Checker]] = {}


def register(cls: Type[Checker]) -> Type[Checker]:
    if not cls.id:
        raise ValueError(f"checker {cls.__name__} has no id")
    if cls.id in _REGISTRY:
        raise ValueError(f"duplicate checker id {cls.id!r}")
    _REGISTRY[cls.id] = cls
    return cls


def all_checkers() -> List[Checker]:
    # Import the checker modules for their registration side effect.
    from . import (deschedule_discipline, eviction_discipline,  # noqa: F401
                   hint_freshness, index_dtype, jit_purity,
                   lock_discipline, metrics_discipline,
                   reconcile_discipline, shed_discipline,
                   sharding_discipline, span_discipline,
                   supervision_discipline, thread_hygiene, wire_discipline)
    return [cls() for _, cls in sorted(_REGISTRY.items())]


def checker_by_id(checker_id: str) -> Checker:
    all_checkers()  # ensure registration ran
    return _REGISTRY[checker_id]()


# ---------------------------------------------------------------------------
# Engine
# ---------------------------------------------------------------------------


@dataclass
class Report:
    findings: List[Finding] = field(default_factory=list)
    suppressed: List[Tuple[Finding, "object"]] = field(default_factory=list)
    unused_allows: List["object"] = field(default_factory=list)
    files_scanned: int = 0

    @property
    def clean(self) -> bool:
        # A stale allowlist entry is a failure too: the violation it named
        # no longer exists, so the entry must be deleted, not carried.
        return not self.findings and not self.unused_allows

    def to_json(self) -> dict:
        return {
            "clean": self.clean,
            "files_scanned": self.files_scanned,
            "findings": [
                {"checker": f.checker, "rule": f.rule, "path": f.path,
                 "line": f.line, "message": f.message}
                for f in self.findings],
            "suppressed": [
                {"checker": f.checker, "path": f.path, "line": f.line,
                 "reason": a.reason}
                for f, a in self.suppressed],
            "unused_allowlist": [
                {"checker": a.checker, "path": a.path, "line": a.line,
                 "reason": a.reason}
                for a in self.unused_allows],
        }


def iter_sources(root: pathlib.Path) -> List[ModuleSource]:
    mods = []
    for path in sorted(root.rglob("*.py")):
        rel = path.relative_to(root).as_posix()
        mods.append(ModuleSource(rel, path.read_text()))
    return mods


def analyze(root: Optional[pathlib.Path] = None,
            checkers: Optional[Sequence[Checker]] = None,
            allowlist: Optional[Iterable] = None) -> Report:
    """Run every (or the given) checker over every ``.py`` under ``root``
    (default: the installed ``kubernetes_tpu`` package)."""
    from .allowlist import ALLOWLIST, validate_allowlist

    root = root or PKG_ROOT
    checkers = list(checkers) if checkers is not None else all_checkers()
    allows = list(ALLOWLIST if allowlist is None else allowlist)
    validate_allowlist(allows)

    report = Report()
    raw: List[Finding] = []
    for mod in iter_sources(root):
        report.files_scanned += 1
        for checker in checkers:
            if not checker.applies_to(mod.path):
                continue
            if mod.tree is None:
                raw.append(Finding(checker.id, "parse-error", mod.path,
                                   mod.parse_error.lineno or 0,
                                   f"syntax error: {mod.parse_error.msg}"))
                break
            raw.extend(checker.check(mod))

    used = set()
    for f in raw:
        allow = next((a for a in allows if a.matches(f)), None)
        if allow is not None:
            used.add(id(allow))
            report.suppressed.append((f, allow))
        else:
            report.findings.append(f)
    report.unused_allows = [a for a in allows if id(a) not in used]
    report.findings.sort(key=lambda f: (f.path, f.line, f.checker, f.rule))
    return report


def check_source(checker: Checker, source: str,
                 path: str = "<fixture>") -> List[Finding]:
    """Run one checker on raw source — the self-test fixture seam. Bypasses
    ``applies_to`` and the allowlist."""
    mod = ModuleSource(path, source)
    if mod.tree is None:
        raise mod.parse_error
    return checker.check(mod)


# ---------------------------------------------------------------------------
# Shared AST helpers
# ---------------------------------------------------------------------------


def attr_chain(node: ast.AST) -> List[str]:
    """``a.b.c`` -> ["a", "b", "c"]; empty list when the base is not a
    plain name (e.g. a call result)."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return list(reversed(parts))
    return []


def nearest_statement(parents: Dict[ast.AST, ast.AST],
                      node: ast.AST) -> Optional[ast.stmt]:
    while node is not None and not isinstance(node, ast.stmt):
        node = parents.get(node)
    return node


def build_parents(tree: ast.AST) -> Dict[ast.AST, ast.AST]:
    parents: Dict[ast.AST, ast.AST] = {}
    for parent in ast.walk(tree):
        for child in ast.iter_child_nodes(parent):
            parents[child] = parent
    return parents


def statement_unit(stmt: ast.stmt) -> List[ast.AST]:
    """The nodes that belong to `stmt` itself: for a simple statement the
    whole subtree, for a compound statement only its header expressions
    (test/iter/items/...), never the nested bodies — those are their own
    statements. This is the AST replacement for the old guard's "statement
    text" scan, immune to strings/comments containing parens."""
    compound_body_fields = ("body", "orelse", "finalbody", "handlers")
    if not any(hasattr(stmt, f) for f in compound_body_fields):
        return list(ast.walk(stmt))
    nodes: List[ast.AST] = [stmt]
    for name, value in ast.iter_fields(stmt):
        if name in compound_body_fields:
            continue
        nodes.extend(_walk_value(value))
    return nodes


def _walk_value(value) -> Iterable[ast.AST]:
    if isinstance(value, ast.AST):
        yield from ast.walk(value)
    elif isinstance(value, list):
        for item in value:
            if isinstance(item, ast.AST):
                yield from ast.walk(item)
