"""The scheduler binary: the cmd/kube-scheduler analogue.

    python -m kubernetes_tpu [--config sched.yaml] [--port 10259]
                             [--cluster cluster.yaml] [--leader-elect]
                             [--identity scheduler-0] [--once]

Re-expresses cmd/kube-scheduler/app/server.go's wiring (Run :183): parse the
KubeSchedulerConfiguration, build the (TPU-backed) scheduler, expose
/healthz /readyz /metrics /debug/cache /debug/comparer, optionally campaign
for leadership, and drive the scheduling loop.

Without a real apiserver, `--cluster` bootstraps the clientset from a YAML
manifest (nodes/pods/podGroups in the perf harness's template shapes), and
the process keeps scheduling whatever arrives through the clientset until
interrupted (`--once` exits after the queue drains — the smoke-test mode).
"""

from __future__ import annotations

import argparse
import signal
import sys
import time


def _load_cluster(cs, path: str) -> None:
    import yaml

    from .perf.harness import _make_node_from_template, _make_pod_from_template
    from .api.types import PodGroup

    with open(path) as f:
        doc = yaml.safe_load(f) or {}
    for i, tpl in enumerate(doc.get("nodes", ())):
        count = int(tpl.pop("count", 1))
        for j in range(count):
            cs.create_node(_make_node_from_template(i * 100000 + j, tpl))
    for g in doc.get("podGroups", ()):
        cs.create_pod_group(PodGroup(
            name=g["name"], min_count=int(g.get("minCount", 1)),
            topology_keys=tuple(g.get("topologyKeys", ()))))
    seq = 0
    for tpl in doc.get("pods", ()):
        count = int(tpl.pop("count", 1))
        for _ in range(count):
            cs.create_pod(_make_pod_from_template(f"pod-{seq}", tpl))
            seq += 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="kubernetes-tpu-scheduler")
    ap.add_argument("--config", default="",
                    help="KubeSchedulerConfiguration YAML (core/config.py)")
    ap.add_argument("--cluster", default="",
                    help="bootstrap manifest: nodes/pods/podGroups")
    ap.add_argument("--api-url", default="",
                    help="schedule against a remote apiserver "
                         "(core/apiserver.py REST+watch) instead of the "
                         "in-process store; with a replicated control "
                         "plane, point this at the shard's FOLLOWER")
    ap.add_argument("--api-fallbacks", default="",
                    help="comma-separated sibling replica base URLs: the "
                         "reflector rotates to one (and RESUMEs by rv) "
                         "when --api-url's replica dies")
    ap.add_argument("--port", type=int, default=10259,
                    help="healthz/metrics port (0 = ephemeral)")
    ap.add_argument("--leader-elect", action="store_true")
    ap.add_argument("--identity", default="scheduler-0")
    ap.add_argument("--shard-index", type=int, default=-1,
                    help="join the shard plane as shard i of --shard-count "
                         "(requires --api-url; kubernetes_tpu/shard/)")
    ap.add_argument("--shard-count", type=int, default=0,
                    help="total shard slots in the plane")
    ap.add_argument("--shard-lease-duration", type=float, default=3.0,
                    help="shard lease duration in seconds (failover takes "
                         "at most one lease period + one renew interval)")
    ap.add_argument("--once", action="store_true",
                    help="exit once the queue drains (smoke/test mode)")
    ap.add_argument("--platform", default="auto",
                    choices=("auto", "cpu", "tpu"),
                    help="JAX platform; 'cpu' forces the host backend via "
                         "the config API BEFORE backend init (the axon TPU "
                         "plugin ignores the JAX_PLATFORMS env var)")
    args = ap.parse_args(argv)

    if args.platform == "cpu":
        import jax
        jax.config.update("jax_platforms", "cpu")

    from .core.config import SchedulerConfiguration
    from .core.server import SchedulerServer
    from .models import TPUScheduler

    cfg = None
    if args.config:
        import yaml
        with open(args.config) as f:
            cfg = SchedulerConfiguration.from_dict(yaml.safe_load(f) or {})
        errs = cfg.validate()
        if errs:
            # kube-scheduler refuses an invalid KubeSchedulerConfiguration
            # (validation.go aggregate -> fatal at startup).
            for e in errs:
                print(f"invalid configuration: {e}", file=sys.stderr)
            return 1
    if args.shard_index >= 0 and cfg is None:
        # Shard-plane processes bind over real HTTP. Async dispatch (the
        # SchedulerAsyncAPICalls thread mode) overlaps every bind's RTT
        # with the commit loop instead of stalling it per pod — the single
        # worker preserves write order, and a late 409 unwinds through
        # on_async_bind_error into the conflict requeue path. A tighter
        # GIL switch interval keeps the worker's socket wakeups from being
        # convoy-delayed behind the reflector thread (which is busy
        # decoding every peer shard's events): at the default 5ms, worker
        # throughput alone can cap binds near 200/s.
        import sys as _sys
        _sys.setswitchinterval(0.001)
        cfg = SchedulerConfiguration(async_dispatch_threads=True)
    cs_kw = {}
    if args.api_url:
        from .core.apiserver import HTTPClientset
        from .core.clientset import RetryingClientset
        # Production shape: every write verb retries transient apiserver
        # failures with backoff before surfacing an error to the scheduler
        # (core/backoff.py; docs/RESILIENCE.md). Calls routed through the
        # async API dispatcher retry at that layer TOO — the layers compose
        # (worst case attempts multiply, bounded by both small budgets);
        # the wrapper here is what covers the dispatcher-less sync writes.
        # Shard members open SERVER-FILTERED watch streams (?shard=i/n,
        # core/watchcache.py): foreign plain pods arrive as slim
        # projections, so this shard's event decode scales with 1/n.
        shard = ((args.shard_index, args.shard_count)
                 if args.shard_index >= 0 and args.shard_count > 0 else None)
        cs_kw["clientset"] = RetryingClientset(HTTPClientset(
            args.api_url,
            fallbacks=[u for u in args.api_fallbacks.split(",") if u],
            shard=shard))
    sched = TPUScheduler(config=cfg, **cs_kw)
    if args.cluster:
        _load_cluster(sched.clientset, args.cluster)

    # Observability (docs/OBSERVABILITY.md): label this process's spans so
    # cross-process trace merges attribute stages, and install the flight
    # recorder when a dump directory is configured (the shard harness sets
    # TPU_SCHED_FLIGHTREC_DIR for bench --trace and the chaos suites).
    import os
    sched.tracer.proc = (f"shard-{args.shard_index}"
                         if args.shard_index >= 0 else args.identity)
    flight = None
    flight_dir = os.environ.get("TPU_SCHED_FLIGHTREC_DIR", "")
    if flight_dir:
        from .core.spans import FlightRecorder
        flight = FlightRecorder(
            flight_dir, tracer=sched.tracer, recorder=sched.recorder,
            scheduler=sched).install(
            at_exit=True,
            autodump_interval=float(
                os.environ.get("TPU_SCHED_FLIGHTREC_INTERVAL", "5.0")))

    member = None
    if args.shard_index >= 0:
        if not args.api_url or args.shard_count <= args.shard_index:
            print("--shard-index requires --api-url and a larger "
                  "--shard-count", file=sys.stderr)
            return 1
        from .shard import ShardMember
        member = ShardMember(sched, args.shard_index, args.shard_count,
                             lease_duration=args.shard_lease_duration,
                             identity=f"{args.identity}-shard-{args.shard_index}")
        member.start_renewer()  # lease acquired before announcing ready;
        member.tick()           # background renewals survive long drains

    server = SchedulerServer(sched, identity=args.identity,
                             leader_elect=args.leader_elect)
    port = server.serve(args.port)
    print(f"kubernetes-tpu-scheduler: serving on 127.0.0.1:{port} "
          f"(profiles: {', '.join(sched.profiles)})", flush=True)

    stop = {"flag": False}

    def _sig(_s, _f):
        stop["flag"] = True

    signal.signal(signal.SIGTERM, _sig)
    signal.signal(signal.SIGINT, _sig)

    try:
        while not stop["flag"]:
            # Sharded runs also refresh ownership per CYCLE via the
            # scheduler's loop_hook; this outer tick covers idle stretches.
            if member is not None:
                member.tick()
            progressed = server.run_cycles()
            if args.once and not progressed:
                active, backoff, _unsched = sched.queue.pending_counts()
                if active == 0 and backoff == 0:
                    # Drained (parked-unschedulable pods don't block exit —
                    # they are reported in the failure count below).
                    break
            if not progressed:
                time.sleep(0.02)
    finally:
        server.shutdown()
        if flight is not None:
            flight.dump("shutdown")
            flight.close()
    try:
        print(f"kubernetes-tpu-scheduler: scheduled={sched.scheduled} "
              f"failures={sched.failures}", flush=True)
    except BrokenPipeError:
        # Parent closed our stdout: drop the buffered bytes too, or the
        # interpreter's exit-time flush re-raises outside this guard.
        import os
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
    return 0


if __name__ == "__main__":
    sys.exit(main())
