"""The hollow-node plane: N synthetic kubelets in one process.

The reference scales its control-plane tests with kubemark
(`pkg/kubemark/hollow_kubelet.go`): a hollow node runs the real kubelet
control loops against the real apiserver but fakes the container
runtime, so a handful of processes impersonate thousands of nodes. This
module is that layer for our plane:

- **register** — bulk node creates (`POST /api/v1/nodes` with a JSON
  array, the PR-5 bulk-commit shape) in profile-sized chunks from a
  small thread pool; 50k nodes arrive in ~100 requests, not 50k;
- **heartbeat** — a paced sweep: every tick the next slice of the fleet
  POSTs the node-status heartbeat sink in ONE bulk request
  (`/api/v1/nodes/status`, the kubelet heartbeat parity stub) — the
  whole fleet heartbeats every ``heartbeat_s`` without the write plane
  seeing per-node requests. A ``drift`` fraction of heartbeats instead
  PUTs a REAL node update with allocatable cpu drifted ±1 core
  (bounded to [½×, 2×] of the shape), driving genuine MODIFIED fanout,
  journal classification, and device-mirror row patches;
- **churn waves** — at ``churn_per_s``, cordon a victim (unschedulable
  node update), dwell ``churn_cordon_s``, then DELETE it and register a
  fresh replacement of the same shape (fleet size stays constant): the
  node-lifecycle half of a MixedChurn workload at hollow scale;
- **failure injection** — a ``silence`` fraction of the fleet simply
  stops heartbeating ``silence_after_s`` seconds into the run (the nodes
  stay registered: a dead kubelet, not a deleted node), a ``flap``
  fraction alternates silent/alive every ``flap_period_s``, and
  ``outage_zone`` blacks out one whole topology zone after
  ``outage_after_s``. Victims are picked deterministically from the
  profile seed, so a chaos scenario knows EXACTLY which nodes the
  node-lifecycle controller must declare Unknown and drain.

The plane keeps per-node wire dicts as its only state; everything it
does to the cluster flows through the public REST surface, so leader
redirects, WAL durability, replication shipping, and watch fanout are
exercised exactly as real kubelets would.
"""

from __future__ import annotations

import random
import threading
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Deque, Dict, List, Tuple

from ..core.apiserver import KeepAliveClient
from .profile import HollowProfile


class HollowNodePlane:
    def __init__(self, base_url: str, profile: HollowProfile,
                 now=time.monotonic):
        self.base = base_url.rstrip("/")
        self.profile = profile
        self.now = now
        self._client = KeepAliveClient(self.base)
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        # Guards the fleet dicts (heartbeat slices, churn victims, and
        # re-registration all touch them from different threads).
        self._lock = threading.Lock()
        self._nodes: Dict[str, dict] = {}       # name -> live wire dict
        self._shape_ix: Dict[str, int] = {}     # name -> shape index arg
        self._order: List[str] = []             # heartbeat round-robin
        self._hb_pos = 0
        self._cordoned: Deque[Tuple[float, str]] = deque()
        self._seq = profile.count               # legacy replacement sequence
        self._gen = 1                           # split replacement generation
        # Split members (profile.total > 0) decorrelate their rng streams
        # by offset so two members don't churn lock-step victim indices;
        # a standalone plane (offset 0) keeps the historical stream.
        mix = profile.offset * 0x9E3779B1 if profile.total else 0
        self._rng = random.Random((profile.seed or 0x5ca1e) ^ mix)
        # Failure-injection victims get their OWN rng stream so enabling
        # silence/flap never perturbs the drift/churn sequences of an
        # otherwise-identical profile (scenario diffing stays apples-to-
        # apples). Victims are picked at start(); replacements for churned
        # victims are new names and therefore healthy — like real fleets.
        self._fault_rng = random.Random(
            (profile.seed or 0x5ca1e) ^ 0xFA11 ^ mix)
        self._silent: set = set()
        self._flappers: set = set()
        self._started_at: float = float("inf")
        # Counters (stats()): what the plane actually did to the cluster.
        self.registered = 0
        self.adopted = 0
        self.heartbeats = 0
        self.drifts = 0
        self.cordons = 0
        self.deletes = 0
        self.reregisters = 0
        self.silenced_beats = 0
        self.errors = 0
        # Bulk heartbeat POSTs whose body went out on the negotiated
        # binary codec (PR 18): at 50k nodes this is the largest
        # client->server stream, and the server's "status" wire surface
        # (apiserver_wire_bytes_total{surface="status"}) is the other
        # half of the proof that it left JSON.
        self.hb_wire_posts = 0
        self.hb_json_posts = 0
        # Imbalance knob (profile.imbalance): capacity-skewed churn
        # replacements, and the achieved mean |factor-1| for the stats
        # line — the reproducibility oracle a seeded run asserts against.
        self.skewed = 0
        self._skew_sum = 0.0

    # -- lifecycle ----------------------------------------------------------

    def register(self, adopt: bool = False) -> int:
        """Bulk-register this plane's index range. Returns the node count
        the server acknowledged (duplicates from a retried chunk are fine
        — the bulk create skips and reports them).

        With ``adopt=True`` (a supervised restart of a fleet member), the
        plane first paged-LISTs the cluster, adopts the survivors of its
        own range — slot names and its slot-encoded replacements — and
        creates only the slots with no live node, so a kill9'd member
        comes back to exactly its spec range with zero duplicates."""
        prof = self.profile
        adopted: Dict[int, dict] = {}
        if adopt:
            adopted = self._adopt_existing()
        wires = [prof.node_wire(i) for i in prof.index_range()
                 if i not in adopted]
        with self._lock:
            for w, i in sorted(
                    [(w, self._slot_of(w["name"])) for w in wires]
                    + [(w, i) for i, w in adopted.items()],
                    key=lambda t: t[1]):
                self._nodes[w["name"]] = w
                self._shape_ix[w["name"]] = i
                self._order.append(w["name"])
        chunks = [wires[i:i + prof.register_chunk]
                  for i in range(0, len(wires), prof.register_chunk)]

        def post(chunk):
            return self._client.call("POST", "/api/v1/nodes", chunk,
                                     timeout=120.0)

        with ThreadPoolExecutor(max_workers=max(1, prof.threads)) as ex:
            for res in ex.map(post, chunks):
                self.registered += int((res or {}).get("created", 0))
                self.registered += int((res or {}).get("alreadyExists", 0))
        self.adopted = len(adopted)
        return self.registered + self.adopted

    # -- sub-range ownership (the conductor's restart-with-adoption seam) ---

    def _slot_of(self, name: str):
        """The absolute slot index a node name belongs to, or None if the
        name is not one this plane's range owns. Slot names are
        ``{prefix}-{i}``; split replacements encode their slot as
        ``{prefix}-{i}r{gen}``; legacy replacements (``{prefix}-r{seq}``,
        the standalone plane's scheme) belong to the sole plane."""
        prof = self.profile
        head = prof.name_prefix + "-"
        if not name.startswith(head):
            return None
        tail = name[len(head):]
        if tail.isdigit():
            i = int(tail)
            return i if i in prof.index_range() else None
        slot, _r, gen = tail.partition("r")
        if _r and gen.isdigit():
            if slot.isdigit():                   # split scheme: {i}r{gen}
                i = int(slot)
                return i if i in prof.index_range() else None
            if not slot and not prof.total:      # legacy: r{seq}, standalone
                return prof.offset + int(gen) % max(1, prof.count)
        return None

    def _replacement_name(self, ix: int) -> str:
        prof = self.profile
        if prof.total:                           # split member: slot-encoded
            name = f"{prof.name_prefix}-{ix}r{self._gen}"
            self._gen += 1
            return name
        name = f"{prof.name_prefix}-r{self._seq}"
        self._seq += 1
        return name

    def _adopt_existing(self) -> Dict[int, dict]:
        """Paged-LIST the cluster and claim the live nodes of this
        plane's range (slot -> wire). Cordoned survivors (a churn wave
        interrupted by the crash) are uncordoned so the adopted fleet
        returns to spec. Never raises — adoption errors mean the node is
        re-created instead."""
        from ..core.apiserver import fetch_paged
        out: Dict[int, dict] = {}
        try:
            listed = fetch_paged(self.base, "nodes", limit=2000)
        except Exception:  # noqa: BLE001 - fall back to plain re-register
            self.errors += 1
            return out
        for wire in listed:
            name = wire.get("name", "")
            ix = self._slot_of(name)
            if ix is None:
                continue
            tail = name.rsplit("r", 1)
            if len(tail) == 2 and tail[1].isdigit():
                self._gen = max(self._gen, int(tail[1]) + 1)
                self._seq = max(self._seq, int(tail[1]) + 1)
            if ix in out:                        # duplicate for one slot:
                continue                         # keep the first, leave the
            if wire.get("unschedulable"):        # rest to churn/lifecycle
                wire = dict(wire, unschedulable=False)
                try:
                    self._client.call("PUT", f"/api/v1/nodes/{name}", wire)
                except Exception:  # noqa: BLE001
                    self.errors += 1
            out[ix] = wire
        return out

    def start(self) -> "HollowNodePlane":
        if self._threads:
            return self
        self._started_at = self.now()
        self._pick_fault_victims()
        t = threading.Thread(target=self._heartbeat_loop,
                             name="hollow-heartbeat", daemon=True)
        t.start()
        self._threads.append(t)
        if self.profile.churn_per_s > 0:
            t = threading.Thread(target=self._churn_loop,
                                 name="hollow-churn", daemon=True)
            t.start()
            self._threads.append(t)
        return self

    def stop(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=10)
        self._threads = []

    def stats(self) -> dict:
        with self._lock:
            live = len(self._nodes)
        return {"count": self.profile.count, "live": live,
                "offset": self.profile.offset,
                "registered": self.registered, "adopted": self.adopted,
                "heartbeats": self.heartbeats, "drifts": self.drifts,
                "cordons": self.cordons, "deletes": self.deletes,
                "reregisters": self.reregisters,
                "silenced": len(self._silent),
                "flapping": len(self._flappers),
                "silenced_beats": self.silenced_beats,
                "hb_wire_posts": self.hb_wire_posts,
                "hb_json_posts": self.hb_json_posts,
                "skewed": self.skewed,
                "achieved_skew": round(
                    self._skew_sum / max(1, self.skewed), 4),
                "errors": self.errors}

    # -- failure injection (silence / flap / zone outage) -------------------

    def _pick_fault_victims(self) -> None:
        """Deterministic victim selection off the fault rng: the chaos
        harness replays the same picks from the profile alone and asserts
        the controller drains exactly this set and nothing else."""
        prof = self.profile
        with self._lock:
            fleet = [n for n in self._order if n in self._nodes]
        k_silent = min(len(fleet), int(len(fleet) * max(0.0, prof.silence)))
        if k_silent:
            self._silent = set(self._fault_rng.sample(fleet, k_silent))
        rest = [n for n in fleet if n not in self._silent]
        k_flap = min(len(rest), int(len(fleet) * max(0.0, prof.flap)))
        if k_flap:
            self._flappers = set(self._fault_rng.sample(rest, k_flap))

    def silent_nodes(self) -> List[str]:
        """The permanently-silent victim set (NOT flappers / outage zone) —
        the oracle the chaos scenarios diff survivor placements against."""
        return sorted(self._silent)

    def _silent_now(self, name: str, now: float) -> bool:
        """Is this node refusing to heartbeat at `now`? Callers hold
        `_lock` (reads `_shape_ix` for the zone check)."""
        prof = self.profile
        elapsed = now - self._started_at
        if elapsed < 0:
            return False
        if name in self._silent and elapsed >= prof.silence_after_s:
            return True
        if (prof.outage_zone >= 0 and prof.zones
                and elapsed >= prof.outage_after_s):
            ix = self._shape_ix.get(name)
            if ix is not None and ix % prof.zones == prof.outage_zone:
                return True
        if name in self._flappers and prof.flap_period_s > 0:
            # Phase 0 alive, phase 1 silent, ... — a flapper always gets
            # one clean period of heartbeats before its first death.
            if int(elapsed / prof.flap_period_s) % 2 == 1:
                return True
        return False

    # -- heartbeats (+ capacity drift) --------------------------------------

    _TICK = 0.25

    def _heartbeat_loop(self) -> None:
        prof = self.profile
        carry = 0.0
        while not self._stop.wait(self._TICK):
            # Slice size so the whole fleet sweeps once per heartbeat_s.
            with self._lock:
                fleet = len(self._order)
            if not fleet:
                continue
            carry += fleet * self._TICK / max(self._TICK, prof.heartbeat_s)
            due = int(carry)
            if due <= 0:
                continue
            carry -= due
            with self._lock:
                names = [self._order[(self._hb_pos + j) % len(self._order)]
                         for j in range(min(due, len(self._order)))]
                self._hb_pos = (self._hb_pos + len(names)) % max(
                    1, len(self._order))
                names = [n for n in names if n in self._nodes]
                if self._silent or self._flappers or prof.outage_zone >= 0:
                    now = self.now()
                    kept = [n for n in names
                            if not self._silent_now(n, now)]
                    self.silenced_beats += len(names) - len(kept)
                    names = kept
            if not names:
                continue
            try:
                # One bulk POST to the heartbeat sink for the whole slice:
                # the write plane sees one request, not len(names). The
                # body rides the KeepAliveClient's negotiated codec —
                # binary after register()'s first reply proved the server
                # speaks it, so the fleet's biggest upstream never pays
                # JSON framing (the server bills it to the "status" wire
                # surface).
                self._client.call("POST", "/api/v1/nodes/status",
                                  {"names": names})
                if self._client._server_wire:
                    self.hb_wire_posts += 1
                else:
                    self.hb_json_posts += 1
                self.heartbeats += len(names)
            except Exception:  # noqa: BLE001 - transient; next sweep retries
                self.errors += 1
                continue
            if prof.drift > 0:
                k = int(len(names) * prof.drift)
                if k == 0 and self._rng.random() < len(names) * prof.drift:
                    k = 1
                for name in self._rng.sample(names, min(k, len(names))):
                    self._drift_one(name)

    def _drift_one(self, name: str) -> None:
        """One real capacity drift: allocatable cpu ±1 core, bounded to
        [½×, 2×] the shape's base — a genuine node UPDATE with MODIFIED
        fanout, exactly what autoscaler/kubelet capacity jitter does."""
        with self._lock:
            wire = self._nodes.get(name)
            if wire is None:
                return
            ix = self._shape_ix.get(name, 0)
            base = int(self.profile.shape_for(ix).cpu) * 1000
            cur = int(wire["allocatable"]["cpu"])
            step = 1000 if self._rng.random() < 0.5 else -1000
            nxt = min(base * 2, max(base // 2, cur + step))
            if nxt == cur:
                nxt = min(base * 2, max(base // 2, cur - step))
            wire = dict(wire, allocatable=dict(
                wire["allocatable"], cpu=nxt))
            self._nodes[name] = wire
        try:
            self._client.call("PUT", f"/api/v1/nodes/{name}", wire)
            self.drifts += 1
        except Exception:  # noqa: BLE001 - transient
            self.errors += 1

    # -- churn waves (cordon -> delete -> re-register) ----------------------

    def _churn_loop(self) -> None:
        prof = self.profile
        period = 1.0 / prof.churn_per_s
        next_wave = self.now()
        while not self._stop.wait(min(0.1, period / 2)):
            now = self.now()
            # Cordoned nodes whose dwell elapsed: delete + replace.
            while self._cordoned and self._cordoned[0][0] <= now:
                _deadline, name = self._cordoned.popleft()
                self._delete_and_replace(name)
            while now >= next_wave:
                next_wave += period
                self._cordon_one()

    def _cordon_one(self) -> None:
        with self._lock:
            now = self.now()
            cordoned = {n for _d, n in self._cordoned}
            # Silent nodes are the lifecycle controller's prey — churn must
            # not delete them out from under the taint ladder (a silent
            # node stays silently dead, it doesn't get gracefully drained).
            candidates = [n for n in self._order
                          if n in self._nodes and n not in cordoned
                          and not self._silent_now(n, now)]
            if not candidates:
                return
            name = candidates[self._rng.randrange(len(candidates))]
            wire = dict(self._nodes[name], unschedulable=True)
            self._nodes[name] = wire
        try:
            self._client.call("PUT", f"/api/v1/nodes/{name}", wire)
            self.cordons += 1
            self._cordoned.append(
                (self.now() + self.profile.churn_cordon_s, name))
        except Exception:  # noqa: BLE001
            self.errors += 1

    def _skew_capacity(self, wire: dict) -> dict:
        """Capacity-skew one churn replacement (profile.imbalance): scale
        the replacement's cpu/memory by a factor in [1-imbalance,
        1+imbalance] keyed off (seed, replacement name) alone — NOT the
        shared drift/churn rng — so the skew any given replacement gets
        is reproducible from the profile regardless of how heartbeat and
        churn threads interleave their rng draws. Caller holds _lock."""
        prof = self.profile
        rnd = random.Random(f"{prof.seed or 0x5ca1e}:{wire['name']}")
        factor = 1.0 + prof.imbalance * (2.0 * rnd.random() - 1.0)
        alloc = dict(wire["allocatable"])
        alloc["cpu"] = max(1000, int(alloc["cpu"] * factor))
        alloc["memory"] = max(1 << 20, int(alloc["memory"] * factor))
        self.skewed += 1
        self._skew_sum += abs(factor - 1.0)
        return dict(wire, allocatable=alloc)

    def _delete_and_replace(self, name: str) -> None:
        try:
            self._client.call("DELETE", f"/api/v1/nodes/{name}")
            self.deletes += 1
        except Exception:  # noqa: BLE001
            self.errors += 1
            return
        with self._lock:
            self._nodes.pop(name, None)
            ix = self._shape_ix.pop(name, 0)
            wire = self.profile.node_wire(ix, name=self._replacement_name(ix))
            if self.profile.imbalance > 0:
                wire = self._skew_capacity(wire)
            self._nodes[wire["name"]] = wire
            self._shape_ix[wire["name"]] = ix
            try:
                pos = self._order.index(name)
                self._order[pos] = wire["name"]
            except ValueError:
                self._order.append(wire["name"])
        try:
            self._client.call("POST", "/api/v1/nodes", wire)
            self.reregisters += 1
        except Exception:  # noqa: BLE001
            self.errors += 1
