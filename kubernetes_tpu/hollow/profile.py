"""Declarative hollow-node profiles.

A profile says WHAT cluster a hollow plane impersonates — how many nodes,
in what heterogeneity mix (weighted shapes: capacity, labels, taints),
how often each node heartbeats, what fraction of heartbeats drift
allocatable capacity, and at what rate churn waves run
(cordon → delete → re-register). The plane (plane.py) owns HOW.

Profiles are plain dicts on disk (JSON) so the perf harness, the CLI, and
tests share one format — docs/SCALE.md documents it:

    {"count": 50000, "zones": 100, "heartbeat_s": 60.0,
     "drift": 0.01, "churn_per_s": 2.0,
     "shapes": [{"weight": 3, "cpu": 32, "memory": "256Gi", "pods": 110},
                {"weight": 1, "cpu": 96, "memory": "1Ti", "pods": 250,
                 "labels": {"pool": "big"},
                 "taints": [{"key": "big", "effect": "NoSchedule"}]}]}
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..api.resource import parse_quantity

ZONE = "topology.kubernetes.io/zone"
HOSTNAME = "kubernetes.io/hostname"


@dataclass
class NodeShape:
    """One entry of the heterogeneity mix. ``weight`` is the relative
    share of the node count this shape gets (shapes interleave
    deterministically by index, so shape assignment is stable across
    plane restarts and identical on every replica of a run)."""

    weight: float = 1.0
    cpu: int = 32              # cores
    memory: str = "256Gi"
    ephemeral: str = "100Gi"
    pods: int = 110
    labels: Dict[str, str] = field(default_factory=dict)
    taints: List[dict] = field(default_factory=list)   # {key,value,effect}
    scalars: Dict[str, float] = field(default_factory=dict)

    @classmethod
    def from_dict(cls, d: dict) -> "NodeShape":
        return cls(weight=float(d.get("weight", 1.0)),
                   cpu=int(d.get("cpu", 32)),
                   memory=str(d.get("memory", "256Gi")),
                   ephemeral=str(d.get("ephemeral", "100Gi")),
                   pods=int(d.get("pods", 110)),
                   labels=dict(d.get("labels", {})),
                   taints=[dict(t) for t in d.get("taints", ())],
                   scalars=dict(d.get("scalars", {})))

    def to_dict(self) -> dict:
        return {"weight": self.weight, "cpu": self.cpu,
                "memory": self.memory, "ephemeral": self.ephemeral,
                "pods": self.pods, "labels": dict(self.labels),
                "taints": [dict(t) for t in self.taints],
                "scalars": dict(self.scalars)}


@dataclass
class HollowProfile:
    count: int = 1000
    # Sub-range seam (the fleet conductor's multi-process split): this
    # plane owns absolute node indices [offset, offset+count) of a parent
    # fleet of `total` nodes. offset/total default to standalone (one
    # plane owns the whole fleet, total == 0 means "not a split member").
    # shape_for / zones / names all key off the ABSOLUTE index, so a
    # split fleet is bit-identical to the same profile run unsplit.
    offset: int = 0
    total: int = 0
    shapes: List[NodeShape] = field(default_factory=lambda: [NodeShape()])
    zones: int = 50
    name_prefix: str = "hollow"
    heartbeat_s: float = 30.0   # full-fleet heartbeat sweep period
    drift: float = 0.0          # fraction of heartbeats that drift capacity
    churn_per_s: float = 0.0    # cordon->delete->re-register waves
    churn_cordon_s: float = 0.5  # dwell between cordon and delete
    # Capacity imbalance (the descheduler's standing prey,
    # docs/DESCHEDULE.md): churn re-registrations land with cpu/memory
    # scaled by a factor in [1-imbalance, 1+imbalance], keyed off
    # (seed, replacement name) alone so the skew any given replacement
    # gets is reproducible from the profile — bound pods stay put while
    # capacity migrates between nodes, so utilization drifts apart until
    # rebalance moves repair it. 0.0 = replacements land at spec shape.
    imbalance: float = 0.0
    threads: int = 4            # register/heartbeat worker threads
    register_chunk: int = 500   # nodes per bulk-create POST
    seed: int = 0               # drift/churn victim selection
    # Failure injection (the node-lifecycle controller's standing prey,
    # docs/RESILIENCE.md § node lifecycle): a `silence` fraction of the
    # fleet stops heartbeating `silence_after_s` seconds into the run
    # (dead kubelets); a `flap` fraction alternates silent/alive every
    # `flap_period_s` (a flapping NIC — the taint must lift when it
    # speaks and re-arm when it dies again); `outage_zone >= 0` blacks
    # out one whole topology zone after `outage_after_s` (the
    # full-disruption case the zone-aware evictor must throttle to zero).
    silence: float = 0.0
    silence_after_s: float = 0.0
    flap: float = 0.0
    flap_period_s: float = 2.0
    outage_zone: int = -1
    outage_after_s: float = 0.0

    @classmethod
    def from_dict(cls, d: dict) -> "HollowProfile":
        shapes = [NodeShape.from_dict(s) for s in d.get("shapes", ())]
        return cls(count=int(d.get("count", 1000)),
                   offset=int(d.get("offset", 0)),
                   total=int(d.get("total", 0)),
                   shapes=shapes or [NodeShape()],
                   zones=int(d.get("zones", 50)),
                   name_prefix=str(d.get("name_prefix", "hollow")),
                   heartbeat_s=float(d.get("heartbeat_s", 30.0)),
                   drift=float(d.get("drift", 0.0)),
                   churn_per_s=float(d.get("churn_per_s", 0.0)),
                   churn_cordon_s=float(d.get("churn_cordon_s", 0.5)),
                   imbalance=float(d.get("imbalance", 0.0)),
                   threads=int(d.get("threads", 4)),
                   register_chunk=int(d.get("register_chunk", 500)),
                   seed=int(d.get("seed", 0)),
                   silence=float(d.get("silence", 0.0)),
                   silence_after_s=float(d.get("silence_after_s", 0.0)),
                   flap=float(d.get("flap", 0.0)),
                   flap_period_s=float(d.get("flap_period_s", 2.0)),
                   outage_zone=int(d.get("outage_zone", -1)),
                   outage_after_s=float(d.get("outage_after_s", 0.0)))

    def to_dict(self) -> dict:
        return {"count": self.count,
                "offset": self.offset, "total": self.total,
                "shapes": [s.to_dict() for s in self.shapes],
                "zones": self.zones, "name_prefix": self.name_prefix,
                "heartbeat_s": self.heartbeat_s, "drift": self.drift,
                "churn_per_s": self.churn_per_s,
                "churn_cordon_s": self.churn_cordon_s,
                "imbalance": self.imbalance,
                "threads": self.threads,
                "register_chunk": self.register_chunk, "seed": self.seed,
                "silence": self.silence,
                "silence_after_s": self.silence_after_s,
                "flap": self.flap, "flap_period_s": self.flap_period_s,
                "outage_zone": self.outage_zone,
                "outage_after_s": self.outage_after_s}

    @classmethod
    def load(cls, path: str) -> "HollowProfile":
        with open(path) as fh:
            return cls.from_dict(json.load(fh))

    def split(self, n: int) -> List["HollowProfile"]:
        """Partition this profile into ``n`` contiguous sub-range members
        for N hollow-plane processes. The sub-ranges are disjoint and
        complete (they tile [offset, offset+count) exactly); every member
        keeps the parent's shapes/zones/prefix/seed and indexes nodes by
        ABSOLUTE position, so shape interleave, zone assignment, and node
        names are identical to the unsplit plane. Churn rates divide by
        fleet share so the aggregate wave rate matches the parent's."""
        n = max(1, int(n))
        base, extra = divmod(self.count, n)
        total = self.total or self.count
        out: List["HollowProfile"] = []
        start = self.offset
        for k in range(n):
            cnt = base + (1 if k < extra else 0)
            if cnt <= 0:
                continue
            share = cnt / max(1, self.count)
            sub = HollowProfile.from_dict(self.to_dict())
            sub.offset = start
            sub.count = cnt
            sub.total = total
            sub.churn_per_s = self.churn_per_s * share
            out.append(sub)
            start += cnt
        return out

    def index_range(self) -> range:
        """The absolute node indices this plane owns."""
        return range(self.offset, self.offset + self.count)

    # Conjugate golden ratio: frac(i*φ') is a low-discrepancy sequence —
    # every shape's share of any index range is within O(1) of its weight
    # quota, so even a weight-1-in-10000 shape gets its ~N/10000 nodes
    # (a fixed modular period would quantize small weights to ZERO).
    _GOLDEN = 0.6180339887498949

    def shape_for(self, i: int) -> NodeShape:
        """Deterministic weighted interleave: node i's shape depends only
        on the profile, never on registration order or timing."""
        total = sum(max(0.0, s.weight) for s in self.shapes) or 1.0
        x = (i * self._GOLDEN) % 1.0
        acc = 0.0
        for s in self.shapes:
            acc += max(0.0, s.weight) / total
            if x < acc:
                return s
        return self.shapes[-1]

    def node_wire(self, i: int, name: Optional[str] = None) -> dict:
        """The wire dict (core/apiserver.py node codec) for node i —
        built directly so registering 50k nodes never allocates 50k
        intermediate Node objects."""
        shape = self.shape_for(i)
        name = name or f"{self.name_prefix}-{i}"
        labels = dict(shape.labels)
        labels[HOSTNAME] = name
        if self.zones:
            labels[ZONE] = f"zone-{i % self.zones}"
        return {
            "name": name, "uid": name, "labels": labels,
            "unschedulable": False,
            "allocatable": {
                "cpu": int(shape.cpu) * 1000,
                "memory": int(parse_quantity(shape.memory)),
                "ephemeral": int(parse_quantity(shape.ephemeral)),
                "pods": int(shape.pods),
                "scalar": dict(shape.scalars)},
            "taints": [
                {"key": t.get("key", ""), "value": t.get("value", ""),
                 "effect": t.get("effect", "NoSchedule")}
                for t in shape.taints],
            "declaredFeatures": {},
        }
