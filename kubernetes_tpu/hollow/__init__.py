"""Hollow-node scale plane (the reference's kubemark layer,
`cmd/kubemark/hollow-node.go` / `pkg/kubemark/hollow_kubelet.go`): one
process impersonates N nodes' full kubelet lifecycle — register,
heartbeat with capacity drift, cordon/delete/re-register churn waves —
against a REAL apiserver over HTTP, so the control plane can be driven at
50k–100k nodes from a box that could never run that many kubelets.

- :mod:`profile` — the declarative profile (count, heterogeneity mix,
  heartbeat cadence, drift, churn rate) a plane runs;
- :mod:`plane` — the synthetic-kubelet thread pool itself;
- ``python -m kubernetes_tpu.hollow`` — the standalone process the
  shard/perf harness spawns alongside real scheduler shards.

docs/SCALE.md holds the profile format and the 50k-node runbook.
"""

from .plane import HollowNodePlane
from .profile import HollowProfile, NodeShape

__all__ = ["HollowNodePlane", "HollowProfile", "NodeShape"]
