"""Standalone hollow-node plane process:

    python -m kubernetes_tpu.hollow --api-url http://127.0.0.1:PORT \
        [--profile profile.json] [--count N] [--heartbeat S] \
        [--drift F] [--churn R] [--zones Z] [--prefix P] \
        [--silence F] [--silence-after S] [--flap F] \
        [--outage-zone Z] [--outage-after S]

Registers the fleet, prints the ready line the spawn harness keys on
(``hollow-node plane: registered N nodes``), then heartbeats/churns until
SIGTERM/SIGINT — finally printing one JSON stats line so harnesses can
fold the plane's activity into their detail objects. CLI flags override
the profile file's fields.
"""

from __future__ import annotations

import argparse
import json
import signal
import sys
import threading

from .plane import HollowNodePlane
from .profile import HollowProfile


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="kubernetes-tpu-hollow")
    ap.add_argument("--api-url", required=True,
                    help="apiserver base URL (the LEADER: the plane writes)")
    ap.add_argument("--profile", default="",
                    help="JSON profile file (docs/SCALE.md format)")
    ap.add_argument("--count", type=int, default=0)
    ap.add_argument("--heartbeat", type=float, default=0.0,
                    help="full-fleet heartbeat sweep period in seconds")
    ap.add_argument("--drift", type=float, default=-1.0,
                    help="fraction of heartbeats that drift capacity")
    ap.add_argument("--churn", type=float, default=-1.0,
                    help="cordon/delete/re-register waves per second")
    ap.add_argument("--zones", type=int, default=-1)
    ap.add_argument("--prefix", default="")
    ap.add_argument("--name-prefix-range", default="",
                    help="START:END — own absolute node indices "
                         "[START, END) of a split fleet (the conductor's "
                         "multi-process seam; sets offset/count)")
    ap.add_argument("--total", type=int, default=0,
                    help="parent fleet size when this plane is one split "
                         "member (defaults to END of --name-prefix-range)")
    ap.add_argument("--adopt", action="store_true",
                    help="supervised restart: paged-LIST survivors of this "
                         "plane's range, adopt them, create only missing "
                         "slots (zero duplicate nodes)")
    ap.add_argument("--silence", type=float, default=-1.0,
                    help="fraction of the fleet that goes permanently "
                         "silent (dead kubelets)")
    ap.add_argument("--silence-after", type=float, default=-1.0,
                    help="seconds into the run silence begins")
    ap.add_argument("--flap", type=float, default=-1.0,
                    help="fraction of the fleet that flaps silent/alive")
    ap.add_argument("--outage-zone", type=int, default=-2,
                    help="zone index to black out entirely (-1 disables)")
    ap.add_argument("--outage-after", type=float, default=-1.0,
                    help="seconds into the run the zone outage begins")
    args = ap.parse_args(argv)

    profile = (HollowProfile.load(args.profile) if args.profile
               else HollowProfile())
    if args.count:
        profile.count = args.count
    if args.heartbeat:
        profile.heartbeat_s = args.heartbeat
    if args.drift >= 0:
        profile.drift = args.drift
    if args.churn >= 0:
        profile.churn_per_s = args.churn
    if args.zones >= 0:
        profile.zones = args.zones
    if args.prefix:
        profile.name_prefix = args.prefix
    if args.silence >= 0:
        profile.silence = args.silence
    if args.silence_after >= 0:
        profile.silence_after_s = args.silence_after
    if args.flap >= 0:
        profile.flap = args.flap
    if args.outage_zone >= -1:
        profile.outage_zone = args.outage_zone
    if args.outage_after >= 0:
        profile.outage_after_s = args.outage_after
    if args.name_prefix_range:
        start, _, end = args.name_prefix_range.partition(":")
        start, end = int(start), int(end)
        if end <= start:
            ap.error("--name-prefix-range END must be > START")
        profile.offset, profile.count = start, end - start
        profile.total = args.total or end
    elif args.total:
        profile.total = args.total

    plane = HollowNodePlane(args.api_url, profile)
    n = plane.register(adopt=args.adopt)
    plane.start()
    # The ready line FIRST (spawn harnesses select()+readline on it).
    print(f"hollow-node plane: registered {n} nodes against "
          f"{args.api_url}", flush=True)
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    # Flight-record fan-out seam (fleet conductor SIGUSR2): dump the live
    # stats line without dying — the drained tail picks it up.
    signal.signal(signal.SIGUSR2, lambda *_: print(
        json.dumps({"hollow_stats": plane.stats()}), flush=True))
    stop.wait()
    plane.stop()
    print(json.dumps({"hollow_stats": plane.stats()}), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
