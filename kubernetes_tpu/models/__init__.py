"""End-to-end scheduling pipelines ("models" of the framework).

The flagship is TPUScheduler (tpu_scheduler.py): the host scheduling core with
the Filter→Score hot path dispatched to the device batch kernel.
"""

from .tpu_scheduler import TPUScheduler

__all__ = ["TPUScheduler"]
