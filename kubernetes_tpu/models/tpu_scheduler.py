"""TPUScheduler — the device-backed scheduling pipeline (the framework's
flagship "model").

Control flow (the TPU-era schedule_one, per SURVEY.md §3.2/§7.4):

    pop → accumulate a row-block of consecutive same-signature pods
        → Cache.update_snapshot (host, incremental)
        → NodeStateMirror.sync/flush (device, dirty-row scatter)
        → build_batch (ONE amortized O(pods) PreFilter aggregation)
        → ops.kernel.schedule_batch (jit: the whole greedy sequential
          assignment for the block runs on device — filters, sampling
          emulation, scoring, selection, carry updates)
        → per pod: assume → reserve → permit → binding cycle (host,
          unchanged semantics; schedule_one.go:315,:211,:141)

Pods whose spec exceeds the kernel's coverage (ops/features.py
batch_supported) take the unchanged host path — the reference-shaped
sequential cycle in core/scheduler.py — preserving exact semantics for every
feature while the dense common case rides the device.

Pod signatures come from the profile's Sign plugins
(framework.sign_pod; staging kube-scheduler framework/signers.go), the same
mechanism the reference's OpportunisticBatching uses (runtime/batch.go:33) —
generalized from one-pod hint reuse to true multi-pod kernel batches.
"""

from __future__ import annotations

import logging
import time as _time
from typing import List, Optional, Tuple

import numpy as np

_log = logging.getLogger(__name__)

from ..core.framework import OK as _OK_STATUS
from ..core.framework import WAIT, Framework
from ..core.queue import (QueuedCompositeGroupInfo, QueuedPodGroupInfo,
                          QueuedPodInfo)
from ..core.scheduler import Scheduler, ScheduleResult
from ..ops.device_state import NodeStateMirror, enable_persistent_compilation_cache
from ..ops.features import Unsupported, batch_supported, build_batch
from ..ops.kernel import schedule_batch


# Sentinel fallback_reason: the popped entity is a pod GROUP that can ride a
# device gang session (schedule_one routes it to run_gang_device_session).
_GANG_SESSION = "__gang_device_session__"


class _SessionDelta:
    """A live session's journal-patchable view: the device state + carry the
    delta patches rewrite, the seq watermark already consumed, and whether a
    shrink patch is parked waiting for the pipeline to drain. One protocol
    (TPUScheduler._note_session_events) mutates it for both session kinds."""

    __slots__ = ("state", "carry", "start_seq", "patch_pending",
                 "busy_patch_rows")

    def __init__(self, state, carry, start_seq):
        self.state = state
        self.carry = carry
        self.start_seq = start_seq
        self.patch_pending = False
        # Rows patched while the pipeline was BUSY (shard-plane foreign-bind
        # feed): an in-flight batch may have placed onto one of them after
        # dispatch, and that placement's aggregate is not in mirror staging
        # yet — so the patch can understate the row until the batch retires.
        # The session end charges these rows dirty, and adopt() re-encodes
        # them from post-commit staging truth; in between, the binding
        # subresource's capacity re-validation bounds the damage to a 409.
        self.busy_patch_rows: list = []


def _pow2_pad(n: int) -> int:
    """Placement-axis pow2 tier (shared by warm + live paths so the warm
    compile always matches the live kernel shape)."""
    from ..ops.features import _pow2
    return _pow2(max(1, n))


class TPUScheduler(Scheduler):
    """Scheduler with the hot path on device. Falls back per-pod to the host
    path for uncovered features; host and device paths produce identical
    assignments (deterministic_ties is forced on)."""

    def __init__(self, *args, max_batch: Optional[int] = None, mesh="auto",
                 **kwargs):
        kwargs.setdefault("deterministic_ties", True)
        super().__init__(*args, **kwargs)
        self._mesh_arg = mesh
        from ..core.features import TPU_BATCH_SCHEDULING
        self.device_enabled = self.gates.enabled(TPU_BATCH_SCHEDULING)
        self.max_batch = max_batch if max_batch is not None else self.config.max_batch
        # Dispatch pipeline depth: how many batches may be in flight on
        # device while the host commits retired ones (2 = double buffering).
        self.pipeline_depth = getattr(self.config, "pipeline_depth", 2)
        enable_persistent_compilation_cache()
        # Multi-chip: with >1 device the node axis shards over a
        # ("cells", "nodes") mesh and the SAME jitted kernel compiles SPMD
        # (GSPMD from committed input shardings; reductions ride ICI
        # collectives — parallelize/parallelism.go:28's scale axis, done the
        # scaling-book way). Single chip runs unsharded, zero overhead.
        self.mesh = None
        if mesh == "auto":
            try:
                import jax
                if len(jax.devices()) > 1:
                    from ..parallel import make_mesh
                    self.mesh = make_mesh(n_cells=1)
            except Exception:  # noqa: BLE001 - probing must never kill init
                self.mesh = None
        else:
            self.mesh = mesh  # explicit Mesh, or None to force single-device
        self.mirror = NodeStateMirror()
        self._holdover: Optional[QueuedPodInfo] = None
        # Explicit shard_map dispatch for row-local plans under a mesh
        # (parallel/mesh.py sharded_lap_schedule): cross-shard collectives
        # are hand-placed and minimal instead of GSPMD-inferred.
        # TPU_SCHED_SHARD_MAP=0 pins the GSPMD path (the A/B seam).
        import os as _os2
        self._shard_map_enabled = (
            _os2.environ.get("TPU_SCHED_SHARD_MAP", "1") != "0")
        # metrics
        self.device_batches = 0
        self.device_scheduled = 0
        self.shard_map_dispatches = 0
        self.host_path_pods = 0
        # Plan acquisition attribution (scheduler_plan_rebuild_total):
        # full = snapshot→features rebuild, resume = untouched cache hit,
        # delta = journal-driven row patch of a live plan+carry.
        self.plan_rebuilds_full = 0
        self.plan_rebuilds_delta = 0
        self.plan_rebuilds_resume = 0
        self.delta_dirty_rows = 0
        # Stacked placement evaluations that ran on device (one per group
        # cycle whose candidate set was kernel-evaluated).
        self.placement_device_evals = 0
        # DryRunPreemption kernel calls (one per device-evaluated PostFilter).
        self.preemption_device_evals = 0
        # Host/device time split (schedule_one.go:574-style step accounting,
        # re-shaped for the batch pipeline): plan_build_s = snapshot→features
        # host work, device_wait_s = time blocked on a device result fetch,
        # host_commit_s = assume/reserve/permit/bind tails. Exported by the
        # perf harness so perf regressions are attributable, not guessed.
        self.plan_build_s = 0.0
        self.device_wait_s = 0.0
        self.host_commit_s = 0.0
        # Terminal-failure memos: state key -> (unschedulable plugins,
        # message) for side-effect-free host diagnoses (see _fail_from_memo).
        # A small keyed LRU, not a single slot: two ALTERNATING unschedulable
        # signatures must each stay memoized or every miss tears down the
        # live device session (VERDICT r3 weakness 6).
        self._fail_memo: "dict" = {}
        self._fail_memo_cap = 64
        # Session-resume cache: (fw id, sig, cluster_event_seq, attempts) →
        # (state, plan, carry) captured at the end of a clean device session.
        # When the next session starts with an identical signature and NO
        # intervening activity (no host attempts, no cluster events), the
        # snapshot/mirror/feature rebuild is skipped entirely and the carry
        # chains on — the cross-session generalization of the in-session
        # chained carry (plan_build was ~1s of the r03 measured window).
        self._resume = None
        # Live session's namespace-erased signature (None = exact-sig only)
        # and the node-name→row map behind journal delta patches.
        self._session_neutral_sig = None
        self._session_row_of = None
        # Per-framework commit fast-path eligibility (see _commit).
        self._fast_tail: dict = {}
        # Drivers with ANY CSINode attach limit (volume aux eligibility);
        # recomputed when the CSINode set grows.
        self._limited_drivers = frozenset()
        self._limited_drivers_n = -1
        # Claims referenced by pods already accepted into the CURRENT device
        # session (committed or in flight): a second pod sharing one of them
        # must not join — the kernel counts attach units per landing, the
        # host per distinct claim (see ops/features.py volume_device_support).
        self._session_claims: set = set()
        # Device-path circuit breaker (core/backoff.py; docs/RESILIENCE.md):
        # any unexpected exception from the device path is caught ONCE, the
        # work reruns on the host Evaluator, and after N consecutive
        # failures the breaker pins the host path for a cool-down. The host
        # path produces identical assignments (the repo's core equivalence
        # invariant), so degradation is graceful, never a crashed cycle.
        from ..core.backoff import CircuitBreaker
        self.device_breaker = CircuitBreaker(
            failure_threshold=getattr(
                self.config, "device_breaker_threshold", 3),
            cooldown=getattr(self.config, "device_breaker_cooldown", 5.0))
        # Chaos seam (testing/faults.py DeviceFaults): called at every
        # device kernel boundary crossing; may raise.
        self._fault_hook = None
        # Signature-keyed score-hint fast path (models/score_hints.py;
        # KEP-5598 OpportunisticBatch, cross-cycle): a clean session's end
        # carry seeds a host-side walk that binds the NEXT identical pods
        # without any device dispatch. Event-driven freshness rides the
        # journal; TPU_SCHED_SCORE_HINTS=0 forces the dispatch-only
        # baseline (the bench A/B seam).
        import os as _os
        from .score_hints import ScoreHintCache
        self._hints = ScoreHintCache(
            self,
            enabled=(self.device_enabled
                     and _os.environ.get("TPU_SCHED_SCORE_HINTS", "1") != "0"
                     and getattr(self.config, "score_hints", True)))
        self.hint_hits = 0
        self.hint_misses = 0
        self.hint_invalidations = 0

    # -- batch accumulation ------------------------------------------------

    def _pop(self) -> Optional[QueuedPodInfo]:
        while True:
            if self._holdover is not None:
                qpi, self._holdover = self._holdover, None
            else:
                qpi = self.queue.pop()
            if qpi is None:
                return None
            if not isinstance(qpi, (QueuedPodGroupInfo,
                                    QueuedCompositeGroupInfo)):
                if (qpi.pod.deletion_ts is not None
                        or qpi.pod.uid in self.cache.pod_states):
                    # skipPodSchedule: deleting pods never dispatch to
                    # device, and neither do pods the cache already placed
                    # (a reconcile unwind raced the bind confirm — see core
                    # process_one). (Group/composite entities are never
                    # skipped whole — their .pod is just the first member.)
                    self.queue.done(qpi.pod.uid)
                    continue
                if self.tracer.enabled:
                    # queue.wait ends here for device-path pods (host-path
                    # pods record in process_one; the qpi guard dedups).
                    self.record_queue_wait(
                        qpi, self.tracer.context_for(qpi.pod.uid))
            return qpi

    def _collect_batch(self) -> Tuple[Optional[Framework], List[QueuedPodInfo], Optional[str]]:
        """Pop a maximal run of consecutive identical-signature pods.
        Returns (framework, batch, fallback_reason); fallback_reason set when
        the batch head must take the host path (batch will be length 1)."""
        head = self._pop()
        if head is None:
            return None, [], None
        if isinstance(head, QueuedCompositeGroupInfo):
            # Composite trees take the host composite cycle (all-or-nothing
            # across levels; core/scheduler.py schedule_composite_group).
            return self.framework_for_pod(head.pod), [head], "composite group entity"
        if isinstance(head, QueuedPodGroupInfo):
            fw, sig = self._gang_device_eligible(head)
            if fw is not None:
                return fw, [head], _GANG_SESSION
            return self.framework_for_pod(head.pod), [head], "pod group entity"
        fw = self.framework_for_pod(head.pod)
        reason = self._batch_supported_memo(head.pod, fw)
        if reason is None:
            reason = self._nominated_device_block(fw, head.pod)
        if reason is None and self.extenders:
            interested = [e for e in self.extenders if e.is_interested(head.pod)]
            if interested:
                reason = "extender-managed pod"
        sig = fw.sign_pod(head.pod) if reason is None else None
        if sig is None:
            return fw, [head], reason or "unsignable pod"
        # The nominated lane's priority threshold is the head's priority
        # (two-pass counts only >=-priority nominations,
        # framework.go:1280-1284): a different-priority member would need a
        # different lane, so it ends the session instead of joining it.
        self._session_nom_priority = (
            head.pod.priority
            if self.queue.nominator.has_nominated_pods() else None)
        self._session_claims = set(self._claims_of(head.pod))
        self._session_claims.update(
            f"dra:{head.pod.namespace}/{n}"
            for n in getattr(head.pod, "resource_claims", ()) or ())
        self._session_aux_shape = self._aux_shape(head.pod)
        self._session_neutral_sig = self._neutral_sig(fw, head.pod, sig)
        batch = [head]
        while len(batch) < self.max_batch:
            nxt = self._pop()
            if nxt is None:
                break
            if self._session_compatible(nxt, fw, sig):
                batch.append(nxt)
            else:
                self._holdover = nxt
                break
        return fw, batch, None

    # -- gang device sessions ----------------------------------------------
    #
    # A pod group scheduled by the DEFAULT algorithm (no topology constraint)
    # is member-wise greedy placement with all-or-nothing commit
    # (schedule_one_podgroup.go:556) — exactly the kernel's scan with a
    # group-granular commit barrier. Groups of identical members ride device
    # sessions like plain pods: whole groups pack into each dispatch, the
    # carry chains across packs, and the host commits a retired pack's
    # groups atomically (any member infeasible ⇒ that group reverts to the
    # exact host cycle for diagnosis/PostFilter and the session invalidates).

    def _gang_device_eligible(self, qgpi: QueuedPodGroupInfo,
                              session_claims=None, session_aux_shape=None):
        """Returns (fw, sig) when the whole group can ride a device session:
        default algorithm, identical batch-supported members, one signature.
        PVC-carrying members are eligible when every member shares ONE
        counted-constraint shape (the plan's aux math models one driver/inc)
        and the members' claims are pairwise distinct and unseen by the
        session (the kernel counts attach units per LANDING; a shared claim
        would double-count what the host counts once per distinct claim).
        DRA resource claims stay on the host group cycle: their commit needs
        a per-member device allocation that can fail mid-group."""
        if not qgpi.members or len(qgpi.members) > self.max_batch:
            return None, None
        if not self.device_enabled or self.queue.nominator.has_nominated_pods():
            return None, None
        p0 = qgpi.members[0].pod
        if p0.scheduler_name not in self.profiles:
            return None, None
        fw = self.framework_for_pod(p0)
        if fw.placement_generate_plugins and getattr(
                qgpi.group, "topology_keys", ()):
            return None, None  # placement algorithm (separate path)
        if self.extenders and any(
                e.is_interested(m.pod) for e in self.extenders
                for m in qgpi.members):
            return None, None
        sig = fw.sign_pod(p0)
        if sig is None:
            return None, None
        aux_shape = self._aux_shape(p0)
        if session_aux_shape is not None and aux_shape != session_aux_shape:
            return None, None  # the live session's plan models one aux shape
        group_claims: set = set()
        for m in qgpi.members:
            if (m.pod.scheduler_name != p0.scheduler_name
                    or fw.sign_pod(m.pod) != sig
                    or self._batch_supported_memo(m.pod, fw) is not None
                    or self._device_unsupported_profile(fw, m.pod) is not None
                    or getattr(m.pod, "resource_claims", None)):
                return None, None
            if self._aux_shape(m.pod) != aux_shape:
                return None, None
            for c in self._claims_of(m.pod):
                if c in group_claims or (session_claims is not None
                                         and c in session_claims):
                    return None, None  # shared claim: host counts it once
                group_claims.add(c)
        return fw, sig

    def _sorted_members(self, qgpi: QueuedPodGroupInfo) -> List[QueuedPodInfo]:
        """Host group-cycle member order (schedule_pod_group)."""
        return sorted(qgpi.members, key=lambda m: (-m.pod.priority, m.timestamp))

    def run_gang_device_session(self, fw: Framework, first: QueuedPodGroupInfo) -> None:
        """Crash-proof wrapper — see run_device_session: stranded packs
        rerun on the host group cycle on an unexpected device failure."""
        pending: List[List[QueuedPodGroupInfo]] = []
        try:
            self._run_gang_device_session(fw, first, pending)
        except Unsupported:
            raise
        except Exception as e:  # noqa: BLE001 - device→host fallback
            self._note_device_failure(e, "gang_device_session")
            for pk in pending:
                for g in pk:
                    self._recover_qpi(g)

    def _run_gang_device_session(self, fw: Framework,
                                 first: QueuedPodGroupInfo,
                                 pending: List[List[QueuedPodGroupInfo]]) -> None:
        pack: Optional[List[QueuedPodGroupInfo]] = [first]
        pending.append(pack)  # crash-recovery registry (wrapper);
        # registered BEFORE build_plan so a plan-build crash recovers too.
        sig = fw.sign_pod(first.members[0].pod)
        aux_shape = self._aux_shape(first.members[0].pod)
        # Claims already accepted into this session (all members' PVCs):
        # collect_pack rejects groups re-using any of them — the kernel's
        # per-landing attach count assumes distinct claims, like the host's
        # distinct-claim NodeVolumeLimits count.
        self._session_claims = {
            c for m in first.members for c in self._claims_of(m.pod)}
        claims_rv = getattr(self.clientset, "resource_claims_rv", 0)
        # Gang resumes stay exact-signature (nsig=None): the neutral erasure
        # targets plain-pod namespace sweeps, not group entities.
        state, plan, carry, node_names, _rkind = self._resume_or_rebuild(
            fw, first.members[0].pod, sig, None, aux_shape, claims_rv)
        sd = _SessionDelta(state, carry, self.cluster_event_seq)
        del state, carry
        start_unwinds = self.state_unwinds
        inflight: List[Tuple[List[QueuedPodGroupInfo], object]] = []
        ok_rows: List[int] = []
        dirty_rows: List[int] = []
        invalidated = False

        def collect_pack() -> List[QueuedPodGroupInfo]:
            groups: List[QueuedPodGroupInfo] = []
            total = 0
            while True:
                nxt = self._pop()
                if nxt is None:
                    break
                if isinstance(nxt, QueuedPodGroupInfo):
                    gfw, gsig = self._gang_device_eligible(
                        nxt, session_claims=self._session_claims,
                        session_aux_shape=aux_shape)
                    if (gfw is fw and gsig == sig
                            and total + len(nxt.members) <= self.max_batch):
                        groups.append(nxt)
                        total += len(nxt.members)
                        self._session_claims.update(
                            c for m in nxt.members
                            for c in self._claims_of(m.pod))
                        continue
                self._holdover = nxt
                break
            return groups

        while True:
            while not invalidated and len(inflight) < self.pipeline_depth:
                if sd.patch_pending:
                    if inflight:
                        break  # retire dispatched packs before patching
                    if not self._note_session_events(sd, plan, node_names,
                                                     busy=False):
                        invalidated = True
                        break
                if pack is None:
                    pack = collect_pack() or None
                    if pack is None:
                        break
                    pending.append(pack)
                members = [m for g in pack for m in self._sorted_members(g)]
                results, sd.carry = self._dispatch(
                    sd.state, plan, len(members), sd.carry)
                try:
                    results.copy_to_host_async()
                except AttributeError:
                    pass
                self.device_batches += 1
                self.metrics.batch_attempts.inc("dispatched")
                self.metrics.batch_size.observe(len(members))
                inflight.append((pack, results))
                self.metrics.goroutines.set(float(len(inflight)),
                                            "device_dispatch")
                pack = None
            if not inflight:
                break
            groups, results = inflight.pop(0)
            self.metrics.goroutines.set(float(len(inflight)),
                                        "device_dispatch")
            _t0 = _time.perf_counter()
            res = np.asarray(results)
            _t1 = _time.perf_counter()
            self.device_wait_s += _t1 - _t0
            if (invalidated or self.state_unwinds != start_unwinds
                    or not self._note_session_events(sd, plan, node_names,
                                                     busy=True)):
                invalidated = True
                for g in groups:
                    for m in self._sorted_members(g):
                        self.host_path_pods += 1
                    self.process_one(g)
                if groups in pending:
                    pending.remove(groups)
                continue
            i = 0
            for g in groups:
                ms = self._sorted_members(g)
                rows = res[0, i:i + len(ms)]
                self.next_start_node_index = int(res[1, i + len(ms) - 1])
                i += len(ms)
                if invalidated or (rows < 0).any():
                    # Some member infeasible (or a prior group diverged):
                    # every row this group DID take is charged dirty (the
                    # carry placed them), and the exact host group cycle
                    # owns the entity (diagnosis, PodGroupPostFilter).
                    for r in rows:
                        if r >= 0:
                            dirty_rows.append(int(r))
                    for _ in ms:
                        self.host_path_pods += 1
                    self.process_one(g)
                    invalidated = True
                    continue
                if not self._commit_gang_group(fw, g, ms, rows, node_names,
                                               ok_rows, dirty_rows):
                    invalidated = True  # a member's host commit rejected a
                    # placement the carry already applied
                if (self.state_unwinds != start_unwinds
                        or not self._note_session_events(sd, plan, node_names,
                                                         busy=True)):
                    invalidated = True
                    sd.start_seq = self.cluster_event_seq
                    start_unwinds = self.state_unwinds
            self.host_commit_s += _time.perf_counter() - _t1
            if getattr(self, "_after_flush", False):
                # First retired pack after a flush (pod_scheduled_after_flush
                # consumption for gang sessions).
                self.metrics.pod_scheduled_after_flush.inc(value=len(ok_rows))
                self._after_flush = False
            if groups in pending:
                pending.remove(groups)  # fully handled: out of crash recovery

        if pack:
            for g in pack:
                for _ in g.members:
                    self.host_path_pods += 1
                self.process_one(g)
            if pack in pending:
                pending.remove(pack)

        self.cache.update_snapshot(self.snapshot)
        dirty_rows.extend(sd.busy_patch_rows)  # re-encode busy-patched rows
        if invalidated:
            self.mirror.invalidate()
            self.metrics.batch_cache_flushed.inc("gang_session_invalidated")
            self._after_flush = True
        else:
            self.mirror.adopt(self.snapshot.node_info_list, ok_rows,
                              sd.carry.req_r, sd.carry.nonzero,
                              sd.carry.pod_count, dirty_rows=dirty_rows)
            if sd.carry is not None and not dirty_rows:
                self._save_resume(fw, first.members[0].pod, sig, aux_shape,
                                  sd.state, plan, sd.carry, node_names,
                                  neutral_ok=False)
        self._note_device_success()

    def _commit_gang_group(self, fw: Framework, qgpi: QueuedPodGroupInfo,
                           members: List[QueuedPodInfo], rows, node_names,
                           ok_rows: List[int], dirty_rows: List[int]) -> bool:
        """All members feasible on device: run the group commit exactly as
        schedule_pod_group's tail (assume into cache, reserve → permit →
        binding cycle per member, group bookkeeping). Returns False when any
        member's host commit rejected its placement — the device carry has
        that placement applied, so the caller must invalidate."""
        from ..core.framework import CycleState

        self.attempts += 1
        committed = 0
        attempted_uids = set()
        for m, r in zip(members, rows):
            attempted_uids.add(m.pod.uid)
            node = node_names[int(r)]
            m.pod.node_name = node
            self.cache.assume_pod(m.pod, m.pod_info)
            if self._commit_group_member(fw, m, CycleState(),
                                         ScheduleResult(suggested_host=node)):
                committed += 1
                ok_rows.append(int(r))
                self.device_scheduled += 1
            else:
                dirty_rows.append(int(r))
        _t_store = _time.perf_counter()
        group_key = (qgpi.group.namespace, qgpi.group.name)
        self.queue.clear_group_members(group_key, attempted_uids)
        self.queue.done(qgpi.uid)
        self.metrics.store_schedule_results_duration.observe(
            _time.perf_counter() - _t_store)
        self.metrics.podgroup_schedule_attempts.inc(
            "scheduled" if committed else "unschedulable")
        return committed == len(members)

    # -- placement-gang device evaluation ----------------------------------

    @staticmethod
    def _placement_plan_restriction_invariant(plan) -> bool:
        """True when the plan can be evaluated per-placement on device.
        Topology-SPREAD tables are no longer a blocker: the host oracle
        computes them over the restricted list (cache.py assume_placement),
        and _placement_spread_overrides rebuilds each placement's restricted
        tables from the plan's per-node columns. Still host-only:
        inter-pod-affinity tables (term matches against restricted pod sets)
        and image-locality (its spread discount divides by the restricted
        node count). Static row-local terms (fit, balance, taints,
        node-affinity preference) restrict exactly."""
        f = plan.features
        return (f.anti_axis.shape[0] == 0 and f.aff_axis.shape[0] == 0
                and f.ipa_axis.shape[0] == 0 and not plan.has_ipa_base
                and not bool(np.asarray(f.il_score).any()))

    def _placement_spread_overrides(self, plan, placements, index):
        """Per-placement restricted spread tables (the device analogue of
        running calPreFilterState / initPreScoreState over
        assume_placement's node list): scatter-add the plan's per-node
        match-count columns over each placement's rows. Returns the
        spread_overrides tuple for ops/kernel.py schedule_placements, or
        None when the plan carries no spread features."""
        import jax.numpy as jnp
        f = plan.features
        c1p, c2p = f.dns_axis.shape[0], f.sa_axis.shape[0]
        if c1p == 0 and c2p == 0:
            return None
        import math
        vmax = plan.vmax
        p_pad = _pow2_pad(len(placements))
        n = len(self.snapshot.node_info_list)
        dns_axis = np.asarray(f.dns_axis)
        sa_axis = np.asarray(f.sa_axis)
        dns_counts = np.zeros((p_pad, c1p, vmax), np.int32)
        dns_dom = np.zeros((p_pad, c1p, vmax), bool)
        dns_forced0 = np.ones((p_pad, c1p), np.int32)  # pad rows: min 0
        sa_counts = np.zeros((p_pad, c2p, vmax), np.int32)
        sa_wq = np.zeros((p_pad, c2p), np.int64)
        nc1 = 0 if plan.dns_node_counts is None else plan.dns_node_counts.shape[0]
        nc2 = 0 if plan.sa_node_counts is None else plan.sa_node_counts.shape[0]
        for pi, placement in enumerate(placements):
            rows = np.array([r for name in placement.node_names
                             if (r := index.get(name)) is not None and r < n],
                            np.int64)
            for ci in range(nc1):
                vids = self.mirror.h_topo[dns_axis[ci], rows]
                elig = plan.dns_node_elig[ci, rows]
                ev = vids[elig]
                np.add.at(dns_counts[pi, ci], ev,
                          plan.dns_node_counts[ci, rows][elig])
                dns_dom[pi, ci, ev] = True
                nd = np.unique(ev).size
                md = plan.dns_min_domains[ci]
                dns_forced0[pi, ci] = 1 if (nd == 0 or (
                    md is not None and nd < md)) else 0
            for ci in range(nc2):
                vids = self.mirror.h_topo[sa_axis[ci], rows]
                live = plan.sa_node_live[rows]
                lv = vids[live]
                np.add.at(sa_counts[pi, ci], lv,
                          plan.sa_node_counts[ci, rows][live])
                size = (int(live.sum()) if plan.sa_hostname_axis[ci]
                        else np.unique(lv).size)
                sa_wq[pi, ci] = int(round(math.log(size + 2) * 1024))
        return (jnp.asarray(dns_counts), jnp.asarray(dns_dom),
                jnp.asarray(dns_forced0), jnp.asarray(sa_counts),
                jnp.asarray(sa_wq))

    def _evaluate_placements(self, fw: Framework, pg_state, group, members,
                             placements, start_index: int):
        """Stacked device evaluation of ALL candidate placements in one
        kernel call (ops/kernel.py schedule_placements) — the TPU form of
        the per-placement simulation loop. Falls back to the host loop when
        any member or the plan is outside the device ring."""
        from ..core.framework import (CycleState, PlacementProgress,
                                      PodGroupAssignments)

        if not self.device_enabled or self.queue.nominator.has_nominated_pods():
            return super()._evaluate_placements(
                fw, pg_state, group, members, placements, start_index)
        p0 = members[0].pod
        sig = fw.sign_pod(p0)
        if sig is None or any(
                fw.sign_pod(m.pod) != sig
                or self._batch_supported_memo(m.pod, fw) is not None
                or self._device_unsupported_profile(fw, m.pod) is not None
                # claim-carrying members: host sims (no intra-sim claim dedup)
                or any(v.pvc_name for v in m.pod.volumes)
                for m in members):
            return super()._evaluate_placements(
                fw, pg_state, group, members, placements, start_index)
        # Plan cache across group cycles: restriction-invariant, port-free
        # plans depend only on NODE state + the pod spec — our own commits
        # between cycles only move per-node aggregates, which flow through
        # the mirror's dirty-row scatter, NOT the feature tables. A stream
        # of identical gangs (the perf shape) then builds features once.
        cache = getattr(self, "_placement_plan_cache", None)
        ckey = (id(fw), sig, len(members), self.cluster_event_seq,
                self.mirror.np_cap)
        if cache is not None and cache[0] == ckey:
            plan = cache[1]
            self.cache.update_snapshot(self.snapshot)
            self.mirror.sync(self.snapshot.node_info_list)
            state = self.mirror.flush()  # resident stays mesh-committed
        else:
            try:
                state, plan = self.build_plan(fw, p0, len(members))
            except Unsupported:
                return super()._evaluate_placements(
                    fw, pg_state, group, members, placements, start_index)
            if not self._placement_plan_restriction_invariant(plan):
                return super()._evaluate_placements(
                    fw, pg_state, group, members, placements, start_index)
            # Spread-carrying plans are NOT cached across group cycles: the
            # per-node match-count columns change with every commit of a
            # matching pod, unlike the node-state aggregates that flow
            # through the mirror's dirty rows.
            self._placement_plan_cache = (
                (id(fw), sig, len(members), self.cluster_event_seq,
                 self.mirror.np_cap),
                plan) if not (plan.port_selfblock or plan.has_aux
                              or plan.dns_node_counts is not None
                              or plan.sa_node_counts is not None) else None

        import jax.numpy as jnp
        from ..ops.kernel import schedule_placements
        index = self.snapshot._index
        if len(index) != len(self.snapshot.node_info_list):
            index = {ni.name: i
                     for i, ni in enumerate(self.snapshot.node_info_list)}
        npc = self.mirror.np_cap
        # Pad the placement axis to a pow2 tier so XLA compiles once per
        # (placement tier, batch tier), not once per candidate count.
        p_pad = _pow2_pad(len(placements))
        # Mask cache: candidate placements for one topology key are identical
        # across a stream of identical groups (same domains, same rows).
        mkey = (self.cluster_event_seq, p_pad, npc,
                tuple(tuple(p.node_names) for p in placements))
        mcache = getattr(self, "_placement_mask_cache", None)
        if mcache is not None and mcache[0] == mkey:
            masks_dev = mcache[1]
        else:
            masks = np.zeros((p_pad, npc), bool)
            for pi, placement in enumerate(placements):
                for name in placement.node_names:
                    row = index.get(name)
                    if row is not None:
                        masks[pi, row] = True
            masks_dev = jnp.asarray(masks)
            self._placement_mask_cache = (mkey, masks_dev)
        _t_pe = _time.perf_counter()
        res = np.asarray(schedule_placements(
            state, plan.features, plan.batch_pad, plan.fit_strategy,
            plan.vmax, masks_dev,
            n_active=np.int32(len(members)),
            has_pns=plan.has_pns, has_na_pref=plan.has_na_pref,
            port_selfblock=plan.port_selfblock,
            has_aux=plan.has_aux,
            spread_overrides=self._placement_spread_overrides(
                plan, placements, index)))  # [P, 2, B]
        self.placement_device_evals += 1
        self.metrics.placement_evaluations.inc(
            "device", value=len(placements))
        self.metrics.placement_evaluation_duration.observe(
            _time.perf_counter() - _t_pe)

        node_names = [ni.name for ni in self.snapshot.node_info_list]
        candidates = []
        for pi, placement in enumerate(placements):
            rows = res[pi, 0, :len(members)]
            placed = [(m, int(r)) for m, r in zip(members, rows) if r >= 0]
            failed = len(members) - len(placed)
            progress = PlacementProgress(len(placed), failed, len(members))
            if not placed or not fw.run_placement_feasible_plugins(
                    pg_state, group, progress).is_success():
                continue
            # Placement-eligible members carry no stateful-plugin simulation
            # data — the explicit volume/claim gate above keeps PVC members
            # off this path (batch_supported itself ACCEPTS bound-PVC pods;
            # do not remove that gate without establishing fresh-CycleState
            # parity for the placement commit) — so a fresh CycleState is
            # exactly what the host simulation would have produced for them.
            assignment = {m.pod.uid: (node_names[r], CycleState())
                          for m, r in placed}
            pga = PodGroupAssignments(
                placement,
                proposed=[(m.pod, assignment[m.pod.uid][0]) for m in members
                          if m.pod.uid in assignment],
                nodes=[self.snapshot.get(n) for n in placement.node_names])
            candidates.append((placement, assignment, pga))
        return candidates

    # -- resilience: device→host fallback + circuit breaker ----------------

    def _batch_spans(self, name: str, qpis, duration: float,
                     **attrs) -> None:
        """Record one batch-level stage span into each SAMPLED member's
        trace (per-pod copies keep the per-pod chain complete while the
        cost scales with sampled pods, not batch size). Entities without a
        plain pod (group infos riding gang paths) are skipped."""
        tr = self.tracer
        if not tr.enabled or not qpis:
            return
        wall = _time.time() - duration
        for qpi in qpis:
            pod = getattr(qpi, "pod", None)
            if pod is None:
                continue
            ctx = tr.context_for(pod.uid)
            if ctx.sampled:
                tr.record(name, ctx, duration, start=wall, **attrs)

    def _note_device_failure(self, exc: BaseException, where: str) -> None:
        """One unexpected device-path exception: log it, count it, charge
        the breaker, and discard every piece of device-resident state the
        failure may have poisoned (mirror, resume carry, plan caches). The
        caller reroutes the affected work to the host Evaluator."""
        reason = type(exc).__name__
        _log.error("device path failed in %s (%s: %s) — falling back to the "
                   "host path", where, reason, exc, exc_info=True)
        self.metrics.device_path_fallback.inc(reason)
        # Fallbacks sample at 100% (forced process context): a flight-
        # recorder dump of the span ring around this instant is exactly the
        # forensic artifact the breaker incidents need.
        self.tracer.record("device.fallback", self.tracer.proc_ctx(),
                           where=where, reason=reason)
        from ..core import spans as _spans
        _spans.request_dump("device_fallback")
        opened = self.device_breaker.record_failure()
        if opened:
            _log.error(
                "device-path circuit breaker OPEN after %d consecutive "
                "failures; host path pinned for %.1fs",
                self.device_breaker.consecutive_failures,
                self.device_breaker.cooldown)
        self.metrics.device_breaker_state.set(
            0.0 if self.device_breaker.allows() else 1.0)
        self.mirror.invalidate()
        self._resume = None
        self._hints.invalidate("device_failure")
        self._placement_plan_cache = None
        self._placement_mask_cache = None
        self._fail_memo.clear()
        self.metrics.batch_cache_flushed.inc("device_path_failure")
        self._after_flush = True

    def _note_device_success(self) -> None:
        self.device_breaker.record_success()
        self.metrics.device_breaker_state.set(0.0)

    def _note_bind_conflict(self, message: str, pod=None, node: str = "") -> None:
        """Bind-409 (sync unwind or async dispatcher error): beyond the
        base accounting, invalidate the score hint for the conflicted NODE
        only — the winner's commit re-encodes the row through the journal
        (docs/PERF.md hint-cache freshness contract). An async 409 also
        takes back the optimistic hint hit: the loser was counted when its
        thread-mode bind committed, and it will be counted again when it
        actually binds."""
        super()._note_bind_conflict(message, pod, node)
        if pod is not None and pod.__dict__.pop("_hint_bound", False):
            self.hint_hits = max(0, self.hint_hits - 1)
        if node:
            self._hints.note_conflict(node)

    def _note_own_bind_confirm(self, new) -> None:
        """The bind settled: drop the optimistic-hit take-back tag from the
        SCHEDULER's assumed object (the watch copy replaces it in the cache
        right after) — a later requeue of that object must not erase a hit
        that really bound."""
        st = self.cache.pod_states.get(new.uid)
        if st is not None:
            st.pod.__dict__.pop("_hint_bound", None)

    def _recover_qpi(self, qpi) -> None:
        """Host-path one entity stranded by a mid-session device failure.
        Pods the session already committed (bound or assumed onto a node)
        are done — re-running them would double-place; everything else gets
        the exact host cycle."""
        members = getattr(qpi, "members", None)
        bindings = getattr(self.clientset, "bindings", None) or {}
        if members is None:
            pod = qpi.pod
            if pod.node_name or pod.uid in bindings:
                self.queue.done(pod.uid)
                return
            self.host_path_pods += 1
            self.process_one(qpi)
        else:
            remaining = [m for m in members
                         if not (m.pod.node_name or m.pod.uid in bindings)]
            if not remaining:
                # _commit_gang_group finished this group before the crash
                # (it already cleared members + queue bookkeeping): re-running
                # the group cycle would double-place every member.
                return
            if len(remaining) < len(members):
                # Crash mid-gang-commit: some members are already bound.
                # Rerun the group cycle over the UNBOUND tail only — the
                # bound members are real cluster load now, and re-placing
                # them would double-count.
                qpi.members = remaining
            self.host_path_pods += len(remaining)
            self.process_one(qpi)

    # -- device preemption dry run -----------------------------------------

    def device_dry_run_preemption(self, fw: Framework, state, pod,
                                  node_to_status, num_candidates: int,
                                  start: int):
        """Batched DryRunPreemption (ops/kernel.py dry_run_preemption): every
        candidate node's minimal victim set in ONE kernel call, replacing the
        host Evaluator's per-node simulation loop (preemption.go:425).
        Returns rotation-ordered, capped [Candidate] — or None when the
        preemptor (or cluster) needs the exact host dry run: topology-coupled
        features change with victim removal (spread counts, affinity terms,
        freed host ports, freed attach room), which the static-filter + fit
        arithmetic kernel doesn't model. The SELECTED candidate is
        host-verified by the caller (plugins/preemption.py post_filter)."""
        if not self.device_enabled or not self.device_breaker.allows():
            return None
        if self._resources_only_block(pod) is not None:
            return None
        if self._device_unsupported_profile(fw, pod) is not None:
            return None
        try:
            return self._device_dry_run_preemption(
                fw, pod, node_to_status, num_candidates, start)
        except Unsupported:
            return None
        except Exception as e:  # noqa: BLE001 - crash-proof fallback
            # The failure class ADVICE r5 found (victim tensors at one
            # r_slots width, the plan at another) lands here if a new
            # variant ever appears: one count, one breaker charge, and the
            # host Evaluator reruns the dry run exactly — never a crashed
            # PostFilter cycle.
            self._note_device_failure(e, "preemption_dry_run")
            return None

    def _device_dry_run_preemption(self, fw: Framework, pod, node_to_status,
                                   num_candidates: int, start: int):
        self.cache.update_snapshot(self.snapshot)
        nodes = self.snapshot.node_info_list
        if any(ni.pods_with_required_anti_affinity for ni in nodes):
            # Removing an anti-carrying victim could clear exist_anti, which
            # the kernel treats as static.
            return None
        self.mirror.sync(nodes)
        from ..ops.features import build_preemption_victims
        built = build_preemption_victims(pod, self.snapshot, self.mirror)
        if built is None:
            return None
        vic_req, vic_valid, potential = built
        dstate, plan = self.build_plan(fw, pod, 1)
        if vic_req.shape[2] != self.mirror.r_slots:
            # build_plan interned the preemptor's never-seen scalar slots
            # AFTER the victim tensors were built, growing the mirror's
            # resource tier (ADVICE r5 medium). The grown slots name
            # resources no victim carries, so zero-padding vic_req to the
            # plan's width is exact — without it the kernel's
            # `state.req_r - sum_vic` raises a shape error.
            grown = np.zeros(
                (vic_req.shape[0], vic_req.shape[1], self.mirror.r_slots),
                np.int64)
            grown[:, :, :vic_req.shape[2]] = vic_req
            vic_req = grown
        if self._fault_hook is not None:
            self._fault_hook("preempt")
        import jax.numpy as jnp
        from ..core.framework import UNSCHEDULABLE_AND_UNRESOLVABLE
        from ..ops.kernel import dry_run_preemption
        from ..plugins.preemption import Candidate
        res = np.asarray(dry_run_preemption(
            dstate, plan.features, jnp.asarray(vic_req),
            jnp.asarray(vic_valid), vic_valid.shape[1]))
        self.preemption_device_evals += 1
        self._note_device_success()
        feasible, vmask = res[:, 0], res[:, 1:]
        n = len(nodes)
        out = []
        for i in range(n):
            r = (start + i) % n
            st = node_to_status.get(nodes[r].name)
            if st is not None and st.code == UNSCHEDULABLE_AND_UNRESOLVABLE:
                continue  # nodesWherePreemptionMightHelp
            if not feasible[r]:
                continue
            victims = [pi for j, pi in enumerate(potential[r]) if vmask[r, j]]
            out.append(Candidate(node_name=nodes[r].name, victims=victims))
            if len(out) >= num_candidates:
                break
        return out

    # -- device dispatch ---------------------------------------------------

    def _profile_weights(self, fw: Framework) -> Tuple[int, int, int, int, int, int, int]:
        w = {p.name: weight for p, weight in fw.score_plugins}
        return (
            w.get("TaintToleration", 0),
            w.get("NodeResourcesFit", 0),
            w.get("PodTopologySpread", 0),
            w.get("InterPodAffinity", 0),
            w.get("NodeResourcesBalancedAllocation", 0),
            w.get("NodeAffinity", 0),
            w.get("ImageLocality", 0),
        )

    def _profile_filters(self, fw: Framework) -> Tuple[bool, bool, bool, bool, bool]:
        names = {p.name for p in fw.filter_plugins}
        return (
            "NodeName" in names,
            "NodeUnschedulable" in names,
            "TaintToleration" in names,
            "NodeAffinity" in names,
            "NodeResourcesFit" in names,
        )

    def _nominated_device_block(self, fw: Framework, pod) -> Optional[str]:
        """Why `pod` cannot ride the device while nominations exist (None =
        the nominated LANE covers it). The lane models pass-1 of the two-pass
        filter (runtime/framework.go:1275,1300-1317) for RESOURCES only:
        nominated pods' requests/counts tighten the fit filter on their
        nominated rows. Features where a nominated pod interacts beyond
        resources — topology domain counts, affinity terms, host ports,
        counted volume/claim constraints — take the host path, as does a pod
        whose own filters a nominated pod's spec could reject (a nominated
        pod carrying required anti-affinity)."""
        nom = self.queue.nominator
        if not nom.has_nominated_pods():
            return None
        reason = self._resources_only_block(pod)
        if reason is not None:
            return f"nominated pods with {reason}"
        for pi in nom.all_nominated_pod_infos():
            if pi.required_anti_affinity_terms:
                return "nominated pod carries required anti-affinity"
        return None

    @staticmethod
    def _resources_only_block(pod) -> Optional[str]:
        """Why `pod`'s filter outcome depends on more than per-node resource
        arithmetic + static per-batch masks. Shared by the nominated lane and
        the preemption dry-run kernel: both model OTHER pods' effects (a
        nomination counted in, a victim removed) as pure request/count
        deltas, which is only exact when the pod carries none of these."""
        if pod.topology_spread_constraints:
            return "spread constraints"
        aff = pod.affinity
        if aff is not None and (aff.pod_affinity or aff.pod_anti_affinity):
            return "pod affinity"
        if pod.host_ports():
            return "host ports"
        if any(v.pvc_name for v in pod.volumes) or getattr(
                pod, "resource_claims", None):
            return "counted claims"
        return None

    def _nominated_lane(self, pod) -> Optional[list]:
        """[(snapshot row, PodInfo)] for the lane: nominated pods with
        priority >= the batch pod's, on rows present in the snapshot.
        Call AFTER update_snapshot (rows index node_info_list)."""
        nom = self.queue.nominator
        if not nom.has_nominated_pods():
            return None
        index = self.snapshot._index
        if len(index) != len(self.snapshot.node_info_list):
            index = {ni.name: i
                     for i, ni in enumerate(self.snapshot.node_info_list)}
        out = []
        for node_name, pis in nom._node_to_pods.items():
            row = index.get(node_name)
            if row is None:
                continue
            for pi in pis:
                if pi.pod.priority >= pod.priority and pi.pod.uid != pod.uid:
                    out.append((row, pi))
        return out or None

    def _device_unsupported_profile(self, fw: Framework, pod) -> Optional[str]:
        """PTS/IPA are always enforced by the kernel when the pod carries the
        feature; if the profile disables the plugin, take the host path."""
        names = {p.name for p in fw.filter_plugins}
        if pod.topology_spread_constraints and "PodTopologySpread" not in names:
            return "spread constraints without PodTopologySpread plugin"
        aff = pod.affinity
        if aff is not None and (aff.pod_affinity or aff.pod_anti_affinity) \
                and "InterPodAffinity" not in names:
            return "pod affinity without InterPodAffinity plugin"
        pts = fw.plugin("PodTopologySpread")
        if pts is not None and getattr(pts, "default_constraints", ()) \
                and not pod.topology_spread_constraints:
            return "plugin-level default spread constraints"
        if fw.plugin("DynamicResources") is not None:
            req = pod.resource_request()
            if req.scalar_resources and any(
                    dc.extended_resource_name in req.scalar_resources
                    for dc in self.clientset.device_classes.values()
                    if dc.extended_resource_name):
                # Extended resources backed by DRA: the kernel's fit math
                # would treat them as plain node scalars, but the plugin may
                # satisfy them from ResourceSlices instead.
                return "extended resources backed by DRA"
        return None

    def build_plan(self, fw: Framework, pod, batch_size: int):
        """Snapshot → mirror sync → batch feature build → device flush.
        Returns (device_state, BatchPlan). Also the graft/bench entry's way
        to produce kernel inputs.

        Mesh-first: under a mesh the mirror's RESIDENT copy is committed to
        mesh_state_shardings, so flush() uploads host staging straight to
        the sharded placement and later dirty scatters / delta patches ride
        pinned jits on the resident itself — no per-session single-device
        copy + device_put round-trip of the whole state."""
        self.cache.update_snapshot(self.snapshot)
        if self.mesh is not None:
            from ..parallel import mesh_state_shardings
            self.mirror.commit_shardings(mesh_state_shardings(self.mesh))
        else:
            self.mirror.commit_shardings(None)
        self.mirror.sync(self.snapshot.node_info_list)
        ipa = fw.plugin("InterPodAffinity")
        dra_enabled, dra_in_use = self._dra_ctx(fw)
        plan = build_batch(
            pod,
            batch_size=batch_size,
            mirror=self.mirror,
            snapshot=self.snapshot,
            ns_labels_fn=self.cache.namespace_labels,
            percentage_of_nodes_to_score=self.percentage_of_nodes_to_score,
            start_index=self.next_start_node_index,
            weights=self._profile_weights(fw),
            filters_on=self._profile_filters(fw),
            extra_filters={
                name: name in {p.name for p in fw.filter_plugins}
                for name in ("NodePorts", "NodeDeclaredFeatures")
            },
            hard_pod_affinity_weight=getattr(ipa, "hard_pod_affinity_weight", 1),
            ignore_preferred_terms_of_existing_pods=getattr(
                ipa, "ignore_preferred_terms_of_existing_pods", False),
            fit_plugin=fw.plugin("NodeResourcesFit"),
            clientset=self.clientset, pvc_refs=self.cache.pvc_refs,
            limited_drivers=self.limited_drivers(),
            dra_enabled=dra_enabled,
            dra_in_use=dra_in_use,
            nominated=self._nominated_lane(pod),
        )
        state = self.mirror.flush()  # committed to the mesh placement
        if self.mesh is not None:
            from ..parallel import shard_features
            plan.features = shard_features(plan.features, self.mesh)
        return state, plan

    def warm_for(self, pod, batch_sizes: Optional[List[int]] = None,
                 nominated: bool = False) -> None:
        """Compile the kernel shapes a workload of `pod`-shaped pods will hit,
        WITHOUT scheduling anything: dispatches with n_active=0 are fully
        inert (every scan step is padding). Benchmark harnesses call this so
        XLA compilation lands outside the measured window. Warms both the
        fresh-carry and chained-carry traces.

        The warm calls MUST be call-signature-identical to the session's
        dispatch (run_device_session) — `carry_in=None` passed explicitly is
        a DIFFERENT kwargs pytree than omitting the kwarg, and a mismatch
        recompiles (~1 min) inside the measured window. Sessions always plan
        with self.max_batch, so that is the only batch_pad tier to warm;
        `batch_sizes` is accepted for compatibility but ignored."""
        del batch_sizes
        fw = self.framework_for_pod(pod)
        if batch_supported(pod, self.snapshot,
                           fit_plugin=fw.plugin("NodeResourcesFit")) is not None:
            return
        state, plan = self.build_plan(fw, pod, self.max_batch)
        # Warm dispatches must ride _dispatch (call-path identity) but must
        # not count as engagement: shard_map_dispatches is what the bench
        # detail and the MULTICHIP dryrun assert LIVE dispatches against.
        _smd0 = self.shard_map_dispatches
        results, carry = self._dispatch(state, plan, 0, None)
        results2, _ = self._dispatch(state, plan, 0, carry)
        np.asarray(results2)  # block until compiled + executed
        self.shard_map_dispatches = _smd0
        if self._shard_map_fn(plan) is not None:
            # The live dispatch rides the shard_map lap path — but a
            # mid-workload row_local flip (an anti-affinity pod lands and
            # exist_anti goes nonzero, or a node-tier regrow breaks shard
            # divisibility) drops later sessions onto the GSPMD
            # schedule_batch fallback. Warm that trace too, or the flip
            # puts its ~1min XLA compile inside the measured window (the
            # same hazard as the anti_rowlocal fallback below).
            r1, c1 = self._gspmd_dispatch(state, plan, 0, None)
            r2, _ = self._gspmd_dispatch(state, plan, 0, c1)
            np.asarray(r2)
        if plan.anti_rowlocal:
            # anti_rowlocal is topology-derived (all anti axes singleton) and
            # can flip to False mid-workload (e.g. churn adds a node sharing a
            # hostname-like value): warm the conservative fallback trace too
            # so the flip can't put a compile inside the measured window.
            import dataclasses
            fb = dataclasses.replace(plan, anti_rowlocal=False)
            r1, c1 = self._dispatch(state, fb, 0, None)
            r2, _ = self._dispatch(state, fb, 0, c1)
            np.asarray(r2)
        if nominated and not plan.has_nom:
            # Preemption workloads flip the nominated lane on mid-run (the
            # first nomination would otherwise compile inside the measured
            # window): warm the has_nom variant with an empty lane — shapes
            # and statics are identical to the live nominated plan.
            import dataclasses
            import jax.numpy as jnp
            nom_req = jnp.zeros((self.mirror.np_cap, self.mirror.r_slots),
                                jnp.int64)
            nom_pods = jnp.zeros(self.mirror.np_cap, jnp.int32)
            if self.mesh is not None:
                # Match the live dispatch's committed shardings (jit keys on
                # them): shard_features puts nom arrays on the node axis.
                import jax
                from jax.sharding import NamedSharding, PartitionSpec as P
                nom_req = jax.device_put(
                    nom_req, NamedSharding(self.mesh, P("nodes", None)))
                nom_pods = jax.device_put(
                    nom_pods, NamedSharding(self.mesh, P("nodes")))
            nf = plan.features._replace(nom_req=nom_req, nom_pods=nom_pods)
            nv = dataclasses.replace(plan, features=nf, has_nom=True)
            r1, c1 = self._dispatch(state, nv, 0, None)
            r2, _ = self._dispatch(state, nv, 0, c1)
            np.asarray(r2)

    def warm_for_placements(self, pod, group_size: int,
                            n_placements: int) -> None:
        """Compile the stacked placement-evaluation kernel for the tiers a
        topology-constrained gang workload will hit (inert n_active=0
        dispatch), so XLA compilation lands outside the measured window —
        the placement analogue of warm_for."""
        import jax.numpy as jnp
        from ..ops.kernel import schedule_placements
        fw = self.framework_for_pod(pod)
        if self._batch_supported_memo(pod, fw) is not None:
            return
        try:
            state, plan = self.build_plan(fw, pod, group_size)
        except Unsupported:
            return
        if not self._placement_plan_restriction_invariant(plan):
            return
        p_pad = _pow2_pad(max(1, n_placements))
        masks = jnp.zeros((p_pad, self.mirror.np_cap), bool)
        overrides = None
        f = plan.features
        if f.dns_axis.shape[0] or f.sa_axis.shape[0]:
            # Warm the spread-override trace with empty tables of the live
            # shapes (pad lanes are inert at n_active=0).
            overrides = (
                jnp.zeros((p_pad, f.dns_axis.shape[0], plan.vmax), jnp.int32),
                jnp.zeros((p_pad, f.dns_axis.shape[0], plan.vmax), bool),
                jnp.ones((p_pad, f.dns_axis.shape[0]), jnp.int32),
                jnp.zeros((p_pad, f.sa_axis.shape[0], plan.vmax), jnp.int32),
                jnp.zeros((p_pad, f.sa_axis.shape[0]), jnp.int64),
            )
        res = schedule_placements(
            state, plan.features, plan.batch_pad, plan.fit_strategy,
            plan.vmax, masks, n_active=np.int32(0),
            has_pns=plan.has_pns, has_na_pref=plan.has_na_pref,
            port_selfblock=plan.port_selfblock, has_aux=plan.has_aux,
            spread_overrides=overrides)
        np.asarray(res)

    def _shard_map_fn(self, plan):
        """The explicit-collectives lap kernel for this plan, or None when
        the GSPMD-compiled schedule_batch owns the dispatch. Row-local
        plans (BatchPlan.row_local) at production batch tiers ride
        shard_map: per-shard work is provably local and the per-lap
        collectives are two small exchanges (vs GSPMD's inferred ~2×
        count, MULTICHIP_r05). Small batches keep the scan path — the lap
        gains nothing there (ops/kernel.py static_scores threshold)."""
        if (self.mesh is None or not self._shard_map_enabled
                or not plan.row_local or plan.batch_pad <= 64):
            return None
        from ..parallel.mesh import mesh_shard_count, sharded_lap_schedule
        if self.mirror.np_cap % mesh_shard_count(self.mesh):
            return None  # node tier not divisible across shards
        return sharded_lap_schedule(self.mesh, plan.batch_pad,
                                    plan.fit_strategy, plan.vmax)

    def _dispatch(self, state, plan, n_active: int, carry):
        """The ONLY kernel call site. Every dispatch — warm or live — must
        be call-signature-identical (kwarg set included: static kwargs are
        part of jit's cache-key pytree structure), or the warmed trace
        misses and a ~1min XLA compile lands inside the measured window.
        The path choice (shard_map lap vs GSPMD schedule_batch) is a pure
        function of (mesh, plan statics), so it is constant for a
        session's lifetime and warm_for warms the same path the live
        session runs."""
        if self._fault_hook is not None:
            self._fault_hook("dispatch")
        fn = self._shard_map_fn(plan)
        if fn is not None:
            self.shard_map_dispatches += 1
            return fn(state, plan.features, np.int32(n_active), carry)
        return self._gspmd_dispatch(state, plan, n_active, carry)

    def _gspmd_dispatch(self, state, plan, n_active: int, carry):
        """The GSPMD-compiled schedule_batch call — one kwargs set shared
        by the live fallback dispatch and warm_for's fallback warming (a
        differing kwarg pytree would be a separate jit cache entry)."""
        return schedule_batch(
            state, plan.features, plan.batch_pad, plan.fit_strategy,
            plan.vmax, n_active=np.int32(n_active), carry_in=carry,
            has_pns=plan.has_pns, has_ipa_base=plan.has_ipa_base,
            anti_rowlocal=plan.anti_rowlocal, has_na_pref=plan.has_na_pref,
            port_selfblock=plan.port_selfblock, has_aux=plan.has_aux,
            has_nom=plan.has_nom)

    def collective_counts(self, pod, batch_size: Optional[int] = None):
        """Compile-time per-step collective profile of the EXACT dispatch a
        `pod`-shaped session runs (ici/dcn split via
        parallel/mesh.py collective_report), or None off-mesh. This is the
        number the MULTICHIP rows regression-pin: the row-local shard_map
        path must stay at-or-below the GSPMD baseline per step."""
        if self.mesh is None:
            return None
        from ..parallel.mesh import collective_report, mesh_host_split
        fw = self.framework_for_pod(pod)
        bs = batch_size or self.max_batch
        state, plan = self.build_plan(fw, pod, bs)
        fn = self._shard_map_fn(plan)
        if fn is not None:
            lowered = fn.lower(state, plan.features, np.int32(bs), None)
            path = "shard_map"
        else:
            lowered = schedule_batch.lower(
                state, plan.features, plan.batch_pad, plan.fit_strategy,
                plan.vmax, n_active=np.int32(bs), carry_in=None,
                has_pns=plan.has_pns, has_ipa_base=plan.has_ipa_base,
                anti_rowlocal=plan.anti_rowlocal,
                has_na_pref=plan.has_na_pref,
                port_selfblock=plan.port_selfblock, has_aux=plan.has_aux,
                has_nom=plan.has_nom)
            path = "gspmd"
        n_hosts, per_host = mesh_host_split(self.mesh)
        report = collective_report(lowered.compile().as_text(),
                                   n_hosts, per_host)
        report["path"] = path
        return report

    # -- device session ----------------------------------------------------
    #
    # A *session* is a run of same-signature batches chained on device: the
    # ScanCarry returned by batch N is passed straight back as batch N+1's
    # carry_in (no feature rebuild, no state re-upload), and the host commits
    # batch N's pods while the device computes batch N+1 — the TPU-era form
    # of the reference's scheduling/binding-cycle overlap
    # (schedule_one.go:141 go runBindingCycle). The session ends when the
    # queue yields something incompatible, a commit diverges from the host
    # oracle, or any external cluster event arrives
    # (Scheduler.cluster_event_seq).

    def _nom_resume_key(self, priority: int):
        """Nomination component of the session-resume key: the set version
        plus — only when a lane is live — the priority threshold the plan
        was built with (an empty nominator makes priority irrelevant)."""
        nom = self.queue.nominator
        return (nom.version, priority if nom.has_nominated_pods() else None)

    # -- incremental session resume (typed event journal) -------------------
    #
    # The resume cache used to be all-or-nothing: ANY cluster event bumped
    # cluster_event_seq, missed the key, and forced a full snapshot→features
    # teardown (plan_build dominated the WhileGated/DeletedPodsWithFinalizers
    # perf rows). The journal (core/cache.py EventJournal) records what each
    # bump WAS, so a session can classify the intervening events against its
    # plan and patch exactly the rows they dirtied — mirror staging, resident
    # device state, and the live carry — then keep (or resume) the session
    # with the pipeline full. Unclassifiable events keep today's behavior:
    # full rebuild / invalidation.

    def _count_rebuild(self, kind: str) -> None:
        if kind == "full":
            self.plan_rebuilds_full += 1
        elif kind == "delta":
            self.plan_rebuilds_delta += 1
        else:
            self.plan_rebuilds_resume += 1
        # plane label: mesh full rebuilds are the cost the delta patches
        # exist to avoid (a sharded teardown re-uploads the whole state) —
        # the MULTICHIP rows regression-pin the split.
        self.metrics.plan_rebuild_total.inc(
            kind, "mesh" if self.mesh is not None else "single")

    def _neutral_sig(self, fw: Framework, pod, sig):
        """Namespace/label-erased session signature, or None when ineligible.

        The IPA and PTS Sign plugins fold (labels, namespace) into every
        pod's signature because affinity terms and spread selectors read
        them — which splits e.g. per-namespace pod sets (the *WithNSSelector
        init phase) into one session per namespace even though every pod
        builds the IDENTICAL plan. When the pod carries no affinity/spread
        machinery, no volumes or claims (namespaced PVC keys), and NO pod in
        the cluster carries affinity terms (cache.affinity_pod_refs — live
        truth, unlike the possibly-stale snapshot sublists), labels and
        namespace are scheduling-inert: erase them so pods differing only
        there share one session, one plan, and one chained carry.

        The erased tuple is pure spec (memoized on the template-shared
        signature holder, so a namespace sweep of N clones erases once);
        only the cluster-side affinity gate is live state."""
        if sig is None or self.cache.affinity_pod_refs:
            return None
        shared = pod.__dict__.get("_sig_shared")
        # node_name rides the key exactly as sign_pod's own memo does (it is
        # the one signed field mutated in place).
        key = ("_nsig", id(fw), pod.node_name)
        if shared is not None and key in shared:
            return shared[key]
        aff = pod.affinity
        if (pod.topology_spread_constraints or pod.volumes
                or getattr(pod, "resource_claims", None)
                or (aff is not None
                    and (aff.pod_affinity or aff.pod_anti_affinity))):
            out = None
        else:
            out = tuple(
                (name, part[2:] if name in ("InterPodAffinity",
                                            "PodTopologySpread") else part)
                for name, part in sig)
        if shared is not None:
            shared[key] = out
        return out

    def _classify_delta(self, events, plan):
        """Map journal events to the feature blocks they dirty under `plan`.
        Returns (level, dirty node names, node_only, pod_only): 'benign'
        (nothing node-side moved), 'safe' (row patches whose events only
        enlarge feasibility — in-flight device results stay committable),
        'strict' (row patches that may shrink feasibility: applicable with
        an empty pipeline, or while busy when pod_only and the bind path
        re-validates capacity) — or None when any event needs the full
        rebuild. node_only/pod_only say whether every dirtying event was a
        taint/alloc node update resp. a plain-pod row event."""
        from ..core.cache import (EV_NAMESPACE, EV_NODE_UPDATE, EV_POD_ADD,
                                  EV_POD_REMOVE, EV_POD_UPDATE, EV_QUEUE)
        level = 0
        names = set()
        node_only = True  # every dirtying event is a taint/alloc node update
        pod_only = True   # every dirtying event is a plain-pod row event
        for ev in events:
            if ev.kind == EV_QUEUE:
                continue
            if ev.kind == EV_NAMESPACE:
                # Namespace labels feed ONLY affinity namespaceSelector
                # matching: inert while no term exists on either side.
                if plan.pod_local and self.cache.affinity_pod_refs == 0:
                    continue
                return None
            if ev.kind in (EV_POD_ADD, EV_POD_REMOVE, EV_POD_UPDATE):
                # plan.pod_local: a pod on node n can only dirty row n's
                # resource aggregates (no count table could have counted
                # it); ev.pod_plain: the pod brings no terms that could
                # flip exist_anti/ipa_base from their compiled-empty state.
                if not (plan.pod_local and ev.pod_plain):
                    return None
                if ev.pod_ports and plan.port_selfblock:
                    return None  # used_ports moved under a port-aware plan
                node_only = False
            elif ev.kind == EV_NODE_UPDATE:
                if not plan.pod_local:
                    return None  # honor-policy spread tables read taints
                pod_only = False
            else:
                return None
            names.add(ev.key)
            level = max(level, 1 if ev.shrink else 2)
        return ("benign", "safe", "strict")[level], names, node_only, pod_only

    def _note_session_events(self, sd, plan, node_names, busy: bool) -> bool:
        """The ONE journal-consumption protocol both session kinds run at
        their invalidation checks. `sd` is the session's mutable delta view
        (_SessionDelta); updated in place. Returns True when the session
        stays valid — benign advance, patch applied, or patch deferred
        until the pipeline drains — False when it must invalidate. `busy` =
        dispatched-but-uncommitted device results exist."""
        if self.cluster_event_seq == sd.start_seq and not sd.patch_pending:
            return True
        events = self.journal.since(sd.start_seq)
        if events is None:
            return False
        cls = self._classify_delta(events, plan)
        if cls is None:
            return False
        level, names, _node_only, pod_only = cls
        if not names:
            sd.start_seq = self.cluster_event_seq
            sd.patch_pending = False
            return True
        if busy:
            if level == "strict" and not (
                    pod_only and self.bind_capacity_validated):
                return False  # in-flight results may no longer fit
            if pod_only and self.bind_capacity_validated:
                # Strict POD rows under a capacity-validating bind path (the
                # shard plane): a foreign scheduler's bind may have consumed
                # room an in-flight result counts on, but the binding
                # subresource re-validates committed usage per node, so the
                # worst case is a 409 → conflict requeue — never an
                # overcommitted node. Patch the carry/state NOW, with the
                # pipeline still full: draining first (the conservative
                # deferral below) serializes every shard against its peers'
                # bind bursts — the ping-pong that held a 2-shard plane
                # under a 1-shard one. The patched rows are charged dirty
                # (_SessionDelta.busy_patch_rows) so session-end adoption
                # re-encodes them from post-commit truth.
                patched = self._apply_delta_patch(
                    plan, node_names, names, sd.state, sd.carry, busy=True)
                if patched is not None:
                    sd.state, sd.carry = patched
                    row_of = self._session_row_of[1]
                    sd.busy_patch_rows.extend(
                        row_of[nm] for nm in names if nm in row_of)
                    sd.start_seq = self.cluster_event_seq
                    sd.patch_pending = False
                    self._count_rebuild("delta")
                    return True
            # Deferral: commit in-flight as-is, patch once the pipeline
            # drains — shrink-only ('safe') events only enlarged
            # feasibility, and a failed busy patch falls back here. Strict
            # NODE events (taint/alloc shrink) still invalidate above:
            # nothing re-validates taints at bind time.
            sd.patch_pending = True
            return True
        patched = self._apply_delta_patch(
            plan, node_names, names, sd.state, sd.carry)
        if patched is None:
            return False
        sd.state, sd.carry = patched
        sd.start_seq = self.cluster_event_seq
        sd.patch_pending = False
        self._count_rebuild("delta")
        return True

    def _apply_delta_patch(self, plan, node_names, names, state, carry,
                           busy: bool = False):
        """Patch the journal's dirty rows into mirror staging, the resident
        device state, and the session carry. Returns (state, carry) or None
        when the patch can't apply — the caller's full-rebuild fallback
        recovers from every None.

        Mesh sessions patch EVERY classifiable kind — POD-event aggregates
        (pod_add/pod_remove/pod_update) included, the events that dominate
        churn workloads: the row scatter and the carry re-eval run through
        jits pinned to the session's committed shardings
        (mesh_state_shardings / patch_carry_rows_pinned), so the patched
        pytrees keep the exact placement the session kernel's traces key
        on, and the stale state/carry buffers are DONATED into the patch
        jits (reused in place) when no dispatched batch still reads them
        (`busy`)."""
        if not names:
            return state, carry
        row_of = getattr(self, "_session_row_of", None)
        if row_of is None or row_of[0] is not node_names:
            row_of = (node_names, {n: i for i, n in enumerate(node_names)})
            self._session_row_of = row_of
        updates = []
        for nm in names:
            row = row_of[1].get(nm)
            ni = self.cache.nodes.get(nm)
            if row is None or ni is None or ni.node is None:
                return None  # row set changed shape: structural after all
            updates.append((row, ni))
        if self.mesh is not None:
            from ..parallel import mesh_state_shardings
            new_state = self.mirror.patch_rows(
                updates, sharded_state=state,
                out_shardings=mesh_state_shardings(self.mesh),
                donate=not busy)
        else:
            new_state = self.mirror.patch_rows(updates)
        if new_state is None:
            return None
        rows = sorted({r for r, _ in updates})
        if not plan.has_pns:
            from ..ops.codebook import EFFECT_PREFER_NO_SCHEDULE
            if (self.mirror.h_taint_eff[rows]
                    == EFFECT_PREFER_NO_SCHEDULE).any():
                # The plan compiled the no-PreferNoSchedule fast path;
                # staging is already patched, so the full rebuild (which
                # recomputes has_pns) resumes from truth.
                return None
        if carry is not None:
            import jax.numpy as jnp
            from ..ops.device_state import patch_tier
            from ..ops.kernel import patch_carry_rows, patch_carry_rows_pinned
            tier = patch_tier(len(rows))
            prows = rows + [rows[-1]] * (tier - len(rows))
            patch_fn = (patch_carry_rows_pinned if self.mesh is not None
                        else patch_carry_rows)
            carry = patch_fn(
                new_state, plan.features, carry,
                jnp.asarray(np.asarray(prows, np.int32)),
                jnp.asarray(self.mirror.h_req_r[prows]),
                jnp.asarray(self.mirror.h_nonzero[prows]),
                jnp.asarray(self.mirror.h_pod_count[prows]),
                fit_strategy=plan.fit_strategy, has_nom=plan.has_nom)
        self.delta_dirty_rows += len(rows)
        self.metrics.plan_rebuild_dirty_rows.inc(value=len(rows))
        return new_state, carry

    def _resume_or_rebuild(self, fw: Framework, head_pod, sig, nsig,
                           aux_shape, claims_rv):
        """Session-start plan acquisition: exact/neutral resume, journal
        delta patch, or full rebuild. Returns (state, plan, carry,
        node_names, kind)."""
        carry = None
        resume, self._resume = self._resume, None
        kind = "full"
        state = plan = node_names = None
        _t_hint = _time.perf_counter()
        if resume is not None:
            rkey, rseq, payload, rnom = resume
            sig_ok = (rkey[1] == sig) if rkey[0] == "exact" else (
                nsig is not None and rkey[1] == nsig)
            if (sig_ok
                    and rkey[2:] == (id(fw), aux_shape, claims_rv,
                                     self.attempts, self.state_unwinds)
                    and rnom == self._nom_resume_key(head_pod.priority)):
                state, plan, carry, node_names = payload
                if rseq == self.cluster_event_seq:
                    kind = "resume"
                else:
                    events = self.journal.since(rseq)
                    cls = (self._classify_delta(events, plan)
                           if events is not None else None)
                    if cls is not None:
                        # No pipeline is in flight at session start: every
                        # level (benign/safe/strict) may patch here.
                        patched = self._apply_delta_patch(
                            plan, node_names, cls[1], state, carry)
                        if patched is not None:
                            state, carry = patched
                            kind = "delta"
                if kind == "full":
                    carry = None
        # get_node_hint_duration (runtime/batch.go GetNodeHint analogue):
        # the batch-reuse lookup is the session-resume key check.
        self.metrics.get_node_hint_duration.observe(
            _time.perf_counter() - _t_hint)
        if kind == "full":
            _t0 = _time.perf_counter()
            state, plan = self.build_plan(fw, head_pod, self.max_batch)
            self.plan_build_s += _time.perf_counter() - _t0
            node_names = [ni.name for ni in self.snapshot.node_info_list]
        self._count_rebuild(kind)
        return state, plan, carry, node_names, kind

    def _save_resume(self, fw: Framework, head_pod, sig, aux_shape,
                     state, plan, carry, node_names,
                     neutral_ok: bool = True) -> None:
        """Capture a clean session's end state for the next resume check.
        Saved under the neutral (namespace-erased) signature when eligible,
        so a stream of label/namespace-only-different sessions chains."""
        nsig = self._neutral_sig(fw, head_pod, sig) if neutral_ok else None
        mode = ("neutral", nsig) if nsig is not None else ("exact", sig)
        self._resume = (
            mode + (id(fw), aux_shape,
                    getattr(self.clientset, "resource_claims_rv", 0),
                    self.attempts, self.state_unwinds),
            self.cluster_event_seq,
            (state, plan, carry, node_names),
            self._nom_resume_key(head_pod.priority))

    def limited_drivers(self) -> frozenset:
        rv = getattr(self.clientset, "csi_nodes_rv", 0)
        if rv != self._limited_drivers_n:
            self._limited_drivers = frozenset(
                d for cn in self.clientset.csi_nodes.values()
                for d in cn.driver_limits)
            self._limited_drivers_n = rv
        return self._limited_drivers

    def _dra_ctx(self, fw: Framework):
        """(dra_enabled, in_use) for eligibility/plan builds: claims are
        scheduling-relevant only when the profile runs DynamicResources."""
        dr = fw.plugin("DynamicResources")
        if dr is None:
            return False, None
        return True, dr._in_use()

    def _claims_of(self, pod) -> list:
        return [f"{pod.namespace}/{v.pvc_name}"
                for v in pod.volumes if v.pvc_name]

    def _claim_shape(self, pod):
        names = getattr(pod, "resource_claims", ()) or ()
        if not names:
            return None
        claim = self.clientset.resource_claims.get(
            f"{pod.namespace}/{names[0]}")
        if claim is None or len(claim.requests) != 1:
            return ("?",)
        r = claim.requests[0]
        return (r.device_class, r.count,
                tuple(sorted(r.selectors.items())), r.expression)

    def _aux_shape(self, pod):
        """The counted-constraint shape a plan models for this pod: the
        volume attach (driver, inc) AND the DRA claim shape. Every session
        member must share it — a mixed batch would run the head's aux math
        against members with different (or no) counted constraints. Plain
        pods (the >13k pods/s path) answer without the volume walk."""
        if not pod.volumes and not getattr(pod, "resource_claims", None):
            return (None, None)
        from ..ops.features import volume_device_support
        _r, vol_d, vol_inc = volume_device_support(
            pod, self.clientset, pvc_refs=self.cache.pvc_refs,
            limited_drivers=self.limited_drivers())
        return ((vol_d, vol_inc) if vol_d else None, self._claim_shape(pod))

    def _batch_supported_memo(self, pod, fw: Framework):
        """batch_supported with the verdict memoized on the pod's shared
        template-signature holder (clone_from_template invariant: clones
        never mutate spec), so a 50k-pod workload computes it once, not 50k
        times. The one per-INSTANCE field the verdict reads —
        nominated_node_name — is checked outside the memo."""
        if pod.nominated_node_name:
            return "nominated node fast path"
        shared = pod.__dict__.get("_sig_shared")
        if (shared is None or any(v.pvc_name for v in pod.volumes)
                or getattr(pod, "resource_claims", None)):
            # PVC/claim verdicts depend on live claim/PV state — never
            # memoized.
            dra_enabled, dra_in_use = self._dra_ctx(fw)
            return batch_supported(
                pod, self.snapshot,
                fit_plugin=fw.plugin("NodeResourcesFit"),
                ba_plugin=fw.plugin("NodeResourcesBalancedAllocation"),
                clientset=self.clientset, pvc_refs=self.cache.pvc_refs,
                limited_drivers=self.limited_drivers(),
                dra_enabled=dra_enabled, dra_in_use=dra_in_use,
                session_claims=self._session_claims)
        key = ("_bsup", id(fw))
        if key in shared:
            return shared[key]
        reason = batch_supported(
            pod, self.snapshot,
            fit_plugin=fw.plugin("NodeResourcesFit"),
            ba_plugin=fw.plugin("NodeResourcesBalancedAllocation"),
            clientset=self.clientset, pvc_refs=self.cache.pvc_refs,
            limited_drivers=self.limited_drivers())
        shared[key] = reason
        return reason

    def _session_compatible(self, head: QueuedPodInfo, fw: Framework, sig) -> bool:
        if isinstance(head, QueuedPodGroupInfo):
            return False
        if (getattr(self, "_session_nom_priority", None) is not None
                and head.pod.priority != self._session_nom_priority):
            return False  # nominated lane is priority-thresholded
        if not (head.pod.scheduler_name in self.profiles
                and self.framework_for_pod(head.pod) is fw):
            return False
        psig = fw.sign_pod(head.pod)
        sig_ok = psig == sig
        if not sig_ok and psig is not None \
                and self._session_neutral_sig is not None:
            # Label/namespace-only signature difference: join the session
            # when the pod's namespace-erased signature matches and the
            # cluster still has no affinity-carrying pods (_neutral_sig
            # re-checks the live gate) — per-namespace pod sweeps then ride
            # ONE session instead of one per namespace.
            sig_ok = self._neutral_sig(fw, head.pod, psig) \
                == self._session_neutral_sig
        if not (sig_ok
                # Signatures only cover the Sign plugins; a member with a
                # feature outside the kernel (unbound volumes, DRA claims)
                # shares the head's signature but must NOT ride the device —
                # it would silently skip that feature's filters.
                and self._batch_supported_memo(head.pod, fw) is None):
            return False
        if self._aux_shape(head.pod) != getattr(
                self, "_session_aux_shape", None):
            # The plan's aux decrement models ONE counted-constraint shape
            # (volume attach or claim); a member with a different (or no)
            # constraint must not share the batch.
            return False
        claims = self._claims_of(head.pod)
        dra_claims = [f"dra:{head.pod.namespace}/{n}"
                      for n in getattr(head.pod, "resource_claims", ()) or ()]
        if claims or dra_claims:
            # A claim already used by a pod accepted into this session must
            # not be counted twice by the kernel's per-landing attach math.
            if any(c in self._session_claims for c in claims + dra_claims):
                return False
            self._session_claims.update(claims)
            self._session_claims.update(dra_claims)
        return True

    def _collect_session_batch(self, fw: Framework, sig) -> List[QueuedPodInfo]:
        """Pop up to max_batch pods matching the session signature; an
        incompatible entity goes to the holdover slot and ends the refill."""
        batch: List[QueuedPodInfo] = []
        while len(batch) < self.max_batch:
            nxt = self._pop()
            if nxt is None:
                break
            if self._session_compatible(nxt, fw, sig):
                batch.append(nxt)
            else:
                self._holdover = nxt
                break
        return batch

    def run_device_session(self, fw: Framework, first_batch: List[QueuedPodInfo]) -> None:
        """Crash-proof wrapper: an unexpected device failure mid-session
        (kernel shape error, dispatch fault, poisoned carry) must not strand
        the entities the session popped — every batch not yet fully
        committed reruns on the host path, the mirror invalidates, and the
        breaker is charged. Unsupported keeps its existing contract
        (schedule_one host-paths first_batch)."""
        pending: List[List[QueuedPodInfo]] = []
        try:
            self._run_device_session(fw, first_batch, pending)
        except Unsupported:
            raise
        except Exception as e:  # noqa: BLE001 - device→host fallback
            self._note_device_failure(e, "device_session")
            for b in pending:
                for qpi in b:
                    self._recover_qpi(qpi)

    def _run_device_session(self, fw: Framework,
                            first_batch: List[QueuedPodInfo],
                            pending: List[List[QueuedPodInfo]]) -> None:
        pending.append(first_batch)  # crash-recovery registry (wrapper);
        # registered BEFORE build_plan so a plan-build crash recovers too.
        sig = fw.sign_pod(first_batch[0].pod)
        nsig = self._neutral_sig(fw, first_batch[0].pod, sig)
        self._session_neutral_sig = nsig
        # Signatures cover only the Sign plugins — NOT volumes/claims, whose
        # counted-constraint shape changes the PLAN (aux_room semantics). A
        # resume must match the aux shape too, or a claim-template session
        # could chain onto a volume session's attach-room plan (fuzz-caught).
        aux_shape = self._aux_shape(first_batch[0].pod)
        claims_rv = getattr(self.clientset, "resource_claims_rv", 0)
        _tp0 = _time.perf_counter()
        state, plan, carry, node_names, _rkind = self._resume_or_rebuild(
            fw, first_batch[0].pod, sig, nsig, aux_shape, claims_rv)
        _tp = _time.perf_counter() - _tp0
        # Plan acquisition latency: the extension-point histogram gets
        # EVERY session (p50/p99 truth); sampled pods get plan.build spans
        # tagged with the acquisition kind (full/delta/resume).
        self.metrics.framework_extension_point_duration.observe(
            _tp, "DevicePlan", "Success", "")
        self._batch_spans("plan.build", first_batch, _tp,
                          kind=_rkind, batch=len(first_batch))
        sd = _SessionDelta(state, carry, self.cluster_event_seq)
        del state, carry
        start_unwinds = self.state_unwinds
        start_nom = self.queue.nominator.version
        inflight: List[Tuple[List[QueuedPodInfo], object]] = []
        ok_rows: List[int] = []
        dirty_rows: List[int] = []
        invalidated = False
        batch: Optional[List[QueuedPodInfo]] = first_batch

        while True:
            # Refill the dispatch pipeline (depth-bounded): dispatch is
            # async — these calls enqueue device work and return immediately.
            while not invalidated and len(inflight) < self.pipeline_depth:
                if sd.patch_pending:
                    if inflight:
                        break  # retire dispatched work before patching
                    if not self._note_session_events(sd, plan, node_names,
                                                     busy=False):
                        invalidated = True
                        break
                if batch is None:
                    batch = self._collect_session_batch(fw, sig) or None
                    if batch is None and self._event_inbox:
                        # A concurrent client (threaded watch feed) may have
                        # parked pod-add events while this session ran: drain
                        # them HERE so a creation burst doesn't end the
                        # session early. Cluster-state events patch the live
                        # plan+carry when the journal classifies them, and
                        # invalidate exactly as before when it can't.
                        self.drain_event_inbox()
                        if not self._note_session_events(
                                sd, plan, node_names, busy=bool(inflight)):
                            invalidated = True
                        elif sd.patch_pending:
                            continue  # patch (or drain) before collecting
                        else:
                            batch = self._collect_session_batch(fw, sig) or None
                    if batch is None:
                        break
                    pending.append(batch)
                _td0 = _time.perf_counter()
                results, sd.carry = self._dispatch(
                    sd.state, plan, len(batch), sd.carry)
                self._batch_spans("device.dispatch", batch,
                                  _time.perf_counter() - _td0,
                                  batch=len(batch))
                # Start the device→host copy NOW: on a tunneled TPU the
                # result fetch pays a full pipeline-flush RTT (~10s of ms);
                # issuing it at dispatch time overlaps that latency with the
                # host commit loop of the previous batch.
                try:
                    results.copy_to_host_async()
                except AttributeError:
                    pass
                self.device_batches += 1
                self.metrics.batch_attempts.inc("dispatched")
                self.metrics.batch_size.observe(len(batch))
                inflight.append((batch, results))
                self.metrics.goroutines.set(float(len(inflight)),
                                            "device_dispatch")
                batch = None
            if not inflight:
                break
            # Retire the oldest batch: block on its results (the device is
            # already computing the NEXT batch), then run the host tail.
            b, results = inflight.pop(0)
            self.metrics.goroutines.set(float(len(inflight)),
                                        "device_dispatch")
            _t0 = _time.perf_counter()
            res = np.asarray(results)  # one device→host fetch
            _t1 = _time.perf_counter()
            self.device_wait_s += _t1 - _t0
            self.metrics.framework_extension_point_duration.observe(
                _t1 - _t0, "DeviceWait", "Success", "")
            self._batch_spans("device.wait", b, _t1 - _t0, batch=len(b))
            if not invalidated:
                invalidated = self._commit_batch(
                    b, res, fw, node_names, ok_rows, dirty_rows)
                _tc = _time.perf_counter() - _t1
                self.host_commit_s += _tc
                self.metrics.framework_extension_point_duration.observe(
                    _tc, "HostCommit", "Success", "")
                self._batch_spans("host.commit", b, _tc, batch=len(b))
                if getattr(self, "_after_flush", False):
                    # First retired batch after a flush: its pods scheduled
                    # from a fresh (non-chained) evaluation.
                    self.metrics.pod_scheduled_after_flush.inc(
                        value=len(ok_rows))
                    self._after_flush = False
                if not invalidated and (
                        self.state_unwinds != start_unwinds
                        or self.queue.nominator.version != start_nom
                        or not self._note_session_events(
                            sd, plan, node_names, busy=bool(inflight))):
                    invalidated = True
                    sd.start_seq = self.cluster_event_seq
                    start_unwinds = self.state_unwinds
                    start_nom = self.queue.nominator.version
            else:
                # A previous batch diverged: every later device choice is
                # stale. Host-path the pods and charge their rows dirty.
                for i, qpi in enumerate(b):
                    row = int(res[0, i])
                    if row >= 0:
                        dirty_rows.append(row)
                    self.host_path_pods += 1
                    self.process_one(qpi)
            if b in pending:
                pending.remove(b)  # fully handled: out of crash recovery

        if batch:  # popped but never dispatched (invalidated mid-refill)
            for qpi in batch:
                self.host_path_pods += 1
                self.process_one(qpi)
            if batch in pending:
                pending.remove(batch)

        self.cache.update_snapshot(self.snapshot)
        dirty_rows.extend(sd.busy_patch_rows)  # re-encode busy-patched rows
        if invalidated:
            # The carry charged host-diverged placements; staging is the
            # authority again — force a full re-encode + upload.
            self.mirror.invalidate()
            self.metrics.batch_cache_flushed.inc("session_invalidated")
            self._after_flush = True
        else:
            # Keep the device state resident: the final carry reflects every
            # successful placement, so the next flush uploads nothing.
            self.mirror.adopt(self.snapshot.node_info_list, ok_rows,
                              sd.carry.req_r, sd.carry.nonzero,
                              sd.carry.pod_count, dirty_rows=dirty_rows)
            if sd.carry is not None and not dirty_rows:
                self._save_resume(fw, first_batch[0].pod, sig, aux_shape,
                                  sd.state, plan, sd.carry, node_names)
                # Score-hint install (the cross-cycle OpportunisticBatch
                # save): the final host-commit completed cleanly, so the
                # carry IS the kernel's sorted-score truth for the next
                # identical pod — persist it for the host-only bind loop.
                from .score_hints import hint_eligible
                if self._hints.enabled and hint_eligible(
                        plan, self.mesh, aux_shape, first_batch[0].pod,
                        self.extenders, self.queue.nominator,
                        self.cache.affinity_pod_refs):
                    self._hints.install(fw, first_batch[0].pod, sig, nsig,
                                        plan, node_names, sd.carry)
        # The session ran to completion (invalidation included — that is a
        # NORMAL end, not a device failure): a half-open breaker closes.
        self._note_device_success()

    def _commit_batch(self, b, res, fw, node_names, ok_rows, dirty_rows) -> bool:
        """Host tail for one retired batch. Returns True when the session
        must invalidate (host/device divergence or host-path interleaving)."""
        invalidated = False
        for i, qpi in enumerate(b):
            row = int(res[0, i])
            self.next_start_node_index = int(res[1, i])
            if invalidated:
                if row >= 0:
                    dirty_rows.append(row)
                self.host_path_pods += 1
                self.process_one(qpi)
                continue
            if row < 0:
                if self._fail_from_memo(fw, qpi):
                    # Identical pod, identical state, known terminal outcome:
                    # park it with the memoized diagnosis. No state mutated,
                    # so the session carry stays valid — an unschedulable
                    # FLOOD (10k hopeless pods + churn) must not tear down
                    # the measured pods' session per flood pod.
                    continue
                if self._fail_with_vector_diagnosis(fw, qpi):
                    # Exact Diagnosis built from the mirror arrays (numpy)
                    # instead of a 0.3s pure-Python cluster scan; when the
                    # PostFilter made no nomination, no state moved and the
                    # session continues.
                    if qpi.pod.nominated_node_name or qpi.pod.node_name:
                        invalidated = True
                    else:
                        self._memoize_failure(fw, qpi)
                    continue
                # Infeasible on device: rerun on the host path for the exact
                # FitError diagnosis. The host attempt may mutate state
                # (preemption nomination), so the session cannot continue on
                # the chained carry.
                self.host_path_pods += 1
                self.process_one(qpi)
                self._memoize_failure(fw, qpi)
                invalidated = True
                continue
            if self._commit(fw, qpi, node_names[row]):
                ok_rows.append(row)
            else:
                # Host rejected what the device applied in its carry.
                dirty_rows.append(row)
                invalidated = True
        return invalidated

    def _fail_state_key(self, fw: Framework, pod) -> tuple:
        """Everything a scheduling outcome can depend on, versioned: the pod
        spec (signature), priority (no Sign plugin covers it, but PostFilter
        preemption eligibility does — a higher-priority pod with an identical
        signature may succeed where the memoized pod could not), external
        cluster changes, our own binds, and the nomination SET (sessions may
        run WITH a nominated lane; a changed set changes two-pass filter
        outcomes, so the memo keys on Nominator.version)."""
        return (fw.sign_pod(pod), pod.priority, self.cluster_event_seq,
                self.scheduled, self.state_unwinds,
                self.queue.nominator.version)

    def _fail_from_memo(self, fw: Framework, qpi: QueuedPodInfo) -> bool:
        """An identical pod was already host-diagnosed unschedulable against
        this exact state with NO side effects (no nomination, no preemption):
        the rerun would reproduce the same diagnosis, so park the pod from
        the memo. Keeps the device session alive through unschedulable
        floods (Unschedulable/5kNodes perf contract), including floods of
        MULTIPLE alternating signatures (keyed LRU, not a single slot)."""
        memo = self._fail_memo.get(self._fail_state_key(fw, qpi.pod))
        if memo is None:
            return False
        plugins, message = memo
        self.attempts += 1
        qpi.unschedulable_plugins |= plugins
        from ..core.framework import Status
        self.handle_scheduling_failure(fw, qpi, Status.unschedulable(message), None)
        self.queue.done(qpi.pod.uid)
        self.metrics.schedule_attempts.inc("unschedulable", fw.profile_name)
        return True

    def _fail_with_vector_diagnosis(self, fw: Framework, qpi: QueuedPodInfo) -> bool:
        """Build the FitError diagnosis for a device-infeasible pod from the
        mirror's staging arrays and run the standard fit-error tail
        (PostFilter/preemption included). Returns False when the pod's
        feature set needs the exact host rerun (topology features)."""
        import time as _t
        from ..core.framework import CycleState, FitError
        from ..ops.features import diagnose_unschedulable

        if self.queue.nominator.has_nominated_pods():
            # The vectorized diagnosis doesn't model the two-pass nominated
            # filter; the exact host rerun owns the Diagnosis.
            return False
        t0 = _t.perf_counter()
        self.cache.update_snapshot(self.snapshot)
        self.mirror.sync(self.snapshot.node_info_list)
        diag = diagnose_unschedulable(qpi.pod, self.mirror, self.snapshot, fw)
        if diag is None:
            return False
        self.attempts += 1
        fe = FitError(qpi.pod, self.snapshot.num_nodes(), diag)
        self.handle_fit_error(fw, CycleState(), qpi, fe, t0)
        return True

    def _memoize_failure(self, fw: Framework, qpi: QueuedPodInfo) -> None:
        """Record the host diagnosis IF the attempt was terminal and
        side-effect-free (keyed on the post-attempt state). State-moving
        attempts (bind/nomination) change the key components (scheduled /
        cluster_event_seq / nominated flag), so stale entries can never be
        served — eviction is purely a memory bound."""
        pod = qpi.pod
        if pod.node_name or pod.nominated_node_name:
            return  # scheduled after all, or nominated: state moved
        if len(self._fail_memo) >= self._fail_memo_cap:
            self._fail_memo.pop(next(iter(self._fail_memo)))
        self._fail_memo[self._fail_state_key(fw, pod)] = (
            frozenset(qpi.unschedulable_plugins),
            f"0/{self.snapshot.num_nodes()} nodes are available",
        )

    def _commit_fast_eligible(self, fw: Framework) -> bool:
        """True when this profile's commit tail collapses to assume+bind for
        non-gang device pods: every Reserve/PreBind/PostBind plugin acts only
        through CycleState it wrote in PreFilter/Filter (state_driven_tail —
        device pods carry a fresh empty state, so those runs are no-ops by
        construction), Permit plugins act only on gang members, and binding
        goes through the single DefaultBinder."""
        ok = self._fast_tail.get(id(fw))
        if ok is None:
            from ..plugins.basic import DefaultBinder
            ok = (
                all(getattr(p, "state_driven_tail", False)
                    for p in fw.reserve_plugins)
                and all(getattr(p, "state_driven_tail", False)
                        for p in fw.pre_bind_plugins)
                and all(getattr(p, "gang_only", False)
                        for p in fw.permit_plugins)
                and not fw.post_bind_plugins
                and len(fw.bind_plugins) == 1
                and isinstance(fw.bind_plugins[0], DefaultBinder)
            )
            self._fast_tail[id(fw)] = ok
        return ok

    _EMPTY_STATE = None  # shared CycleState for stateless fast commits

    def _commit(self, fw: Framework, qpi: QueuedPodInfo, node_name: str) -> bool:
        """assume → reserve → permit → binding cycle (the unchanged host tail
        of the scheduling cycle, schedule_one.go:315 onward). Returns False
        when the host rejected the placement (carry divergence)."""
        from ..core.framework import CycleState

        pod = qpi.pod
        self.attempts += 1
        dra_state = None
        if getattr(pod, "resource_claims", None):
            dr = fw.plugin("DynamicResources")
            if dr is not None:
                # The kernel decided the NODE via the free-matching-device
                # aux count; the host picks the actual devices by running
                # the plugin's allocation on that one node (the full
                # per-node Filter, restricted to the winner). A miss means
                # the carry diverged from live device state.
                dra_state = CycleState()
                ni = self.snapshot.get(node_name)
                _r, st = dr.pre_filter(dra_state, pod,
                                       [ni] if ni is not None else [])
                if st.is_success() and ni is not None:
                    st = dr.filter(dra_state, pod, ni)
                if ni is None or not st.is_success():
                    self.host_path_pods += 1
                    self.process_one(qpi)
                    return False
        if (dra_state is None and not pod.pod_group and not self.extenders
                and self._commit_fast_eligible(fw)):
            # Lean tail: identical observable semantics to the full path
            # below for this plugin shape (the skipped plugin runs are
            # provably no-ops on an empty CycleState), ~2x cheaper — this
            # runs once per scheduled pod at >13k pods/s.
            if TPUScheduler._EMPTY_STATE is None:
                TPUScheduler._EMPTY_STATE = CycleState()
            pod.node_name = node_name
            self.cache.assume_pod(pod, qpi.pod_info)
            st = fw.bind_plugins[0].bind(
                TPUScheduler._EMPTY_STATE, pod, node_name)
            if st.is_success():
                self.cache.finish_binding(pod)
                nom = self.queue.nominator
                if nom._pod_to_node:
                    nom.delete_nominated_pod(pod)
                self.scheduled += 1
                self.observe_bound(qpi, node_name)
                self.recorder.eventf(
                    pod.namespace + "/" + pod.name, "Normal", "Scheduled",
                    ("Successfully assigned %s/%s to %s",
                     (pod.namespace, pod.name, node_name)))
                self.device_scheduled += 1
                self.queue.done(pod.uid)
                return True
            self._unwind_binding(fw, CycleState(), qpi, node_name, st)
            self.queue.done(pod.uid)
            return False
        state = dra_state if dra_state is not None else CycleState()
        pod.node_name = node_name
        self.cache.assume_pod(pod, qpi.pod_info)
        if fw.reserve_plugins:  # guard: this tail runs once per pod at >10k/s
            st = fw.run_reserve_plugins_reserve(state, pod, node_name)
            if not st.is_success():
                fw.run_reserve_plugins_unreserve(state, pod, node_name)
                self.cache.forget_pod(pod)
                pod.node_name = ""
                self.handle_scheduling_failure(fw, qpi, st, None)
                self.queue.done(pod.uid)
                return False
        st = fw.run_permit_plugins(state, pod, node_name) if fw.permit_plugins \
            else _OK_STATUS
        if st.is_rejected():
            fw.run_reserve_plugins_unreserve(state, pod, node_name)
            self.cache.forget_pod(pod)
            pod.node_name = ""
            self.handle_scheduling_failure(fw, qpi, st, None)
            self.queue.done(pod.uid)
            return False
        if st.code == WAIT:
            # WaitOnPermit (framework.go:2097): park exactly as process_one
            # does — the pod stays assumed on the node, so the device carry
            # remains correct (no divergence).
            self.park_waiting_pod(
                fw, state, qpi, ScheduleResult(suggested_host=node_name))
            self.queue.done(pod.uid)
            # Not counted in device_scheduled yet: the bind outcome is only
            # known when the waiter is allowed/rejected.
            return True
        if not self.run_binding_cycle(fw, state, qpi, ScheduleResult(suggested_host=node_name)):
            self.queue.done(pod.uid)
            return False  # bind failed and unwound
        self.device_scheduled += 1
        self.queue.done(pod.uid)
        return True

    # -- score-hint fast path (models/score_hints.py) ----------------------

    def _try_hint_binds(self) -> int:
        """Bind a run of identical replicas host-side off the live score
        hint — the steady-state execution model for deployment-shaped
        traffic: per pod, a cheap validate (journal replay + counters) and
        the kernel's own selection math in numpy, then the existing commit
        tail (bulk-binding path included). Any miss — signature, validation,
        infeasibility — parks the entity in the holdover slot and returns,
        so the normal batch path owns it. Returns pods bound."""
        hints = self._hints
        if hints.entry is None:
            return 0
        bound = 0
        handled = 0
        while True:
            if bound and bound % 64 == 0:
                # Surface thread-mode async bind errors (409 → per-node
                # hint invalidation) while the loop runs.
                self.process_async_api_errors()
            qpi = self._pop()
            if qpi is None:
                if self._event_inbox:
                    # Concurrent creators park pod-adds in the inbox
                    # (queue-only events): drain so a creation burst does
                    # not end the hint run early — the session refill seam.
                    self.drain_event_inbox()
                    qpi = self._pop()
                if qpi is None:
                    break
            if (isinstance(qpi, (QueuedPodGroupInfo,
                                 QueuedCompositeGroupInfo))
                    or qpi.pod.scheduler_name not in self.profiles):
                self._holdover = qpi
                break
            fw = self.framework_for_pod(qpi.pod)
            _t0 = _time.perf_counter()
            served = hints.serve(fw, qpi.pod)
            if served is None:
                # Misses pay validation too (a stale-entry journal replay
                # is the EXPENSIVE path) — the histogram must see them.
                self.metrics.hint_validation_duration.observe(
                    _time.perf_counter() - _t0)
                self._holdover = qpi
                break
            entry, kind = served
            row, evaluated = entry.select(self.next_start_node_index)
            self.metrics.hint_validation_duration.observe(
                _time.perf_counter() - _t0)
            if row < 0:
                # No feasible node under the hint: the normal path owns the
                # exact diagnosis (FitError / PostFilter) — fall through.
                hints._miss("infeasible")
                self._holdover = qpi
                break
            node = entry.node_names[row]
            committed = self._commit(fw, qpi, node)
            hints.note_own_attempt(node if committed else "", entry)
            handled += 1
            if not committed:
                # A sync 409 already blocked the row via _note_bind_conflict
                # (the pod re-enters through requeue_conflict); any other
                # rejection moved state the next serve() fences. Either way
                # the attempt was hint-path work — report it handled so the
                # surviving hint keeps the NEXT replica off the device.
                break
            entry.apply(row)
            self.next_start_node_index = (
                self.next_start_node_index % entry.num + evaluated) % entry.num
            bound += 1
            if qpi.pod.uid not in self.waiting_pods:
                # Hits count BINDS only. A Permit-WAIT park returns True
                # from _commit with the pod assumed-but-unbound — the
                # walker must apply the placement (it occupies the node),
                # but the hit waits for a real bind (a rejected/expired
                # waiter unwinds through state_unwinds, killing the hint).
                hints._hit(kind)
                if qpi.pod.uid in self.cache.assumed_pods:
                    # Still assumed ⇒ the bind committed OPTIMISTICALLY
                    # (thread-mode dispatcher; an inline clientset confirms
                    # inside _commit and never reaches here). Tag the pod
                    # so a later async 409 takes this hit back — hint_hits
                    # must never exceed pods actually bound, or HintHitRate
                    # reads > 1.0 on exactly the contended runs where it
                    # matters. The tag is dropped at the own-bind confirm
                    # (_note_own_bind_confirm): once settled, a later life
                    # of the same object must not erase a real hit.
                    qpi.pod.__dict__["_hint_bound"] = True
            if hints.entry is not entry:
                break  # invalidated mid-loop (conflict burst)
        return handled

    # -- run loop ----------------------------------------------------------

    def schedule_one(self) -> bool:
        if not self.device_enabled:
            return super().schedule_one()  # TPUBatchScheduling gate off
        if not self.device_breaker.allows():
            # Breaker open: the host Evaluator owns every cycle until the
            # cool-down elapses (then ONE probe session runs half-open).
            # The device path's holdover slot (an entity popped by a session
            # refill but never dispatched) MUST drain here — the host
            # schedule_one only pops the queue and would strand it forever.
            if self._holdover is not None:
                qpi, self._holdover = self._holdover, None
                self.host_path_pods += len(getattr(qpi, "members", ()) or (1,))
                self.process_one(qpi)
                return True
            return super().schedule_one()
        self.process_async_api_errors()
        # Score-hint fast path FIRST: while a fresh hint matches the queue
        # head, identical replicas bind in a host-only loop with zero
        # device dispatches; the first miss falls through to the batch
        # path below (the popped entity waits in the holdover slot).
        if self._hints.entry is not None and self._try_hint_binds():
            return True
        fw, batch, fallback_reason = self._collect_batch()
        if not batch:
            return False
        if fallback_reason is _GANG_SESSION:
            try:
                self.run_gang_device_session(fw, batch[0])
            except Unsupported:
                self.metrics.device_path_fallback.inc("unsupported")
                for qpi in batch:
                    self.host_path_pods += len(getattr(qpi, "members", ()) or (1,))
                    self.process_one(qpi)
            return True
        if fallback_reason is None and len(batch) >= 1:
            pr = self._device_unsupported_profile(fw, batch[0].pod)
            if pr is not None:
                fallback_reason = pr
        if fallback_reason is not None:
            for qpi in batch:
                self.host_path_pods += 1
                self.process_one(qpi)
            return True
        try:
            self.run_device_session(fw, batch)
        except Unsupported:
            self.metrics.device_path_fallback.inc("unsupported")
            for qpi in batch:
                self.host_path_pods += 1
                self.process_one(qpi)
        return True
