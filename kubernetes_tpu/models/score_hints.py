"""Signature-keyed score-hint fast path: bind identical replicas without a
device dispatch.

The reference's opportunistic batching (KEP-5598, framework/runtime/batch.go
OpportunisticBatch) caches the previous cycle's sorted score list keyed by
pod signature so the next identical pod gets a node hint and skips
filter/score entirely. This module is that cache's TPU-era form: when a
device session ends cleanly, the session's final carry — per-node requested
aggregates plus the carried fit/balance score vector, i.e. the kernel's OWN
sorted-score truth — is persisted host-side, keyed by the session's exact
AND namespace-erased neutral signature and stamped with the
cluster_event_seq it reflects. The next identical pod then walks the hinted
score vector entirely on the host: a numpy replica of the kernel's
scores_carried/incremental_feas selection (the only shapes eligible — see
``hint_eligible``) picks the SAME node the kernel would, the pod binds
through the existing commit tail (bulk-binding path included), and the
walker applies the placement to its own row state — a host-only bind loop
with the device reserved for novel signatures.

Exactness contract: hint placements must be bit-identical to the
always-dispatch oracle. That holds because eligibility is restricted to
plans where the kernel itself proves the total score row-local
(``scores_carried``: no spread/IPA/NA-pref normalization, no
PreferNoSchedule counts) and feasibility row-local (``incremental_feas``
with no anti/affinity axes at all — ``BatchPlan.pod_local``), so the walk
is the kernel's scan step with the dead lanes removed: same int64 fit/BA
arithmetic (ops/kernel.py _resource_eval), same adaptive-sampling
truncation and rotation (schedule_one.go:779-892 emulation), same
max-score-then-min-rotation packed selection.

Freshness is event-driven, not TTL-driven (the journal decides which hints
survive — core/cache.py EventJournal):

    event kind          hint survival
    ------------------  ------------------------------------------------
    queue               free (nothing node-side moved)
    namespace           free while no affinity-term pod exists
    pod_add/remove/upd  plain pod: re-encode that ROW from cache truth
                        (and unblock a 409-blocked row); terms: killed
    node_update         re-validate that ROW's taints/alloc/unschedulable
                        (labels/images intact by the kind's contract);
                        a PreferNoSchedule taint kills the hint (the plan
                        compiled the no-PNS fast path). This row is how
                        the node-lifecycle controller's unreachable taint
                        (controllers/node_lifecycle.py NoSchedule ladder
                        step) reaches the fast path: the taint PUT fans a
                        MODIFIED node event, the journal records
                        node_update, and the tainted node's hint row dies
                        here — zero lifecycle-specific device code
    structural/other    killed
    journal gap         killed (anything may have changed)

Out-of-journal state moves are fenced by counters the serve path checks:
any scheduling attempt the walker did not make itself (``attempts``), any
cache unwind (``state_unwinds``), any nomination change
(``Nominator.version``), and the cluster-wide 0→1 affinity-pod transition
(``cache.affinity_pod_refs`` — mirroring the watch plane's selector gate)
all invalidate. A bind-409 invalidates the hinted NODE only: the row is
blocked until the winner's commit re-encodes it through the journal.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from ..api.types import PREFER_NO_SCHEDULE
from ..core.cache import (EV_NAMESPACE, EV_NODE_UPDATE, EV_POD_ADD,
                          EV_POD_REMOVE, EV_POD_UPDATE, EV_QUEUE)
# The kernel's own lap bound: windows of consecutive pods are disjoint
# until the rotation laps the cluster — ONE shared constant, so the host
# walk's batching can never silently diverge from the device lap's.
from ..ops.kernel import LAP_MAX as _LAP_MAX

MAX_NODE_SCORE = 100
_BA_SCALE = 1_000_000


def hint_eligible(plan, mesh, aux_shape, head_pod, extenders,
                  nominator, affinity_pod_refs: int) -> bool:
    """Can a clean session of this shape seed a score hint? Mirrors the
    kernel's scores_carried ∧ incremental_feas preconditions (the walk
    replicates exactly that fast path) plus the host-side state the walk
    does not model: counted claims, extenders, nominated lanes, and any
    live affinity-term pod (cluster-wide disable — the 0→1 transition
    mirrors the watch plane's selector gate). Mesh sessions are eligible
    too (ROADMAP 12d): the install fetches the per-node aggregates/score
    vector from the SHARDED carry via one device→host gather at clean
    session end — sharded and single-device carries are bit-identical
    (integer arithmetic), so the walk stays oracle-exact."""
    del mesh  # sharded carries install through the same gather
    return (plan.pod_local
            and not (plan.has_pns or plan.has_ipa_base or plan.has_na_pref
                     or plan.port_selfblock or plan.has_aux or plan.has_nom)
            and aux_shape == (None, None)
            and not head_pod.volumes
            and not getattr(head_pod, "resource_claims", None)
            and not extenders
            and not nominator.has_nominated_pods()
            and affinity_pod_refs == 0)


class HintEntry:
    """One live hint: the per-node walk state for one pod signature."""

    __slots__ = (
        "keys", "fw_id", "pod", "node_names", "row_of",
        "NP", "num", "to_find",
        # pod-spec facts (ints / small np vectors)
        "request", "nz_request", "has_request", "ba_skip",
        "fit_slots", "fit_weights", "fit_strategy",
        "w_tt", "w_fit", "w_ba", "w_il", "tolerates_unsched", "enable",
        # per-node state (np arrays, entry-owned copies)
        "alloc_r", "alloc_pods", "req_r", "nonzero", "pod_count",
        "static_ok", "fit_ok", "fit_sc", "ba", "total", "ok", "blocked",
        "il_score", "sel_ok", "extra_ok", "name_ok", "valid", "_idx",
        # freshness watermarks
        "seq", "attempts", "unwinds", "nom_version",
        # scalar-slot interning view (read-only; a slot the map lacks
        # cannot affect this plan — its request is zero)
        "scalar_slots",
        # batched-walk state (ROADMAP 12a): precomputed (row, evaluated,
        # expected_start) placements for the rest of the current LAP —
        # adaptive-sampling windows of consecutive pods are disjoint, so
        # one cumsum serves up to total_feas//to_find pods. Any row
        # mutation that is NOT the served head's own apply() clears it.
        "_pending", "lap_enabled", "lap_walks",
    )

    # -- construction -------------------------------------------------------

    @classmethod
    def from_session(cls, sched, fw, head_pod, sig, nsig, plan, node_names,
                     carry) -> "HintEntry":
        """Capture the session's end state. `carry` is the final ScanCarry —
        its req_r/nonzero/pod_count/fit_ok/fit_sc/ba ARE the kernel's
        post-commit truth, so copying them (one device→host fetch) makes
        the walk bit-identical to what the next dispatch would compute."""
        e = cls()
        e.keys = {("exact", sig)}
        if nsig is not None:
            e.keys.add(("neutral", nsig))
        e.fw_id = id(fw)
        e.pod = head_pod
        e.node_names = list(node_names)
        e.row_of = {n: i for i, n in enumerate(node_names)}
        f = plan.features
        mirror = sched.mirror
        e.NP = int(mirror.np_cap)
        e.num = max(int(np.asarray(f.num_nodes)), 1)
        e.to_find = int(np.asarray(f.to_find))
        e._idx = np.arange(e.NP, dtype=np.int64)
        # pod-spec facts
        e.request = np.asarray(f.request).astype(np.int64)
        e.nz_request = np.asarray(f.nz_request).astype(np.int64)
        e.has_request = int(np.asarray(f.has_request))
        e.ba_skip = int(np.asarray(f.ba_skip))
        e.fit_slots = np.asarray(f.fit_slots).astype(np.int64)
        e.fit_weights = np.asarray(f.fit_weights).astype(np.int64)
        e.fit_strategy = int(plan.fit_strategy)
        w = np.asarray(f.weights)
        e.w_tt, e.w_fit, e.w_ba, e.w_il = (
            int(w[0]), int(w[1]), int(w[4]), int(w[6]))
        e.tolerates_unsched = int(np.asarray(f.tolerates_unsched))
        e.enable = tuple(int(x) for x in np.asarray(f.enable))
        e.scalar_slots = mirror.scalar_slots
        # per-node dynamic state: the carry's own arrays (post-commit
        # truth). ONE device→host gather for all six lanes — under a mesh
        # the carry is sharded across chips and per-leaf np.asarray would
        # pay a separate cross-device gather each (ROADMAP 12d).
        import jax
        req_r, nonzero, pod_count, fit_ok, fit_sc, ba = jax.device_get(
            (carry.req_r, carry.nonzero, carry.pod_count,
             carry.fit_ok, carry.fit_sc, carry.ba))
        e.req_r = np.asarray(req_r).astype(np.int64).copy()
        e.nonzero = np.asarray(nonzero).astype(np.int64).copy()
        e.pod_count = np.asarray(pod_count).astype(np.int64).copy()
        e.fit_ok = np.asarray(fit_ok).astype(bool).copy()
        e.fit_sc = np.asarray(fit_sc).astype(np.int64).copy()
        e.ba = np.asarray(ba).astype(np.int64).copy()
        # per-node static state (mirror staging is in line after adopt())
        e.alloc_r = mirror.h_alloc_r.astype(np.int64).copy()
        e.alloc_pods = mirror.h_alloc_pods.astype(np.int64).copy()
        e.il_score = np.asarray(f.il_score).astype(np.int64)
        e.sel_ok = np.asarray(f.sel_match).astype(bool)
        e.extra_ok = np.asarray(f.extra_ok).astype(bool)
        e.valid = mirror.h_valid.copy() & (e._idx < e.num)
        nid = int(np.asarray(f.node_name_id))
        e.name_ok = ((nid == 0) | (mirror.h_name_id == nid)
                     | (e.enable[0] == 0))
        e.static_ok = e.valid & e.name_ok & e.sel_ok_effective() \
            & e.extra_ok & e._taint_unsched_ok(mirror, f)
        e.blocked = np.zeros(e.NP, bool)
        e.total = (e.w_tt * MAX_NODE_SCORE + e.w_fit * e.fit_sc
                   + e.w_ba * e.ba + e.w_il * e.il_score)
        e.ok = e.static_ok & e.fit_ok & ~e.blocked
        # freshness watermarks
        e.seq = sched.cluster_event_seq
        e.attempts = sched.attempts
        e.unwinds = sched.state_unwinds
        e.nom_version = sched.queue.nominator.version
        import os
        e._pending = []
        e.lap_enabled = os.environ.get("TPU_SCHED_HINT_LAP", "1") != "0"
        e.lap_walks = 0
        return e

    def sel_ok_effective(self) -> np.ndarray:
        return self.sel_ok | (self.enable[3] == 0)

    def _taint_unsched_ok(self, mirror, f) -> np.ndarray:
        """Vectorized _static_masks taint + unschedulable verdicts over the
        staging arrays (ops/kernel.py semantics, numpy)."""
        from ..ops.codebook import (EFFECT_NO_EXECUTE, EFFECT_NO_SCHEDULE,
                                    OP_EXISTS)
        tk = np.asarray(f.tol_key)
        tv = np.asarray(f.tol_val)
        te = np.asarray(f.tol_eff)
        to = np.asarray(f.tol_op)
        k = mirror.h_taint_key[:, :, None]
        v = mirror.h_taint_val[:, :, None]
        ef = mirror.h_taint_eff[:, :, None]
        if tk.shape[0]:
            eff_ok = (te[None, None, :] == 0) | (te[None, None, :] == ef)
            key_ok = (tk[None, None, :] == 0) | (tk[None, None, :] == k)
            val_ok = (to[None, None, :] == OP_EXISTS) | (tv[None, None, :] == v)
            tolerated = (eff_ok & key_ok & val_ok).any(axis=2)
        else:
            tolerated = np.zeros(mirror.h_taint_key.shape, bool)
        relevant = ((mirror.h_taint_eff == EFFECT_NO_SCHEDULE)
                    | (mirror.h_taint_eff == EFFECT_NO_EXECUTE))
        taint_ok = ~(relevant & ~tolerated).any(axis=1) | (self.enable[2] == 0)
        unsched_ok = (~mirror.h_unsched | (self.tolerates_unsched == 1)
                      | (self.enable[1] == 0))
        return taint_ok & unsched_ok

    # -- the walk (the kernel's scores_carried scan step, host-side) --------

    def select(self, start: int) -> Tuple[int, int]:
        """One pod's selection against the current walk state: returns
        (row or -1, evaluated) where `evaluated` advances the rotation
        exactly as the kernel's window-boundary reduction does.

        Batched walk (ROADMAP 12a): when adaptive-sampling truncation is
        live (total_feas // to_find >= 2), consecutive pods examine
        DISJOINT windows — the kernel's own lap-vectorization fact
        (ops/kernel.py _lap_schedule) — so ONE cumsum pass segments up to
        a lap of placements and the per-pod cost drops to ~1/L of a full
        walk (the per-pod numpy cumsum over np_cap rows was ~200µs/pod at
        5k nodes). Served placements pop off `_pending`; any row mutation
        other than the served head's own apply() clears it. Bit-exact:
        window w's selection reads only rows later windows never touch."""
        num, NP, to_find = self.num, self.NP, self.to_find
        start = start % num
        if self._pending:
            if self._pending[0][2] == start:
                row, evaluated, _ = self._pending.pop(0)
                return row, evaluated
            self._pending = []  # rotation moved outside the walk: recompute
        ok = self.ok
        F = np.cumsum(ok, dtype=np.int64)
        total_feas = int(F[-1])
        idx = self._idx
        f_start = int(F[start - 1]) if start > 0 else 0
        rank = np.where(idx >= start, F - f_start,
                        F + total_feas - f_start)
        rot = (idx - start) % num
        if total_feas:
            # Lap attempt FIRST: when it serves, the single-pod boundary
            # reduction below is never needed (the lap carries its own
            # per-window evaluated values).
            tf = max(to_find, 1)
            L = min(total_feas // tf, _LAP_MAX)
            if self.lap_enabled and L >= 2:
                got = self._lap_select(start, ok, rank, rot, int(L), tf,
                                       num, NP)
                if got is not None:
                    return got
        boundary = ok & (rank == to_find)
        mx = int(np.max(np.where(boundary, num - 1 - rot, 0))) \
            if NP else 0
        evaluated = num - mx
        if not total_feas:
            return -1, evaluated
        kept = ok & (rank <= to_find)
        key = np.where(kept, self.total * NP + (NP - 1 - rot), -1)
        best = int(key.max())
        if best < 0:
            return -1, evaluated
        chosen_rot = (NP - 1) - (best % NP)
        return (start + chosen_rot) % num, evaluated

    def _lap_select(self, start, ok, rank, rot, L, tf, num, NP):
        """Segment the feasible rotation into L disjoint sampling windows
        (window w = feasible ranks (w·tf, (w+1)·tf]) and pick each
        window's max-score-then-min-rotation key in ONE vectorized pass —
        the numpy restatement of the kernel lap's segmented argmax. Every
        window holds exactly tf feasible rows, so its boundary row
        (rank == (w+1)·tf) exists and the per-window `evaluated` is the
        boundary-to-boundary rotation span, exactly the scan's per-pod
        advance. Returns the first (row, evaluated) and stashes the rest
        on `_pending`, or None to fall back to the single-pod path."""
        key = np.where(ok, self.total * NP + (NP - 1 - rot), -1)
        w = np.zeros_like(rank)
        np.floor_divide(rank - 1, tf, out=w, where=ok)
        sel = ok & (w < L)
        best = np.full(L, -1, np.int64)
        np.maximum.at(best, w[sel], key[sel])
        is_b = ok & (rank % tf == 0) & (rank >= tf) & (rank // tf <= L)
        # Sentinel num+1: a genuine boundary at the LAST rotation slot is
        # ev == num (the scan's evaluated=num full-wrap case) and must be
        # kept; only a truly boundary-less window exceeds it.
        ev_abs = np.full(L, num + 1, np.int64)
        np.minimum.at(ev_abs, rank[is_b] // tf - 1, rot[is_b] + 1)
        entries = []
        cur, prev_abs = start, 0
        for wi in range(L):
            k = int(best[wi])
            if k < 0 or ev_abs[wi] > num:
                break  # defensive: empty / unbounded window ends the lap
            row = (start + (NP - 1 - k % NP)) % num
            entries.append((int(row), int(ev_abs[wi]) - prev_abs, cur))
            prev_abs = int(ev_abs[wi])
            cur = (start + prev_abs) % num
        if not entries:
            return None
        self.lap_walks += 1
        row, evaluated, _ = entries.pop(0)
        self._pending = entries
        return row, evaluated

    def apply(self, row: int) -> None:
        """Commit one placement into the walk state (the scan's carry
        update restricted to the landed row)."""
        self.req_r[row] += self.request
        self.nonzero[row] += self.nz_request
        self.pod_count[row] += 1
        self._reval_row(row)

    # -- row re-evaluation (ops/kernel.py _resource_eval, one row) ----------

    def _reval_row(self, row: int) -> None:
        alloc = self.alloc_r[row]
        pods_ok = int(self.pod_count[row]) + 1 <= int(self.alloc_pods[row])
        avail = alloc - self.req_r[row]
        viol = bool(((self.request > 0) & (self.request > avail)).any())
        fit_ok = ((pods_ok and (not viol or self.has_request == 0))
                  or self.enable[4] == 0)
        used0 = int(self.nonzero[row, 0]) + int(self.nz_request[0])
        used1 = int(self.nonzero[row, 1]) + int(self.nz_request[1])
        num_ = den = 0
        for j in range(self.fit_slots.shape[0]):
            slot = int(self.fit_slots[j])
            wj = int(self.fit_weights[j])
            a = int(alloc[slot])
            if slot == 0:
                used = used0
            elif slot == 1:
                used = used1
            else:
                used = int(self.req_r[row, slot]) + int(self.request[slot])
            if self.fit_strategy == 0:  # LeastAllocated
                rscore = ((a - used) * MAX_NODE_SCORE // max(a, 1)
                          if (a > 0 and used <= a) else 0)
            else:  # MostAllocated
                rscore = (min(used, a) * MAX_NODE_SCORE // max(a, 1)
                          if a > 0 else 0)
            if a > 0:
                num_ += rscore * wj
                den += wj
        fit_sc = num_ // max(den, 1) if den > 0 else 0
        a_cpu, a_mem = int(alloc[0]), int(alloc[1])
        q_cpu = min(used0 * _BA_SCALE // max(a_cpu, 1), _BA_SCALE)
        q_mem = min(used1 * _BA_SCALE // max(a_mem, 1), _BA_SCALE)
        if self.ba_skip == 1:
            ba = 0
        elif a_cpu > 0 and a_mem > 0:
            ba = (MAX_NODE_SCORE * _BA_SCALE
                  - 50 * abs(q_cpu - q_mem)) // _BA_SCALE
        else:
            ba = MAX_NODE_SCORE
        self.fit_ok[row] = fit_ok
        self.fit_sc[row] = fit_sc
        self.ba[row] = ba
        self.total[row] = (self.w_tt * MAX_NODE_SCORE + self.w_fit * fit_sc
                           + self.w_ba * ba + self.w_il * int(self.il_score[row]))
        self.ok[row] = (bool(self.static_ok[row]) and fit_ok
                        and not self.blocked[row])

    # -- event-driven freshness (the journal replay) ------------------------

    def block_row(self, node: str) -> bool:
        """Bind-409: the hint's view of this node understates committed
        usage — exclude the row until a journal pod event re-encodes it
        from cache truth (the winner's commit arrives as exactly that)."""
        row = self.row_of.get(node)
        if row is None:
            return False
        self.blocked[row] = True
        self.ok[row] = False
        self._pending = []  # feasibility shrank outside the walk
        return True

    def _resource_vec(self, r) -> np.ndarray:
        """Entry-width resource vector. Scalar resources the interning map
        lacks are ignored: this plan's request for them is zero by
        construction, so they cannot move its fit filter or scores."""
        out = np.zeros(self.req_r.shape[1], np.int64)
        out[0] = r.milli_cpu
        out[1] = r.memory
        out[2] = r.ephemeral_storage
        for name, amount in r.scalar_resources.items():
            slot = self.scalar_slots.get(name)
            if slot is not None and slot < out.shape[0]:
                out[slot] = amount
        return out

    def _reencode_pod_row(self, cache, key: str,
                          unblock: bool = True) -> Optional[str]:
        row = self.row_of.get(key)
        ni = cache.nodes.get(key)
        if row is None or ni is None or ni.node is None:
            return "structural"  # row set changed shape after all
        self.req_r[row] = self._resource_vec(ni.requested)
        self.nonzero[row, 0] = ni.non_zero_requested.milli_cpu
        self.nonzero[row, 1] = ni.non_zero_requested.memory
        self.pod_count[row] = len(ni.pods)
        if unblock:
            # Journal truth (the 409 winner's commit arrives as exactly
            # this event) releases a conflict block. A SIBLING entry's own
            # bind (note_own_attempt cross-feed) must NOT: the winner's
            # watch copy may not have landed in the cache yet, so the row
            # would understate committed usage all over again.
            self.blocked[row] = False
        self._reval_row(row)
        self._pending = []  # a row moved outside the walk: re-segment
        return None

    def resync_rows(self, cache) -> Optional[str]:
        """Re-encode EVERY row's dynamic pod state from cache truth: a
        device session this entry did not watch just committed placements
        (own binds are journal-benign, so there is no event stream to
        replay). One full pass of the journal pod re-encode, blocked rows
        kept blocked. O(rows) host work — paid once per install, only when
        a sibling entry survives, never on the single-shape steady state."""
        for name in self.node_names:
            reason = self._reencode_pod_row(cache, name, unblock=False)
            if reason:
                return reason
        return None

    def _revalidate_node_row(self, cache, key: str) -> Optional[str]:
        """EV_NODE_UPDATE: taints/allocatable/unschedulable moved on one
        row (labels/images/declared-features intact by the event kind's
        contract, so sel/extra/name verdicts stay valid)."""
        row = self.row_of.get(key)
        ni = cache.nodes.get(key)
        if row is None or ni is None or ni.node is None:
            return "structural"
        node = ni.node
        if any(t.effect == PREFER_NO_SCHEDULE for t in node.taints):
            # The plan compiled the no-PNS fast path (has_pns=False); the
            # oracle would now score PreferNoSchedule counts.
            return "pns_taint"
        tols = self.pod.tolerations
        taint_ok = (self.enable[2] == 0) or all(
            any(tol.tolerates(t) for tol in tols)
            for t in node.taints if t.effect != PREFER_NO_SCHEDULE)
        unsched_ok = ((not node.unschedulable)
                      or self.tolerates_unsched == 1
                      or self.enable[1] == 0)
        self.static_ok[row] = (bool(self.valid[row])
                               and bool(self.name_ok[row])
                               and bool(self.sel_ok_effective()[row])
                               and bool(self.extra_ok[row])
                               and taint_ok and unsched_ok)
        self.alloc_r[row] = self._resource_vec(ni.allocatable)
        self.alloc_pods[row] = ni.allocatable.allowed_pod_number
        self._reval_row(row)
        self._pending = []  # a row moved outside the walk: re-segment
        return None

    def consume(self, sched, events) -> Optional[str]:
        """Replay journal events into the walk state. Returns None when the
        hint survives (rows patched as needed) or the invalidation reason."""
        cache = sched.cache
        for ev in events:
            if ev.kind == EV_QUEUE:
                continue
            if ev.kind == EV_NAMESPACE:
                if cache.affinity_pod_refs == 0:
                    continue  # namespace labels feed only affinity selectors
                return "namespace"
            if ev.kind in (EV_POD_ADD, EV_POD_REMOVE, EV_POD_UPDATE):
                if not ev.pod_plain:
                    return "pod_terms"
                reason = self._reencode_pod_row(cache, ev.key)
                if reason:
                    return reason
            elif ev.kind == EV_NODE_UPDATE:
                reason = self._revalidate_node_row(cache, ev.key)
                if reason:
                    return reason
            else:
                return ev.kind  # structural / other
        return None


class ScoreHintCache:
    """The scheduler's live hints + serve/install/invalidate protocol.
    Counters live on the scheduler (WINDOW_COUNTERS surface); labeled
    series on its SchedulerMetrics.

    The cache is a small signature-keyed LRU (``TPU_SCHED_HINT_LRU``
    slots, default 2, MRU first): alternating deployment waves — two
    replica shapes interleaving through one queue — keep BOTH shapes on
    the host path instead of thrashing a single slot. ``=1`` is the A/B
    seam back to the historical single-entry behavior. Coherence across
    entries is push-based, not journal-based, because own binds are
    deliberately journal-benign: every own attempt bumps EVERY live
    entry's attempt watermark, and a committed bind re-encodes the landed
    node's row on the non-serving entries from cache truth
    (``note_own_attempt``), so a sibling's placements can never make an
    entry serve a stale row."""

    def __init__(self, sched, enabled: bool = True):
        import os
        self.sched = sched
        self.enabled = enabled
        self.capacity = max(1, int(os.environ.get("TPU_SCHED_HINT_LRU",
                                                  "2") or 2))
        self.entries: list = []  # HintEntry, MRU first

    @property
    def entry(self) -> Optional[HintEntry]:
        """The MRU entry or None — the 'is a hint live at all' view the
        scheduler's fast-path gates read."""
        return self.entries[0] if self.entries else None

    @entry.setter
    def entry(self, value: Optional[HintEntry]) -> None:
        self.entries = [] if value is None else [value]

    # -- counters -----------------------------------------------------------

    def _miss(self, reason: str) -> None:
        self.sched.hint_misses += 1
        self.sched.metrics.hint_cache_misses.inc(reason)

    def _hit(self, kind: str) -> None:
        self.sched.hint_hits += 1
        self.sched.metrics.hint_cache_hits.inc(kind)

    def _drop(self, e: HintEntry, reason: str) -> None:
        self.entries.remove(e)
        self.sched.hint_invalidations += 1
        self.sched.metrics.hint_cache_invalidations.inc(reason)

    def invalidate(self, reason: str) -> None:
        while self.entries:
            self._drop(self.entries[-1], reason)

    # -- lifecycle ----------------------------------------------------------

    def install(self, fw, head_pod, sig, nsig, plan, node_names,
                carry) -> None:
        if not self.enabled:
            return
        e = HintEntry.from_session(
            self.sched, fw, head_pod, sig, nsig, plan, node_names, carry)
        # Same-signature slots are superseded in place (the fresh carry IS
        # the newer truth for that shape); a genuinely new shape pushes the
        # coldest entry out. Surviving siblings ABSORB the device session
        # that just ended — its attempts bump and its committed placements
        # (re-encoded from cache truth) — or the attempts fence would read
        # every sibling as foreign next serve and alternating shapes would
        # thrash the cache one install per pod. unwinds/nomination fences
        # are deliberately NOT absorbed: a session that moved those leaves
        # the sibling stale, and the fence catches it.
        kept = []
        for x in self.entries:
            if x.keys & e.keys:
                continue
            x.attempts = self.sched.attempts
            if x.resync_rows(self.sched.cache) is None:
                kept.append(x)
            else:
                self.sched.hint_invalidations += 1
                self.sched.metrics.hint_cache_invalidations.inc(
                    "cross_reencode")
        self.entries = [e] + kept
        while len(self.entries) > self.capacity:
            self._drop(self.entries[-1], "lru_evict")

    def note_conflict(self, node: str) -> None:
        """Bind-409 on `node`: invalidate EVERY entry's view of that node
        ONLY. The conflict's unwind (forget_pod) is absorbed — its entire
        effect is on the blocked rows, which re-encode from cache truth
        when the winner's commit lands through the journal. An entry whose
        row set does not cover the node cannot absorb and is dropped."""
        for e in list(self.entries):
            if e.block_row(node):
                e.unwinds += 1
                self.sched.hint_invalidations += 1
                self.sched.metrics.hint_cache_invalidations.inc(
                    "bind_conflict")
            else:
                self._drop(e, "bind_conflict")

    def note_own_attempt(self, node: str = "",
                         served: Optional[HintEntry] = None) -> None:
        """One walker attempt just ran: absorb the scheduler attempt-
        counter bump on EVERY live entry (all watermarks stay current —
        without this, one entry serving would read as a foreign attempt to
        its siblings and evict them). A committed bind passes the landed
        `node`: non-serving entries re-encode that row from cache truth
        (the assumed pod is already in it) WITHOUT unblocking — a 409
        block must outlive a sibling's bind. A failed attempt passes
        node="" (the 409 path already blocked the row via note_conflict)."""
        if not self.entries:
            return
        cache = self.sched.cache
        for e in list(self.entries):
            e.attempts += 1
            if e is served or not node:
                continue
            if e._reencode_pod_row(cache, node, unblock=False) is not None:
                # The sibling's row set does not cover the landed node —
                # its world no longer matches the cluster's shape.
                self._drop(e, "cross_reencode")

    # -- serve --------------------------------------------------------------

    def serve(self, fw, pod) -> Optional[Tuple[HintEntry, str]]:
        """Validate the signature-matched entry against `pod` and the
        world; returns (entry, hit kind) when the hint path may bind this
        pod, else None (counted as a miss; stale entries are dropped +
        counted as invalidations). A served entry moves to the LRU head."""
        if not self.enabled:
            # The A/B seam (`_hints.enabled = False` /
            # TPU_SCHED_SCORE_HINTS=0) must hold on a WARM scheduler too:
            # live entries installed before the flip may not keep serving,
            # or the dispatch-only baseline is silently invalid.
            self.entries = []
            return None
        s = self.sched
        if not self.entries:
            self._miss("empty")
            return None
        if s.cache.affinity_pod_refs:
            # 0→1 affinity-pod transition: hints disabled cluster-wide
            # (labels/namespaces just became scheduling-relevant).
            self.invalidate("affinity_transition")
            self._miss("affinity_gate")
            return None
        sig = fw.sign_pod(pod)
        if sig is None:
            self._miss("unsignable")
            return None
        same_fw = [x for x in self.entries if id(fw) == x.fw_id]
        if not same_fw:
            self._miss("profile")
            return None
        # Exact key beats neutral ACROSS entries (single-entry semantics —
        # both keys lived on one entry — carried to the LRU); MRU order
        # breaks ties within a kind.
        e = kind = None
        for x in same_fw:
            if ("exact", sig) in x.keys:
                e, kind = x, "exact"
                break
        if e is None:
            nsig = s._neutral_sig(fw, pod, sig)
            for x in same_fw:
                if nsig is not None and ("neutral", nsig) in x.keys:
                    e, kind = x, "neutral"
                    break
        if e is None:
            self._miss("signature")
            return None
        if pod.volumes or getattr(pod, "resource_claims", None):
            self._miss("claims")
            return None
        if s._batch_supported_memo(pod, fw) is not None:
            self._miss("unsupported")
            return None
        if s.extenders and any(x.is_interested(pod) for x in s.extenders):
            self._miss("extender")
            return None
        if s.queue.nominator.version != e.nom_version:
            self._drop(e, "nomination")
            self._miss("stale")
            return None
        if s.attempts != e.attempts:
            # A scheduling attempt the walker did not make (host path,
            # device session, fall-through) moved cache state the journal
            # does not record (own binds are deliberately benign there —
            # sibling-entry serves are absorbed by note_own_attempt, so
            # only a genuinely foreign attempt lands here).
            self._drop(e, "foreign_attempt")
            self._miss("stale")
            return None
        if s.state_unwinds != e.unwinds:
            self._drop(e, "state_unwind")
            self._miss("stale")
            return None
        if s.cluster_event_seq != e.seq:
            events = s.journal.since(e.seq)
            if events is None:
                self._drop(e, "journal_gap")
                self._miss("stale")
                return None
            reason = e.consume(s, events)
            if reason is not None:
                self._drop(e, reason)
                self._miss("stale")
                return None
            e.seq = s.cluster_event_seq
        if self.entries[0] is not e:
            self.entries.remove(e)
            self.entries.insert(0, e)
        return e, kind
