from .wrappers import MakeNode, MakePod, make_node, make_pod

__all__ = ["MakeNode", "MakePod", "make_node", "make_pod"]
