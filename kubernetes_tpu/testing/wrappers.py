"""Fluent Pod/Node builders — the pkg/scheduler/testing/wrappers.go analogue
(st.MakePod().Name("p").Req(...).Obj() style)."""

from __future__ import annotations

from typing import Dict, Optional, Sequence

from ..api.labels import IN, LabelSelector, Requirement
from ..api.resource import Resource
from ..api.types import (
    Affinity,
    Container,
    ContainerPort,
    DO_NOT_SCHEDULE,
    ImageState,
    Node,
    NodeAffinity,
    NodeSelector,
    NodeSelectorTerm,
    Pod,
    PodAffinity,
    PodAffinityTerm,
    PodAntiAffinity,
    PreferredSchedulingTerm,
    Taint,
    Toleration,
    TopologySpreadConstraint,
    WeightedPodAffinityTerm,
)


class MakePod:
    def __init__(self):
        self._pod = Pod(name="pod", containers=[Container(name="c")])

    def name(self, n: str) -> "MakePod":
        self._pod.name = n
        return self

    def namespace(self, ns: str) -> "MakePod":
        self._pod.namespace = ns
        return self

    def uid(self, uid: str) -> "MakePod":
        self._pod.uid = uid
        return self

    def label(self, k: str, v: str) -> "MakePod":
        self._pod.labels[k] = v
        return self

    def labels(self, m: Dict[str, str]) -> "MakePod":
        self._pod.labels.update(m)
        return self

    def req(self, requests: Dict[str, object]) -> "MakePod":
        self._pod.containers[0].requests = Resource.from_map(requests)
        return self

    def container_req(self, requests: Dict[str, object]) -> "MakePod":
        self._pod.containers.append(Container(name=f"c{len(self._pod.containers)}",
                                              requests=Resource.from_map(requests)))
        return self

    def init_req(self, requests: Dict[str, object], sidecar: bool = False) -> "MakePod":
        self._pod.init_containers.append(Container(
            name=f"i{len(self._pod.init_containers)}",
            requests=Resource.from_map(requests),
            restart_policy="Always" if sidecar else None,
        ))
        return self

    def overhead(self, requests: Dict[str, object]) -> "MakePod":
        self._pod.overhead = Resource.from_map(requests)
        return self

    def image(self, img: str) -> "MakePod":
        self._pod.containers[0].image = img
        return self

    def node(self, name: str) -> "MakePod":
        self._pod.node_name = name
        return self

    def priority(self, p: int) -> "MakePod":
        self._pod.priority = p
        return self

    def scheduler_name(self, n: str) -> "MakePod":
        self._pod.scheduler_name = n
        return self

    def node_selector(self, sel: Dict[str, str]) -> "MakePod":
        self._pod.node_selector.update(sel)
        return self

    def toleration(self, key: str, value: str = "", operator: str = "Equal",
                   effect: str = "") -> "MakePod":
        self._pod.tolerations.append(Toleration(key=key, operator=operator, value=value, effect=effect))
        return self

    def host_port(self, port: int, protocol: str = "TCP", host_ip: str = "") -> "MakePod":
        ports = self._pod.containers[0].ports + (ContainerPort(host_port=port, protocol=protocol, host_ip=host_ip),)
        self._pod.containers[0].ports = ports
        return self

    def scheduling_gate(self, name: str) -> "MakePod":
        self._pod.scheduling_gates.append(name)
        return self

    def nominated_node(self, name: str) -> "MakePod":
        self._pod.nominated_node_name = name
        return self

    def _affinity(self) -> Affinity:
        if self._pod.affinity is None:
            self._pod.affinity = Affinity()
        return self._pod.affinity

    def node_affinity_in(self, key: str, values: Sequence[str]) -> "MakePod":
        term = NodeSelectorTerm(match_expressions=(Requirement(key, IN, tuple(values)),))
        a = self._affinity()
        existing = a.node_affinity.required.terms if a.node_affinity and a.node_affinity.required else ()
        self._pod.affinity = Affinity(
            node_affinity=NodeAffinity(required=NodeSelector(existing + (term,)),
                                       preferred=a.node_affinity.preferred if a.node_affinity else ()),
            pod_affinity=a.pod_affinity,
            pod_anti_affinity=a.pod_anti_affinity,
        )
        return self

    def preferred_node_affinity(self, weight: int, key: str, values: Sequence[str]) -> "MakePod":
        term = PreferredSchedulingTerm(
            weight=weight,
            preference=NodeSelectorTerm(match_expressions=(Requirement(key, IN, tuple(values)),)),
        )
        a = self._affinity()
        na = a.node_affinity or NodeAffinity()
        self._pod.affinity = Affinity(
            node_affinity=NodeAffinity(required=na.required, preferred=na.preferred + (term,)),
            pod_affinity=a.pod_affinity,
            pod_anti_affinity=a.pod_anti_affinity,
        )
        return self

    def node_affinity_name(self, node_name: str) -> "MakePod":
        """Required affinity pinning metadata.name via matchFields
        (templates/daemonset-pod.yaml shape)."""
        term = NodeSelectorTerm(match_fields=(
            Requirement("metadata.name", IN, (node_name,)),))
        a = self._affinity()
        existing = a.node_affinity.required.terms if a.node_affinity and a.node_affinity.required else ()
        self._pod.affinity = Affinity(
            node_affinity=NodeAffinity(required=NodeSelector(existing + (term,)),
                                       preferred=a.node_affinity.preferred if a.node_affinity else ()),
            pod_affinity=a.pod_affinity,
            pod_anti_affinity=a.pod_anti_affinity,
        )
        return self

    def pod_affinity(self, topology_key: str, match_labels: Dict[str, str],
                     anti: bool = False, weight: int = 0,
                     ns_labels: Optional[Dict[str, str]] = None) -> "MakePod":
        term = PodAffinityTerm(
            label_selector=LabelSelector.of(match_labels=match_labels),
            topology_key=topology_key,
            namespace_selector=(LabelSelector.of(match_labels=dict(ns_labels))
                                if ns_labels is not None else None),
        )
        a = self._affinity()
        pa = a.pod_affinity or PodAffinity()
        paa = a.pod_anti_affinity or PodAntiAffinity()
        if weight > 0:
            wterm = WeightedPodAffinityTerm(weight=weight, term=term)
            if anti:
                paa = PodAntiAffinity(required=paa.required, preferred=paa.preferred + (wterm,))
            else:
                pa = PodAffinity(required=pa.required, preferred=pa.preferred + (wterm,))
        else:
            if anti:
                paa = PodAntiAffinity(required=paa.required + (term,), preferred=paa.preferred)
            else:
                pa = PodAffinity(required=pa.required + (term,), preferred=pa.preferred)
        self._pod.affinity = Affinity(node_affinity=a.node_affinity, pod_affinity=pa, pod_anti_affinity=paa)
        return self

    def spread_constraint(self, max_skew: int, topology_key: str,
                          when_unsatisfiable: str = DO_NOT_SCHEDULE,
                          match_labels: Optional[Dict[str, str]] = None,
                          min_domains: Optional[int] = None,
                          node_affinity_policy: str = "Honor",
                          node_taints_policy: str = "Ignore") -> "MakePod":
        self._pod.topology_spread_constraints.append(TopologySpreadConstraint(
            max_skew=max_skew,
            topology_key=topology_key,
            when_unsatisfiable=when_unsatisfiable,
            label_selector=LabelSelector.of(match_labels=match_labels or {}),
            min_domains=min_domains,
            node_affinity_policy=node_affinity_policy,
            node_taints_policy=node_taints_policy,
        ))
        return self

    def obj(self) -> Pod:
        return self._pod


class MakeNode:
    def __init__(self):
        self._node = Node(name="node")

    def name(self, n: str) -> "MakeNode":
        self._node.name = n
        self._node.labels["kubernetes.io/hostname"] = n
        return self

    def label(self, k: str, v: str) -> "MakeNode":
        self._node.labels[k] = v
        return self

    def capacity(self, m: Dict[str, object]) -> "MakeNode":
        self._node.capacity = Resource.from_map(m)
        self._node.allocatable = Resource.from_map(m)
        if self._node.allocatable.allowed_pod_number == 0:
            self._node.allocatable.allowed_pod_number = 110
        return self

    def allocatable(self, m: Dict[str, object]) -> "MakeNode":
        self._node.allocatable = Resource.from_map(m)
        return self

    def taint(self, key: str, value: str = "", effect: str = "NoSchedule") -> "MakeNode":
        self._node.taints.append(Taint(key=key, value=value, effect=effect))
        return self

    def unschedulable(self, v: bool = True) -> "MakeNode":
        self._node.unschedulable = v
        return self

    def image(self, name: str, size_bytes: int) -> "MakeNode":
        self._node.images.append(ImageState(names=(name,), size_bytes=size_bytes))
        return self

    def zone(self, z: str) -> "MakeNode":
        self._node.labels["topology.kubernetes.io/zone"] = z
        return self

    def obj(self) -> Node:
        return self._node


def make_pod() -> MakePod:
    return MakePod()


def make_node() -> MakeNode:
    return MakeNode()
