"""Runtime lock-order watchdog: the dynamic half of the lock-discipline
story (static half: ``kubernetes_tpu.analysis.lock_discipline``).

Wrap the locks of a component under test (``LockWatch.wrap`` /
``instrument``) and run a workload; the watch records, per thread, the
acquisition-order graph — an edge A→B for every acquisition of B while A
is held, stamped with the source sites of both acquisitions. After the
run:

- ``cycles()`` reports lock-order cycles (ABBA and longer): two threads
  that ever take the same pair of locks in opposite orders can deadlock
  under the right interleaving, even if the test run happened not to —
  this is the class a chaos run cannot reliably reproduce but a
  lock-order graph catches every time;
- ``long_holds`` reports holds that exceeded the threshold (a lock held
  across a blocking call starves every other acquirer — the PR 2 incident
  that moved request-body reads outside the apiserver write lock);
- ``assert_no_cycles()`` is the chaos-suite assertion seam.

The wrapper is deliberately thin (one monotonic read + dict work per
acquire/release) so instrumented chaos runs stay representative.
"""

from __future__ import annotations

import sys
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Set, Tuple

_THIS_FILE = __file__


def _call_site(depth_limit: int = 12) -> str:
    """file:line of the nearest caller frame outside this module."""
    f = sys._getframe(2)
    for _ in range(depth_limit):
        if f is None:
            break
        fname = f.f_code.co_filename
        if fname != _THIS_FILE and "threading" not in fname:
            return f"{fname.rsplit('/', 1)[-1]}:{f.f_lineno}"
        f = f.f_back
    return "<unknown>"


@dataclass
class LongHold:
    lock: str
    seconds: float
    acquire_site: str
    release_site: str


@dataclass
class Cycle:
    """A lock-order cycle: ``locks`` in cycle order; ``sites`` holds one
    recorded (holding, acquiring, held_at, acquired_at) witness per edge —
    for an ABBA pair that is exactly the two sites to fix."""
    locks: Tuple[str, ...]
    sites: Tuple[Tuple[str, str, str, str], ...]

    def __str__(self) -> str:
        arrows = " -> ".join(self.locks + (self.locks[0],))
        edges = "; ".join(
            f"{a}(held@{ha}) then {b}(acquired@{hb})"
            for a, b, ha, hb in self.sites)
        return f"lock-order cycle {arrows}: {edges}"


class WatchedLock:
    """Drop-in wrapper for Lock/RLock: context manager + acquire/release/
    locked, reporting to its LockWatch. RLock re-entry is not re-recorded
    as a new hold (no self-edge noise)."""

    def __init__(self, inner, name: str, watch: "LockWatch"):
        self._inner = inner
        self.name = name
        self._watch = watch

    def acquire(self, blocking: bool = True, timeout: float = -1):
        got = (self._inner.acquire(blocking, timeout) if timeout != -1
               else self._inner.acquire(blocking))
        if got:
            self._watch._on_acquire(self, _call_site())
        return got

    def release(self) -> None:
        self._watch._on_release(self, _call_site())
        self._inner.release()

    def locked(self) -> bool:
        try:
            return self._inner.locked()
        except AttributeError:  # RLock has no locked() pre-3.12
            return False

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()


class LockWatch:
    """Records the acquisition-order graph across every lock it wraps."""

    def __init__(self, hold_threshold: float = 0.05):
        self.hold_threshold = hold_threshold
        self._tl = threading.local()
        self._mu = threading.Lock()  # guards the shared graph/report state
        # edge (a, b) -> witness sites (holding_site, acquiring_site)
        self.edges: Dict[Tuple[str, str], Tuple[str, str]] = {}
        self.long_holds: List[LongHold] = []
        self.acquisitions = 0

    # -- instrumentation ----------------------------------------------------

    def wrap(self, lock, name: str) -> WatchedLock:
        return WatchedLock(lock, name, self)

    def instrument(self, obj, *attrs: str, prefix: str = "") -> None:
        """Replace ``obj.<attr>`` locks with watched wrappers in place:
        ``watch.instrument(api, "_lock", "_write_lock", prefix="api")``."""
        for attr in attrs:
            inner = getattr(obj, attr)
            label = f"{prefix or type(obj).__name__}.{attr}"
            setattr(obj, attr, self.wrap(inner, label))

    # -- recording ----------------------------------------------------------

    def _held(self) -> List[Tuple[str, str, float]]:
        held = getattr(self._tl, "held", None)
        if held is None:
            held = self._tl.held = []
        return held

    def _on_acquire(self, lock: WatchedLock, site: str) -> None:
        held = self._held()
        now = time.monotonic()
        if any(name == lock.name for name, _, _ in held):
            return  # RLock re-entry
        with self._mu:
            self.acquisitions += 1
            for prior_name, prior_site, _ in held:
                self.edges.setdefault((prior_name, lock.name),
                                      (prior_site, site))
        held.append((lock.name, site, now))

    def _on_release(self, lock: WatchedLock, site: str) -> None:
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            name, acq_site, t0 = held[i]
            if name == lock.name:
                del held[i]
                dt = time.monotonic() - t0
                if dt >= self.hold_threshold:
                    with self._mu:
                        self.long_holds.append(
                            LongHold(lock.name, dt, acq_site, site))
                return

    # -- reporting ----------------------------------------------------------

    def cycles(self) -> List[Cycle]:
        """Every elementary cycle in the acquisition-order graph (DFS over
        the recorded edges; ABBA pairs come out as 2-cycles)."""
        with self._mu:
            edges = dict(self.edges)
        graph: Dict[str, Set[str]] = {}
        for a, b in edges:
            graph.setdefault(a, set()).add(b)
        out: List[Cycle] = []
        seen_cycles: Set[Tuple[str, ...]] = set()

        def dfs(start: str, node: str, path: List[str],
                on_path: Set[str]) -> None:
            for nxt in sorted(graph.get(node, ())):
                if nxt == start and len(path) >= 2:
                    # canonical rotation so each cycle reports once
                    i = path.index(min(path))
                    canon = tuple(path[i:] + path[:i])
                    if canon in seen_cycles:
                        continue
                    seen_cycles.add(canon)
                    sites = tuple(
                        (a, b) + edges[(a, b)]
                        for a, b in zip(path, path[1:] + [path[0]]))
                    out.append(Cycle(tuple(path), sites))
                elif nxt not in on_path and nxt > start:
                    # only expand nodes > start: each cycle found exactly
                    # from its smallest member
                    dfs(start, nxt, path + [nxt], on_path | {nxt})

        for start in sorted(graph):
            dfs(start, start, [start], {start})
        return out

    def assert_no_cycles(self) -> None:
        cycles = self.cycles()
        if cycles:
            raise AssertionError(
                "lock-order cycles detected (potential deadlock):\n"
                + "\n".join(str(c) for c in cycles))
