"""Fault injection for the control-plane and device boundaries.

The chaos layer the resilience subsystem (docs/RESILIENCE.md) is tested
against. Three seams, matching the process boundaries the production
deployment has:

- ``FlakyClientset`` — wraps any clientset and makes WRITE verbs raise
  retriable :class:`~..core.backoff.TransientAPIError` (5xx/timeout
  analogue) on a deterministic seeded schedule. Reads and informer
  registration pass through untouched. Pair with ``RetryingClientset``
  (core/clientset.py) to prove write-path retries.

- ``ChaosTCPProxy`` — a byte-pump TCP proxy in front of the REST+watch
  apiserver (core/apiserver.py). ``drop_connections()`` resets every live
  connection mid-stream (the dropped-watch / connection-reset fault);
  ``delay`` slows responses. The reflector's resourceVersion re-list runs
  against exactly this.

- ``DeviceFaults`` — installed as ``TPUScheduler._fault_hook``; raises a
  configured exception on the Nth device kernel boundary crossing
  (``dispatch`` / ``preempt``), driving the device→host fallback and the
  circuit breaker.

- ``ApiServerProcess`` — a real-OS-process apiserver under chaos control:
  spawn with a durable data dir (WAL+snapshot, core/wal.py), ``kill9()``
  (SIGKILL — no goodbye, no flush), ``restart()`` in place on the SAME
  port + data dir. The crash-restart fault the durability layer and the
  scheduler's post-restart reconciliation are tested against.

Sidecar process kill rides ``SidecarServer.kill()`` (parallel/sidecar.py):
an abrupt listener+connection teardown, distinct from graceful shutdown.

Everything is deterministically seeded: a chaos test that fails replays
byte-for-byte from its seed.
"""

from __future__ import annotations

import os
import random
import re
import signal
import socket
import struct
import subprocess
import sys
import threading
import time
from typing import Dict, Iterable, Optional

from ..core.backoff import TransientAPIError

# Clientset write verbs the chaos layer may afflict (the API-mutation
# surface the scheduler exercises).
WRITE_VERBS = (
    "create_pod", "update_pod", "delete_pod", "bind", "patch_pod_status",
    "create_node", "update_node", "delete_node", "evict_pod",
)


class FlakyClientset:
    """Deterministic write-fault decorator over any clientset.

    ``fail_first`` maps verb -> how many leading calls of that verb raise;
    ``failure_rate`` additionally fails each write call with the given
    seeded probability. Injected failures raise BEFORE the inner verb runs
    (the write never lands — a replay is required, like a request that
    died on the wire). ``injected`` counts faults by verb for assertions.
    """

    def __init__(self, inner, seed: int = 0, failure_rate: float = 0.0,
                 fail_first: Optional[Dict[str, int]] = None,
                 exc_factory=TransientAPIError):
        self._inner = inner
        self._rng = random.Random(seed)
        self._rate = failure_rate
        self._fail_first = dict(fail_first or {})
        self._exc_factory = exc_factory
        self.injected: Dict[str, int] = {}

    def _maybe_fail(self, verb: str) -> None:
        remaining = self._fail_first.get(verb, 0)
        if remaining > 0:
            self._fail_first[verb] = remaining - 1
        elif not (self._rate and self._rng.random() < self._rate):
            return
        self.injected[verb] = self.injected.get(verb, 0) + 1
        raise self._exc_factory(f"injected fault on {verb}")

    def __getattr__(self, name):
        attr = getattr(self._inner, name)
        if name in WRITE_VERBS:
            def flaky(*args, _attr=attr, _verb=name, **kwargs):
                self._maybe_fail(_verb)
                return _attr(*args, **kwargs)
            return flaky
        return attr


class ChaosTCPProxy:
    """TCP byte pump with a kill switch, for resetting watch streams and
    in-flight requests between a client and the apiserver."""

    def __init__(self, upstream_host: str, upstream_port: int,
                 delay: float = 0.0):
        self.upstream = (upstream_host, upstream_port)
        self.delay = delay
        self._conns: set = set()
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self.drops = 0  # connections reset by drop_connections()
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind(("127.0.0.1", 0))
        self._listener.listen(64)
        self.port = self._listener.getsockname()[1]
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="chaos-proxy-accept", daemon=True)
        self._accept_thread.start()

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                client, _ = self._listener.accept()
            except OSError:
                return
            try:
                server = socket.create_connection(self.upstream, timeout=10)
            except OSError:
                client.close()
                continue
            with self._lock:
                self._conns.add(client)
                self._conns.add(server)
            for src, dst in ((client, server), (server, client)):
                threading.Thread(target=self._pump, args=(src, dst),
                                 daemon=True).start()

    def _pump(self, src: socket.socket, dst: socket.socket) -> None:
        try:
            while True:
                data = src.recv(65536)
                if not data:
                    break
                if self.delay:
                    self._stop.wait(self.delay)
                dst.sendall(data)
        except OSError:
            pass
        finally:
            for s in (src, dst):
                try:
                    s.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                try:
                    s.close()
                except OSError:
                    pass
                with self._lock:
                    self._conns.discard(s)

    def drop_connections(self) -> int:
        """Reset every live proxied connection NOW (watch streams included).
        New connections keep working — this is a network blip, not an
        outage. Returns how many sockets were torn down."""
        with self._lock:
            victims = list(self._conns)
            self._conns.clear()
        for s in victims:
            try:
                # linger(on, 0): close sends RST, not FIN — a real reset.
                s.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                             struct.pack("ii", 1, 0))
            except OSError:
                pass
            try:
                s.close()
            except OSError:
                pass
        self.drops += len(victims)
        return len(victims)

    def close(self) -> None:
        self._stop.set()
        try:
            self._listener.close()
        except OSError:
            pass
        self.drop_connections()


def drain_pipe(proc, keep: int = 200) -> "deque":
    """Start a daemon thread that keeps reading a spawned child's stdout
    AFTER the ready line, retaining the last `keep` lines for diagnostics.

    Without this, a child that logs under load (slow-step warnings, a
    device-fallback traceback) eventually fills the 64KB pipe buffer and
    BLOCKS on the write — mid-scheduling-cycle — which reads as a
    mysterious 2x throughput collapse, not a log problem (PR 8 incident:
    one fallback's host-path slow-step flood stalled a whole shard).
    Returns the deque of retained lines."""
    from collections import deque

    tail: "deque" = deque(maxlen=keep)

    def pump():
        try:
            for line in proc.stdout:
                tail.append(line)
        except (ValueError, OSError):
            pass  # pipe closed at process teardown

    threading.Thread(target=pump, name="pipe-drain", daemon=True).start()
    return tail


def spawn_ready(cmd, pattern, cwd=None, env=None, timeout=120.0):
    """Spawn a subprocess and block until a stdout line matches `pattern`
    (stderr is folded into stdout). select-before-readline: a
    silent-but-alive child trips the deadline instead of hanging the
    harness; a dead child raises immediately. Returns (proc, match).

    NOTE for callers printing a ready line: it must be the FIRST line the
    child emits — readline buffers everything already in the pipe, so a
    line printed BEFORE the ready line that arrives in the same chunk
    would leave select() waiting on a drained fd."""
    import select
    from collections import deque

    proc = subprocess.Popen(cmd, cwd=cwd, env=env, stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True)
    deadline = time.monotonic() + timeout
    # Keep the tail of everything read pre-ready: a child that dies before
    # its ready line usually printed WHY (a traceback) — surfacing it here
    # turns "exited rc=1" into an actionable failure.
    tail: "deque" = deque(maxlen=40)
    while time.monotonic() < deadline:
        ready, _, _ = select.select([proc.stdout], [], [],
                                    max(0.0, deadline - time.monotonic()))
        if not ready:
            break
        line = proc.stdout.readline()
        if line:
            tail.append(line)
        if not line and proc.poll() is not None:
            raise RuntimeError(
                f"{cmd[:3]} exited rc={proc.returncode}; "
                f"output tail:\n{''.join(tail)}")
        m = re.search(pattern, line)
        if m:
            return proc, m
    proc.kill()
    raise TimeoutError(
        f"{cmd[:3]} never printed {pattern!r}; "
        f"output tail:\n{''.join(tail)}")


class ApiServerProcess:
    """Standalone apiserver (`python -m kubernetes_tpu.core.apiserver`) as a
    killable OS process: the control-plane analogue of SidecarServer.kill().

    ``kill9()`` delivers SIGKILL mid-flight; ``restart()`` relaunches on the
    SAME port with the SAME ``--data-dir`` so the new process recovers from
    WAL+snapshot and watch clients reconnect to an identical address — the
    crash-restart fault the durable store is specified against."""

    _READY = re.compile(r"serving on 127\.0\.0\.1:(\d+)")

    def __init__(self, data_dir: str, port: int = 0, fsync: bool = False,
                 snapshot_every: int = 2048, startup_timeout: float = 60.0,
                 extra_args=(), extra_env=None):
        self.data_dir = data_dir
        self.port = port
        self.fsync = fsync
        self.snapshot_every = snapshot_every
        self.startup_timeout = startup_timeout
        # Extension seams for composed harnesses (ReplicaSet): replication
        # flags + per-process env (flight-recorder dir) without a second
        # copy of the spawn/env/teardown mechanics.
        self.extra_args = list(extra_args)
        self.extra_env = dict(extra_env or {})
        self.kills = 0
        self.restarts = 0
        self.proc: Optional[subprocess.Popen] = None
        self._spawn()

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def _spawn(self) -> None:
        repo_root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)  # never touch the TPU tunnel
        env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONPATH"] = repo_root
        env.update(self.extra_env)
        cmd = [sys.executable, "-m", "kubernetes_tpu.core.apiserver",
               "--port", str(self.port), "--data-dir", self.data_dir,
               "--snapshot-every", str(self.snapshot_every)]
        if self.fsync:
            cmd.append("--fsync")
        cmd += self.extra_args
        self.proc, m = spawn_ready(cmd, self._READY, cwd=repo_root, env=env,
                                   timeout=self.startup_timeout)
        # Pin the OS-assigned port: restarts re-bind the same one.
        self.port = int(m.group(1))
        # Drained stdout (see drain_pipe): an unread pipe would block the
        # server once it logs more than the 64KB buffer.
        self.log_tail = drain_pipe(self.proc)

    def kill9(self) -> None:
        """SIGKILL — the process dies mid-write, no flush, no shutdown."""
        self.proc.send_signal(signal.SIGKILL)
        self.proc.wait(timeout=30)
        self.kills += 1

    def restart(self) -> None:
        """Relaunch in place (same port, same data dir); blocks until the
        recovered server is serving."""
        assert self.proc.poll() is not None, "kill9()/stop() first"
        self._spawn()
        self.restarts += 1

    def stop(self) -> None:
        if self.proc is None or self.proc.poll() is not None:
            return
        self.proc.terminate()
        try:
            self.proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            self.proc.kill()


class ReplicaSet:
    """A replicated control plane under chaos control: one leader + N
    follower apiservers (kubernetes_tpu/replication/), each a killable OS
    process (composed :class:`ApiServerProcess` handles) with its own data
    dir. ``kill9_leader()`` is the headline fault — the lowest-ranked live
    follower must promote within the replication lease TTL;
    ``kill9_follower(rank)`` exercises the read plane's client-side
    failover (HTTPClientset fallbacks)."""

    def __init__(self, data_root: str, followers: int = 1,
                 repl_lease: float = 2.0, snapshot_every: int = 100_000,
                 startup_timeout: float = 120.0, flightrec_dir: str = ""):
        self.data_root = data_root
        self.repl_lease = repl_lease
        self.snapshot_every = snapshot_every
        self.startup_timeout = startup_timeout
        self.flightrec_dir = flightrec_dir
        if flightrec_dir:
            os.makedirs(flightrec_dir, exist_ok=True)
        self.kills: Dict[str, int] = {}
        # replicas[0] is the seed leader; replicas[k] is follower rank k.
        self.replicas: list = [self._spawn_replica(
            os.path.join(data_root, "leader"))]
        for rank in range(1, followers + 1):
            self.replicas.append(self._spawn_replica(
                os.path.join(data_root, f"follower-{rank}"),
                replicate_from=self.leader_url, rank=rank))
        # Inject the full rank -> URL topology into every replica (ports
        # are ephemeral, so peers are only known post-spawn). Elections
        # probe this map.
        self.peers = {rank: r.url for rank, r in enumerate(self.replicas)}
        body = {"peers": {str(k): v for k, v in self.peers.items()}}
        for r in self.replicas:
            self._post_json(r.url, "/replication/peers", body)

    @property
    def leader_url(self) -> str:
        return self.replicas[0].url

    @property
    def follower_urls(self) -> list:
        return [r.url for r in self.replicas[1:]]

    def _post_json(self, base: str, path: str, body: dict) -> None:
        # shard/harness._call: the shared pooled keep-alive JSON helper
        # (function-local import — harness itself imports from this
        # module, so a top-level import would cycle).
        from ..shard.harness import _call
        _call(base, "POST", path, body)

    def _spawn_replica(self, data_dir: str, replicate_from: str = "",
                       rank: int = 0) -> ApiServerProcess:
        extra = ["--repl-lease-duration", str(self.repl_lease)]
        if replicate_from:
            extra += ["--replicate-from", replicate_from,
                      "--replica-rank", str(rank)]
        extra_env = ({"TPU_SCHED_FLIGHTREC_DIR": self.flightrec_dir}
                     if self.flightrec_dir else {})
        return ApiServerProcess(
            data_dir, snapshot_every=self.snapshot_every,
            startup_timeout=self.startup_timeout,
            extra_args=extra, extra_env=extra_env)

    def kill9_leader(self) -> None:
        """SIGKILL the leader mid-flight: no flush, no goodbye — the
        promotion path's acceptance fault."""
        self.replicas[0].kill9()
        self.kills["leader"] = self.kills.get("leader", 0) + 1

    def kill9_follower(self, index: int = 0) -> None:
        """SIGKILL follower `index` (rank index+1): its local shards must
        rotate reads to a sibling replica."""
        self.replicas[index + 1].kill9()
        self.kills[f"follower-{index + 1}"] = \
            self.kills.get(f"follower-{index + 1}", 0) + 1

    def status(self, base: str) -> Optional[dict]:
        from ..shard.harness import _call
        try:
            return _call(base, "GET", "/replication/status", timeout=5)
        except Exception:  # noqa: BLE001 - replica down
            return None

    def wait_for_leader(self, timeout: float = 30.0) -> Optional[str]:
        """Block until some live replica reports role=leader; returns its
        base URL (None on timeout)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            for r in self.replicas:
                st = self.status(r.url)
                if st is not None and st.get("role") == "leader":
                    return r.url
            time.sleep(0.1)
        return None

    def stop(self) -> None:
        for r in self.replicas:
            r.stop()


class DeviceFaults:
    """Fault hook for TPUScheduler's device kernel boundaries.

    Install as ``scheduler._fault_hook``. Raises ``exc_factory()`` when the
    running count of crossings for a site ('dispatch' | 'preempt') lands in
    that site's configured set. Counts are 1-based and per-site, so a plan
    like ``DeviceFaults(dispatch={3}, preempt={1})`` is fully
    deterministic regardless of interleaving."""

    def __init__(self, dispatch: Iterable[int] = (),
                 preempt: Iterable[int] = (),
                 exc_factory=lambda: RuntimeError("injected device fault")):
        self._plan = {"dispatch": set(dispatch), "preempt": set(preempt)}
        self._exc_factory = exc_factory
        self.calls: Dict[str, int] = {"dispatch": 0, "preempt": 0}
        self.injected: Dict[str, int] = {"dispatch": 0, "preempt": 0}

    def __call__(self, site: str) -> None:
        self.calls[site] = self.calls.get(site, 0) + 1
        if self.calls[site] in self._plan.get(site, ()):
            self.injected[site] = self.injected.get(site, 0) + 1
            raise self._exc_factory()


def scrape_metric(base_url: str, name: str, timeout: float = 5.0) -> float:
    """Fetch `base_url`/metrics and return the value of the un-labelled
    series `name`. Chaos tests poll counters across a kill9 with this;
    raises AssertionError if the series is not exposed (a typo'd series
    name must fail loudly, not read as 0.0)."""
    from urllib import request as _rq

    with _rq.urlopen(base_url + "/metrics", timeout=timeout) as resp:
        text = resp.read().decode()
    for line in text.splitlines():
        if line.startswith(name + " "):
            return float(line.split()[1])
    raise AssertionError(f"series {name} not exposed by {base_url}")


def wait_metric(base_url: str, name: str, pred, timeout: float = 60.0,
                poll: float = 0.1) -> float:
    """Poll `scrape_metric` until `pred(value)` holds; returns the value
    that satisfied it. Scrape errors (the target may be mid-kill9) are
    swallowed and retried until the deadline."""
    deadline = time.monotonic() + timeout
    last = None
    while time.monotonic() < deadline:
        try:
            last = scrape_metric(base_url, name, timeout=poll * 50)
        except Exception:  # noqa: BLE001 - target racing a death
            last = None
        if last is not None and pred(last):
            return last
        time.sleep(poll)
    raise AssertionError(
        f"timed out waiting for {name} (last observed: {last})")
