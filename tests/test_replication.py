"""WAL-shipping apiserver replication (kubernetes_tpu/replication/):
frame seq/epoch stamping, follower convergence + read serving, torn-frame
and reconnect tolerance, stale-epoch fencing, NotLeader write routing with
leader re-resolution, ship-ack reply gating, promotion, and the
scheduler's failover bind reconciliation. docs/RESILIENCE.md § replication.
"""

import json
import os
import threading
import time

import pytest

from kubernetes_tpu.core import FakeClientset, Scheduler
from kubernetes_tpu.core.apiserver import APIServer, HTTPClientset
from kubernetes_tpu.core.backoff import RetryConfig
from kubernetes_tpu.core.clientset import RetryingClientset
from kubernetes_tpu.replication import LeaderLease, ReplicationTail
from kubernetes_tpu.testing.wrappers import make_node, make_pod


def _node(name="n0", cpu=8):
    return (make_node().name(name)
            .capacity({"cpu": cpu, "memory": "32Gi", "pods": 110}).obj())


def _pod(name, cpu="100m"):
    return make_pod().name(name).req({"cpu": cpu, "memory": "64Mi"}).obj()


def _wait(cond, timeout=10.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return cond()


class _Plane:
    """In-process leader + follower pair over REAL HTTP sockets."""

    def __init__(self, tmp_path=None, lease=0.6, follower_dir=None,
                 leader_dir=None):
        self.leader = APIServer(
            data_dir=str(tmp_path / leader_dir) if leader_dir else None)
        self.leader.serve(0)
        self.lease = LeaderLease(self.leader, "leader-0",
                                 duration=lease).start()
        self.follower = APIServer(
            data_dir=str(tmp_path / follower_dir) if follower_dir else None)
        self.tail = ReplicationTail(self.follower,
                                    self.leader.advertise_url,
                                    rank=1, lease_duration=lease)
        self.tail.bootstrap()
        self.follower.serve(0)
        peers = {0: self.leader.advertise_url,
                 1: self.follower.advertise_url}
        self.leader.repl_peers.update(peers)
        self.follower.repl_peers.update(peers)
        self.tail.start()

    def stop(self):
        self.tail.stop()
        self.lease.stop()
        self.follower.shutdown()
        self.leader.shutdown()


# ---------------------------------------------------------------------------
# frame metadata + follower convergence
# ---------------------------------------------------------------------------


def test_wal_frames_carry_monotonic_seq_and_epoch(tmp_path):
    api = APIServer(data_dir=str(tmp_path / "leader"))
    api.store.create_node(_node("n0"))
    for i in range(4):
        api.store.create_pod(_pod(f"p{i}"))
    api.shutdown()
    # WAL records are binary frames by default now (core/wire.py); scan()
    # sniffs per record, so this read works for either codec's history.
    from kubernetes_tpu.core import wire
    buf = (tmp_path / "leader" / "wal.log").read_bytes()
    recs, pos = [], 0
    while True:
        got = wire.scan(buf, pos)
        if got is None:
            break
        rec, pos = got
        recs.append(rec)
    assert [r["seq"] for r in recs] == list(range(1, len(recs) + 1))
    assert all(r["epoch"] == 1 for r in recs)
    # restart resumes the seq counter, not restarts it
    api2 = APIServer(data_dir=str(tmp_path / "leader"))
    assert api2._repl_seq == len(recs)
    api2.store.create_pod(_pod("p-post"))
    assert api2._repl_seq == len(recs) + 1
    api2.shutdown()


def test_follower_converges_and_serves_watch_reads(tmp_path):
    plane = _Plane(tmp_path)
    try:
        leader, follower = plane.leader, plane.follower
        # writes via a client pointed at the FOLLOWER: reads local,
        # mutations redirect (421 NotLeader -> leader)
        cs = HTTPClientset(follower.advertise_url)
        try:
            cs.create_node(_node("n0"))
            pods = [_pod(f"p{i}") for i in range(5)]
            for p in pods:
                cs.create_pod(p)
            assert cs.write_redirects >= 1
            # the follower's OWN store and watch plane converge: the
            # client's informer cache is fed by the follower stream
            assert _wait(lambda: len(cs.pods) == 5 and len(cs.nodes) == 1)
            assert _wait(lambda: len(follower.store.pods) == 5)
            # bind through the same redirect path; the slim BOUND event
            # reaches the follower-watching client
            cs.bind(pods[0], "n0")
            assert _wait(lambda: cs.bindings.get(pods[0].uid) == "n0")
            assert follower.store.bindings.get(pods[0].uid) == "n0"
            # leases replicate too (the lease table rides LEASE frames)
            leader.upsert_lease("shard-0", "holder-a", 5.0)
            assert _wait(lambda: any(
                l["name"] == "shard-0"
                for l in follower.list_leases()))
            # per-kind rv continuity: follower serves the same rv space
            assert follower._seq == leader._seq
        finally:
            cs.close()
    finally:
        plane.stop()


def test_cold_follower_snapshot_bootstrap(tmp_path):
    leader = APIServer()
    leader.serve(0)
    leader.store.create_node(_node("n0"))
    for i in range(6):
        leader.store.create_pod(_pod(f"p{i}"))
    leader.store.bind(leader.store.pods[
        next(iter(leader.store.pods))], "n0")
    try:
        follower = APIServer()
        tail = ReplicationTail(follower, leader.advertise_url, rank=1,
                               lease_duration=0.5)
        tail.bootstrap()
        try:
            # snapshot installed everything, including the binding and the
            # leader's WATCH epoch (rv/epoch continuity for RESUME)
            assert len(follower.store.pods) == 6
            assert follower.store.bindings
            assert follower.epoch == leader.epoch
            assert follower._repl_seq == leader._repl_seq
            assert follower.repl_resyncs == 1
        finally:
            tail.stop()
            follower.shutdown()
    finally:
        leader.shutdown()


def test_resync_when_ship_window_compacted(tmp_path):
    # A tiny ship backlog: the follower's `from` falls off the window and
    # the ship endpoint answers 410 ResyncRequired -> snapshot bootstrap.
    leader = APIServer(backlog=8)
    leader.serve(0)
    for i in range(64):
        leader.store.create_pod(_pod(f"p{i}"))
    try:
        follower = APIServer()
        follower.serve(0)
        tail = ReplicationTail(follower, leader.advertise_url, rank=1,
                               lease_duration=0.5)
        # deliberately NO bootstrap: from=0 is far outside the 8-frame
        # window, so the first tail attachment must resync via snapshot
        tail.start()
        try:
            assert _wait(lambda: len(follower.store.pods) == 64)
            assert follower.repl_resyncs >= 1
            # and the tail keeps riding frames afterwards
            leader.store.create_pod(_pod("p-live"))
            assert _wait(lambda: "p-live" in
                         {p.name for p in follower.store.pods.values()})
        finally:
            tail.stop()
            follower.shutdown()
    finally:
        leader.shutdown()


# ---------------------------------------------------------------------------
# torn frames + reconnect (the DurableStore truncate contract, replicated)
# ---------------------------------------------------------------------------


def test_follower_recovery_discards_torn_frame_and_retails(tmp_path):
    plane = _Plane(tmp_path, follower_dir="follower")
    try:
        leader = plane.leader
        for i in range(5):
            leader.store.create_pod(_pod(f"p{i}"))
        assert _wait(lambda: plane.follower._repl_seq == leader._repl_seq)
        good_seq = plane.follower._repl_seq
        # stop the follower process-equivalent (tail + server)...
        plane.tail.stop()
        plane.follower.shutdown()
        # ...and tear its WAL mid-record, as a kill -9 during append would
        with open(tmp_path / "follower" / "wal.log", "ab") as fh:
            fh.write(b'{"kind": "pods", "type": "ADD')
        # more leader writes while the follower is down
        for i in range(5, 8):
            leader.store.create_pod(_pod(f"p{i}"))
        # recover: the torn frame is discarded (DurableStore truncate
        # contract), _repl_seq resumes at the last GOOD frame, and the new
        # tail re-requests exactly from there
        f2 = APIServer(data_dir=str(tmp_path / "follower"))
        assert f2.persistence.torn_records_discarded == 1
        assert f2._repl_seq == good_seq
        t2 = ReplicationTail(f2, leader.advertise_url, rank=1,
                             lease_duration=0.6)
        t2.bootstrap()  # no-op: local WAL state stands
        f2.serve(0)
        t2.start()
        try:
            assert _wait(lambda: f2._repl_seq == leader._repl_seq)
            assert len(f2.store.pods) == 8
            # no duplicate application: each pod exactly once
            names = [p.name for p in f2.store.pods.values()]
            assert len(names) == len(set(names)) == 8
        finally:
            t2.stop()
            f2.shutdown()
    finally:
        plane.lease.stop()
        plane.leader.shutdown()


def test_reconnect_re_requests_from_last_applied_seq(tmp_path):
    plane = _Plane(tmp_path)
    try:
        leader, follower = plane.leader, plane.follower
        for i in range(4):
            leader.store.create_pod(_pod(f"p{i}"))
        assert _wait(lambda: follower._repl_seq == leader._repl_seq)
        applied_before = follower.repl_frames_applied
        # Tear every ship stream (leader side): the tail must reconnect
        # and re-request from its last applied seq — zero re-application.
        with leader._lock:
            streams = list(leader._ship_streams)
        for st in streams:
            st.q.put(None)  # poison: the ship loop dies on TypeError
        for i in range(4, 7):
            leader.store.create_pod(_pod(f"p{i}"))
        assert _wait(lambda: follower._repl_seq == leader._repl_seq)
        assert len(follower.store.pods) == 7
        # only the NEW frames were applied after the reconnect
        assert follower.repl_frames_applied - applied_before == 3
    finally:
        plane.stop()


# ---------------------------------------------------------------------------
# epoch fencing
# ---------------------------------------------------------------------------


def test_stale_epoch_frame_rejected():
    api = APIServer()
    api.repl_epoch = 5
    rec = {"kind": "pods", "type": "ADDED", "rv": 1, "seq": 1, "epoch": 4,
           "object": {"uid": "u1", "name": "p", "namespace": "d",
                      "requests": {"cpu": 100, "memory": 1}}}
    assert api.apply_frame(rec) is False
    assert api.repl_frames_rejected == 1
    assert not api.store.pods  # nothing leaked into the store
    # a frame from the CURRENT epoch applies; a newer epoch is adopted
    rec2 = dict(rec, epoch=5)
    assert api.apply_frame(rec2) is True
    rec3 = dict(rec, epoch=7, seq=2, rv=2)
    assert api.apply_frame(rec3) is True
    assert api.repl_epoch == 7
    api.shutdown()


def test_deposed_leader_fenced_by_ship_request():
    from urllib import error as urlerror
    from urllib import request as urlrequest

    stale = APIServer()
    stale.serve(0)
    try:
        assert stale.role == "leader"
        # A follower that has seen epoch 3 re-tails against this stale
        # leader (epoch 1): the ship endpoint must refuse AND self-fence.
        url = (stale.advertise_url
               + "/replication/wal?from=0&epoch=3&leader=http%3A%2F%2Fnew")
        with pytest.raises(urlerror.HTTPError) as ei:
            urlrequest.urlopen(url, timeout=5)
        assert ei.value.code == 409
        assert stale.role == "follower"
        assert stale.leader_url == "http://new"
        assert stale.repl_epoch == 3
        # and its write plane is fenced: NotLeader redirect
        req = urlrequest.Request(
            stale.advertise_url + "/api/v1/nodes", method="POST",
            data=b"{}", headers={"Content-Type": "application/json"})
        with pytest.raises(urlerror.HTTPError) as ei2:
            urlrequest.urlopen(req, timeout=5)
        assert ei2.value.code == 421
        assert json.loads(ei2.value.read())["leader"] == "http://new"
    finally:
        stale.shutdown()


def test_promotion_bumps_and_persists_fencing_epoch(tmp_path):
    api = APIServer(data_dir=str(tmp_path / "r"))
    api.role = "follower"
    api.advertise_url = "http://127.0.0.1:1"
    api.promote(reason="test")
    assert api.role == "leader"
    assert api.repl_epoch == 2
    assert api.failovers == {"test": 1}
    api.shutdown()
    # the bumped epoch survives a restart of the promoted replica
    api2 = APIServer(data_dir=str(tmp_path / "r"))
    assert api2.repl_epoch == 2
    api2.shutdown()


# ---------------------------------------------------------------------------
# client write routing: NotLeader redirect + re-resolution single replay
# ---------------------------------------------------------------------------


def test_write_replay_re_resolves_leader_across_promotion(tmp_path):
    """Satellite regression: a bind in flight during the failover is
    committed EXACTLY once — the replay re-resolves the leader first
    (never a blind same-host replay) and lands on the promoted follower
    through the idempotent/409 surface."""
    plane = _Plane(tmp_path, lease=0.5)
    cs = None
    try:
        leader, follower = plane.leader, plane.follower
        cs = HTTPClientset(follower.advertise_url)
        rcs = RetryingClientset(cs, retry=RetryConfig(
            initial_backoff=0.05, max_backoff=0.3, max_attempts=40, seed=7))
        rcs.create_node(_node("n0"))
        p = _pod("p0")
        rcs.create_pod(p)
        assert _wait(lambda: p.uid in follower.store.pods)
        # the client now routes writes at the LEADER (redirect learned);
        # kill the leader and immediately fire the bind: it must queue
        # behind retries until the follower promotes, then commit once
        leader.shutdown()
        done = {}

        def bind():
            try:
                rcs.bind(p, "n0")
                done["ok"] = True
            except Exception as e:  # noqa: BLE001 - the assertion target
                done["err"] = e

        t = threading.Thread(target=bind, daemon=True)
        t.start()
        assert _wait(lambda: follower.role == "leader", timeout=15)
        t.join(timeout=20)
        assert done.get("ok"), f"bind failed across failover: {done!r}"
        assert follower.store.bindings == {p.uid: "n0"}
        assert cs.leader_resolutions >= 1
        # replaying the SAME bind again rides the idempotent path (200)
        rcs.bind(p, "n0")
        assert follower.store.bindings == {p.uid: "n0"}
    finally:
        if cs is not None:
            cs.close()
        plane.tail.stop()
        plane.lease.stop()
        plane.follower.shutdown()


def test_failover_marker_triggers_scheduler_reconcile():
    """A bind the dead leader acked but never shipped leaves the promoted
    truth UNBOUND with no event: the FAILOVER-driven sweep unwinds the
    phantom placement and the pod is rescheduled."""
    cs = FakeClientset()
    s = Scheduler(clientset=cs, deterministic_ties=True)
    cs.create_node(_node("n0"))
    p = _pod("p0")
    cs.create_pod(p)
    s.run_until_idle()
    assert cs.bindings.get(p.uid) == "n0"
    # On the wire path finish_binding runs before the async BOUND event
    # confirms; the synchronous FakeClientset confirms first, so pin the
    # wire-path state explicitly (the leader-kill chaos test exercises
    # the real sequence end to end).
    s.cache.pod_states[p.uid].binding_finished = True
    # simulate the promoted follower's truth: the bind never shipped
    cs.bindings.pop(p.uid)
    cs.pods[p.uid].node_name = ""
    cs.failover_count = 1  # what the FAILOVER watch marker bumps
    s.run_until_idle()
    assert s.reconcile_unwinds == 1
    assert cs.bindings.get(p.uid) == "n0"  # re-bound, exactly once


# ---------------------------------------------------------------------------
# ship-ack reply gating
# ---------------------------------------------------------------------------


def test_await_shipped_gates_acked_writes_and_drops_laggards():
    api = APIServer()
    api.serve(0)
    try:
        # a fake attached follower that never drains its queue
        st = api._attach_ship(api._repl_seq)
        t0 = time.perf_counter()
        api.store.create_pod(_pod("p0"))  # in-process write, no HTTP gate
        assert api._await_shipped(api._repl_seq, timeout=0.2) is False
        waited = time.perf_counter() - t0
        assert waited >= 0.15
        assert api.ship_wait_timeouts == 1
        assert st.acked is False  # dropped from the ack quorum
        # once dropped, acked writes stop convoying behind it
        t1 = time.perf_counter()
        assert api._await_shipped(api._repl_seq, timeout=0.2) is True
        assert time.perf_counter() - t1 < 0.1
        # catching up re-enters the quorum
        api._ship_mark_sent(st, api._repl_seq)
        assert st.acked is True
        api._detach_ship(st)
    finally:
        api.shutdown()


def test_ship_ack_covers_http_acked_write(tmp_path):
    """Over real HTTP: with a live follower attached, a 201-acked create is
    already on the wire to the follower when the client sees the reply."""
    plane = _Plane(tmp_path)
    try:
        from kubernetes_tpu.core.apiserver import (KeepAliveClient,
                                                   pod_to_wire)
        # quiesce the leader-lease renewer: a renewal frame landing between
        # the POST reply and the assertion would race the seq snapshot
        plane.lease.stop()
        ka = KeepAliveClient(plane.leader.advertise_url)
        ka.call("POST", "/api/v1/pods", pod_to_wire(_pod("p0")))
        # sent_seq has reached the commit seq on every in-quorum stream
        with plane.leader._ship_cond:
            assert all(st.sent_seq >= plane.leader._repl_seq
                       for st in plane.leader._ship_streams if st.acked)
        assert _wait(lambda: len(plane.follower.store.pods) == 1)
    finally:
        plane.stop()


def test_follower_compaction_never_drops_the_triggering_frame(tmp_path):
    """Review regression (confirmed by repro): apply_frame used to run
    WAL compaction BETWEEN append and store upsert — the snapshot
    excluded the triggering frame while the WAL reset discarded it, so a
    follower restart fast-forwarded straight past the hole (silently
    missing an acked write forever). Compaction must run after apply."""
    def frame(i):
        return {"kind": "pods", "type": "ADDED", "rv": i, "seq": i,
                "epoch": 1, "object": {
                    "name": f"p{i}", "namespace": "d", "uid": f"u{i}",
                    "requests": {"cpu": 100, "memory": 1}}}

    api = APIServer(data_dir=str(tmp_path / "f"), snapshot_every=3)
    api.role = "follower"
    for i in range(1, 8):
        assert api.apply_frame(frame(i)) is True
    assert api.persistence.compactions >= 1  # compaction really fired
    api.shutdown()
    api2 = APIServer(data_dir=str(tmp_path / "f"))
    assert api2._repl_seq == 7
    assert sorted(p.name for p in api2.store.pods.values()) == [
        f"p{i}" for i in range(1, 8)]
    api2.shutdown()


def test_promotion_announcement_converges_peers():
    """Review regression: a promotion is ANNOUNCED to every peer — the
    surviving follower re-tails immediately (no silence detection wait),
    and a stale co-claimant leader demotes itself even though no follower
    ever tails it."""
    stale = APIServer()
    stale.serve(0)  # role=leader, epoch 1 — the deposed generation
    other = APIServer()
    other.role = "follower"
    other.serve(0)
    winner = APIServer()
    tail = ReplicationTail(winner, stale.advertise_url, rank=1,
                           lease_duration=0.5)
    winner.serve(0)
    peers = {0: stale.advertise_url, 1: winner.advertise_url,
             2: other.advertise_url}
    winner.repl_peers.update(peers)
    try:
        winner.promote(reason="test")
        tail.leader_url = winner.advertise_url
        tail._announce_leadership()
        # the stale co-leader fenced itself...
        assert stale.role == "follower"
        assert stale.repl_epoch == winner.repl_epoch
        assert stale.leader_url == winner.advertise_url
        # ...and the surviving follower learned the new leader instantly
        assert other.leader_url == winner.advertise_url
        assert other.repl_epoch == winner.repl_epoch
    finally:
        tail.stop()
        for a in (stale, other, winner):
            a.shutdown()


def test_lagging_survivor_accepts_old_generation_frames_from_new_leader():
    """Review regression: a survivor that adopted the winner's epoch
    BEFORE catching up must still accept the winner's pre-promotion
    frames (stamped with the old epoch) — the stream's claimed generation
    legitimizes them. Without a stream claim the same frame stays fenced
    (a deposed leader's append)."""
    api = APIServer()
    api.repl_epoch = 2  # adopted via the promotion announcement
    old_frame = {"kind": "pods", "type": "ADDED", "rv": 1, "seq": 1,
                 "epoch": 1, "object": {
                     "name": "p", "namespace": "d", "uid": "u1",
                     "requests": {"cpu": 100, "memory": 1}}}
    assert api.apply_frame(old_frame) is False  # no claim: fenced
    assert api.repl_frames_rejected == 1
    assert api.apply_frame(old_frame, stream_epoch=2) is True
    assert "u1" in api.store.pods
    api.shutdown()


def test_equal_epoch_dual_promotion_resolved_by_rank():
    """Review regression: two followers promoting concurrently land on
    the SAME epoch — the announcement's rank tie-break stands the
    higher-ranked one down, and the lower-ranked claimant ignores the
    rival's announcement."""
    from urllib import request as urlrequest

    low = APIServer()
    tail = ReplicationTail(low, "http://dead", rank=1, lease_duration=0.5)
    low.serve(0)
    high = APIServer()
    high.replica_rank = 2
    high.serve(0)
    try:
        low.promote(reason="race")   # follower(rank 1) -> leader epoch 2
        high.role = "leader"         # the concurrent rank-2 claimant
        high.repl_epoch = 2
        low.repl_peers.update({1: low.advertise_url, 2: high.advertise_url})
        tail.leader_url = low.advertise_url
        tail._announce_leadership()
        assert high.role == "follower"  # rank 2 stood down at equal epoch
        assert high.leader_url == low.advertise_url
        # the reverse announcement does NOT depose the lower rank
        body = json.dumps({"leader": high.advertise_url, "epoch": 2,
                           "rank": 2}).encode()
        req = urlrequest.Request(
            low.advertise_url + "/replication/leader", data=body,
            method="POST", headers={"Content-Type": "application/json"})
        with urlrequest.urlopen(req, timeout=5):
            pass
        assert low.role == "leader"
    finally:
        tail.stop()
        low.shutdown()
        high.shutdown()


def test_redirect_hop_notleader_surfaces_retriable():
    """Review regression: mid-failover a followed redirect can land on a
    freshly deposed replica that answers 421 itself — that must surface
    as a retriable TransientAPIError (binds queue behind the retry
    layers), never a hard non-retriable 4xx."""
    from kubernetes_tpu.core.backoff import TransientAPIError, is_retriable

    a = APIServer()
    a.role = "follower"
    b = APIServer()
    b.role = "follower"
    b.leader_url = "http://127.0.0.1:1"  # nobody leads yet
    a.serve(0)
    b.serve(0)
    a.leader_url = b.advertise_url
    cs = None
    try:
        cs = HTTPClientset(a.advertise_url)
        with pytest.raises(TransientAPIError) as ei:
            cs.create_pod(_pod("p0"))
        assert is_retriable(ei.value)
        assert cs.write_redirects == 1
    finally:
        if cs is not None:
            cs.close()
        a.shutdown()
        b.shutdown()


def test_non_leader_heartbeats_do_not_hold_off_election():
    """Review regression: a follower whose tail landed on a DEMOTED peer
    (role=follower, equal epoch, shipping only heartbeats) must not treat
    those HBs as leader liveness — the stream is fenced without
    refreshing last_contact, so the election that finds the real leader
    still fires."""
    demoted = APIServer()
    demoted.role = "follower"  # equal epoch, no frames to ship
    demoted.serve(0)
    api = APIServer()
    tail = ReplicationTail(api, demoted.advertise_url, rank=2,
                           lease_duration=0.5, hb_interval=0.1)
    api.serve(0)
    api.repl_peers.update({1: demoted.advertise_url,
                           2: api.advertise_url})
    tail.start()
    try:
        assert _wait(lambda: tail.fenced_streams >= 1, timeout=5)
        # the silence clock keeps running -> an election runs, and with no
        # live-tailed lower rank it promotes this replica
        assert _wait(lambda: tail.elections >= 1, timeout=5)
        assert _wait(lambda: api.role == "leader", timeout=5)
    finally:
        tail.stop()
        demoted.shutdown()
        api.shutdown()


def test_snapshot_bootstrap_refuses_non_leader_source():
    """Review regression: installing a snapshot from a demoted/stale peer
    would REGRESS this replica to a forked, older history — the source
    must claim role=leader at >= our epoch."""
    demoted = APIServer()
    demoted.role = "follower"
    demoted.serve(0)
    api = APIServer()
    tail = ReplicationTail(api, demoted.advertise_url, rank=1,
                           lease_duration=0.5)
    try:
        with pytest.raises(RuntimeError):
            tail._bootstrap_snapshot()
        assert api.repl_resyncs == 0
    finally:
        tail.stop()
        demoted.shutdown()
        api.shutdown()


def test_ship_fence_demote_never_names_itself_as_leader():
    """Review regression: the fencing ship request's leader hint is the
    follower's TAIL TARGET — this very server — so a deposed leader must
    not record itself as the redirect target (clients would loop)."""
    from urllib import error as urlerror
    from urllib import request as urlrequest
    from urllib.parse import quote

    stale = APIServer()
    stale.serve(0)
    try:
        url = (f"{stale.advertise_url}/replication/wal?from=0&epoch=3"
               f"&leader={quote(stale.advertise_url, safe='')}")
        with pytest.raises(urlerror.HTTPError) as ei:
            urlrequest.urlopen(url, timeout=5)
        assert ei.value.code == 409
        assert stale.role == "follower"
        assert stale.leader_url == ""  # never itself
    finally:
        stale.shutdown()


def test_deposed_role_survives_restart(tmp_path):
    """Review regression: a deposed leader must NEVER restart read-write —
    it would accept acked writes into a forked history at the winner's
    epoch, which the fencing cannot distinguish. The role rides
    meta.json."""
    api = APIServer(data_dir=str(tmp_path / "r"))
    assert api.role == "leader"
    api.demote("http://winner", 3)
    assert api.role == "follower"
    api.shutdown()
    api2 = APIServer(data_dir=str(tmp_path / "r"))
    assert api2.role == "follower"
    assert api2.leader_url == "http://winner"
    assert api2.repl_epoch == 3
    # and its lease surface is fenced under the write lock too
    assert api2.upsert_lease("shard-0", "h", 5.0) is APIServer.NOT_LEADER
    api2.shutdown()


def test_resolve_leader_prefers_highest_epoch(tmp_path):
    """Review regression: with a stale leader still claiming the role
    (it never learned it was deposed), write routing must pick the claim
    with the HIGHEST fencing epoch, not the first one probed."""
    stale = APIServer()
    stale.serve(0)  # role=leader, epoch 1
    winner = APIServer()
    winner.repl_epoch = 3
    winner.serve(0)  # role=leader, epoch 3
    cs = None
    try:
        cs = HTTPClientset(stale.advertise_url,
                           fallbacks=[winner.advertise_url])
        assert cs._resolve_leader() == winner.advertise_url
    finally:
        if cs is not None:
            cs.close()
        stale.shutdown()
        winner.shutdown()


def test_stalled_ship_stream_is_dropped_not_unbounded():
    """Review regression: a connected-but-stalled follower (no socket
    error, it just stopped reading) must not make the leader buffer the
    entire write history — its bounded queue overflows, the stream is
    detached and counted, and the write plane keeps moving."""
    api = APIServer(backlog=8)
    st = api._attach_ship(0)
    assert st is not None and st.q.maxsize == 8
    for i in range(20):  # nobody drains the queue
        api.store.create_pod(_pod(f"p{i}"))
    assert st.dead is True
    assert api._ship_streams == []
    assert api.ship_streams_dropped == 1
    assert len(api.store.pods) == 20  # commits never blocked on it
    api.shutdown()


# ---------------------------------------------------------------------------
# observability: replication metrics + failover trace timeline
# ---------------------------------------------------------------------------


def test_replication_metrics_exposed(tmp_path):
    plane = _Plane(tmp_path)
    try:
        for i in range(3):
            plane.leader.store.create_pod(_pod(f"p{i}"))
        assert _wait(lambda: len(plane.follower.store.pods) == 3)
        leader_text = plane.leader.expose_metrics()
        follower_text = plane.follower.expose_metrics()
        assert "apiserver_replication_role 1" in leader_text
        assert "apiserver_replication_role 0" in follower_text
        assert "apiserver_replication_lag_records" in leader_text
        assert "apiserver_replication_frames_applied_total 0" in leader_text
        assert ("apiserver_replication_frames_applied_total 0"
                not in follower_text)
    finally:
        plane.stop()


def test_failover_counter_and_trace_timeline():
    from kubernetes_tpu import trace as trace_mod
    from kubernetes_tpu.core import spans as spans_mod

    api = APIServer()
    api.role = "follower"
    api.advertise_url = "http://127.0.0.1:9"
    api.tracer = spans_mod.SpanRecorder(proc="apiserver-r1", sample_n=1,
                                        enabled=True)
    api.promote(reason="leader_lost")
    assert ('apiserver_failover_total{reason="leader_lost"} 1'
            in api.expose_metrics())
    # the 100%-sampled promote span feeds the analyzer's failover timeline
    rows = list(api.tracer.ring)
    summary = trace_mod.summarize(rows)
    assert summary["failovers"], rows
    fo = summary["failovers"][0]
    assert fo["proc"] == "apiserver-r1"
    assert fo["epoch"] == 2 and fo["reason"] == "leader_lost"
    assert "replication.promote" in spans_mod.FORCED_STAGES
    api.shutdown()


# ---------------------------------------------------------------------------
# watch continuity across promotion (no re-list / 410)
# ---------------------------------------------------------------------------


def test_follower_watch_survives_promotion_without_relist(tmp_path):
    plane = _Plane(tmp_path, lease=0.5)
    cs = None
    try:
        leader, follower = plane.leader, plane.follower
        cs = HTTPClientset(follower.advertise_url)
        cs.create_node(_node("n0"))
        for i in range(3):
            cs.create_pod(_pod(f"p{i}"))
        assert _wait(lambda: len(cs.pods) == 3)
        relists = dict(cs.relists)
        leader.shutdown()
        assert _wait(lambda: follower.role == "leader", timeout=15)
        assert _wait(lambda: cs.failover_count >= 1)
        # post-promotion writes flow to the same watch stream
        cs.create_pod(_pod("p-post"))
        assert _wait(lambda: len(cs.pods) == 4)
        # the reads NEVER re-listed: same stream, same rv space
        assert dict(cs.relists) == relists
        assert cs._leader_base == follower.advertise_url
    finally:
        if cs is not None:
            cs.close()
        plane.tail.stop()
        plane.lease.stop()
        plane.follower.shutdown()


def test_filtered_stream_resumes_across_promotion_without_relist(tmp_path):
    """Watch-cache read plane on followers: a shard's FILTERED stream
    (?shard=i/n, core/watchcache.py) rides a leader kill -> promotion with
    zero re-lists — the follower's watch cache was maintained from applied
    frames in the shared rv space, so the reconnect RESUMEs and keeps
    slimming, and post-promotion events keep flowing filtered."""
    plane = _Plane(tmp_path, lease=0.5)
    cs = None
    try:
        leader, follower = plane.leader, plane.follower
        cs = HTTPClientset(follower.advertise_url, shard=(0, 2))
        cs.create_node(_node("n0"))
        for i in range(20):
            cs.create_pod(_pod(f"p{i}"))
        assert _wait(lambda: len(cs.pods) == 20)
        assert cs.watch_events_slim > 0          # filter engaged pre-kill
        slim_before = cs.watch_events_slim
        relists = dict(cs.relists)
        leader.shutdown()
        assert _wait(lambda: follower.role == "leader", timeout=15)
        assert _wait(lambda: cs.failover_count >= 1)
        # post-promotion writes keep flowing through the SAME filtered
        # stream; foreign plain pods still arrive slim
        for i in range(10):
            cs.create_pod(_pod(f"post{i}"))
        assert _wait(lambda: len(cs.pods) == 30)
        assert dict(cs.relists) == relists       # ZERO re-lists
        assert cs.watch_events_slim > slim_before
        # the promoted replica's cache serves the read plane too
        import urllib.request as _rq
        with _rq.urlopen(follower.advertise_url
                         + "/api/v1/pods?summary=true", timeout=5) as r:
            assert json.loads(r.read())["total"] == 30
    finally:
        if cs is not None:
            cs.close()
        plane.tail.stop()
        plane.lease.stop()
        plane.follower.shutdown()
