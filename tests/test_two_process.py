"""REAL two-OS-process integration (round-4 VERDICT item 5): the apiserver
(`python -m kubernetes_tpu.core.apiserver`) and the scheduler binary
(`python -m kubernetes_tpu --api-url`) run as separate processes on a real
socket (ref test/integration/framework/test_server.go:78 StartTestServer +
cmd/kube-scheduler); the test drives the cluster purely over HTTP, asserts
assignments identical to an in-process oracle, and reports the measured
write RTT. Node update/delete verbs make the MixedChurn shape run over the
wire too."""

import json
import os
import subprocess
import sys
import time
from urllib import request as urlrequest

import pytest

from kubernetes_tpu.core import FakeClientset, Scheduler
from kubernetes_tpu.core.apiserver import node_to_wire, pod_to_wire
from kubernetes_tpu.testing.wrappers import make_node, make_pod

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _env():
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # never touch the TPU tunnel
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO
    return env


def _call(base, method, path, body=None):
    data = json.dumps(body).encode() if body is not None else None
    req = urlrequest.Request(base + path, data=data, method=method,
                             headers={"Content-Type": "application/json"})
    with urlrequest.urlopen(req, timeout=10) as resp:
        return json.loads(resp.read())


def _start(cmd, pattern, timeout=120):
    # Shared select-before-readline ready-wait (one implementation for every
    # harness that spawns a binary and waits for its ready line).
    from kubernetes_tpu.testing.faults import spawn_ready
    return spawn_ready(cmd, pattern, cwd=REPO, env=_env(), timeout=timeout)


def _nodes(n):
    out = []
    for i in range(n):
        out.append(make_node().name(f"n{i}")
                   .capacity({"cpu": 16, "memory": "64Gi", "pods": 110})
                   .zone(f"z{i % 4}").obj())
    return out


def _pods(n):
    proto = (make_pod().name("proto").req({"cpu": "100m", "memory": "64Mi"})
             .labels({"app": "wire"}).obj())
    return [proto.clone_from_template(f"p{i}") for i in range(n)]


@pytest.fixture()
def cluster_procs():
    api_proc, m = _start(
        [sys.executable, "-m", "kubernetes_tpu.core.apiserver", "--port", "0"],
        r"serving on 127\.0\.0\.1:(\d+)")
    base = f"http://127.0.0.1:{m.group(1)}"
    sched_proc = None
    try:
        sched_proc, _ = _start(
            [sys.executable, "-m", "kubernetes_tpu",
             "--api-url", base, "--platform", "cpu", "--port", "0"],
            r"serving on 127\.0\.0\.1:\d+")
        yield base, api_proc, sched_proc
    finally:
        for p in (sched_proc, api_proc):
            if p is not None:
                p.terminate()
                try:
                    p.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    p.kill()


def test_two_process_scheduling_matches_in_process(cluster_procs):
    base, _api, _sched = cluster_procs
    N_NODES, N_PODS = 100, 5000

    # in-process oracle (same specs, name-keyed comparison)
    cs_h = FakeClientset()
    host = Scheduler(clientset=cs_h, deterministic_ties=True)
    for node in _nodes(N_NODES):
        cs_h.create_node(node)
    for p in _pods(N_PODS):
        cs_h.create_pod(p)
    host.run_until_idle()
    oracle = {cs_h.pods[u].name: n for u, n in cs_h.bindings.items()}
    assert len(oracle) == N_PODS

    # drive the two-process cluster over the socket
    for node in _nodes(N_NODES):
        _call(base, "POST", "/api/v1/nodes", node_to_wire(node))
    rtts = []
    for p in _pods(N_PODS):
        t0 = time.perf_counter()
        _call(base, "POST", "/api/v1/pods", pod_to_wire(p))
        rtts.append(time.perf_counter() - t0)

    deadline = time.monotonic() + 180
    bound = {}
    while time.monotonic() < deadline:
        pods = _call(base, "GET", "/api/v1/pods")
        bound = {p["name"]: p["nodeName"] for p in pods if p["nodeName"]}
        if len(bound) >= N_PODS:
            break
        time.sleep(0.25)
    assert len(bound) == N_PODS, f"only {len(bound)}/{N_PODS} bound"
    diffs = {k: (oracle[k], bound.get(k)) for k in oracle
             if oracle[k] != bound.get(k)}
    assert not diffs, f"{len(diffs)} divergences, e.g. {list(diffs.items())[:5]}"
    rtts.sort()
    print(f"\nwrite RTT over the socket: p50={rtts[len(rtts)//2]*1e3:.2f}ms "
          f"p99={rtts[int(len(rtts)*0.99)]*1e3:.2f}ms "
          f"({N_PODS} creates)")


def test_mixed_churn_over_the_wire(cluster_procs):
    """Node relabel/retaint/delete churn through PUT/DELETE while pods
    schedule — the MixedChurn shape running entirely over the socket.
    Taint churn alternates PreferNoSchedule (scoring) with hard
    **NoSchedule** (VERDICT weak #6): an untolerated NoSchedule taint must
    actually FILTER the node out over the wire while pods flow, and lifting
    it must return the capacity (the eviction-relevant add/remove cycle,
    not just preference scoring)."""
    base, api_proc, _sched = cluster_procs
    nodes = _nodes(20)
    for node in nodes:
        _call(base, "POST", "/api/v1/nodes", node_to_wire(node))
    pods = _pods(300)
    last_tainted = None
    for i, p in enumerate(pods):
        _call(base, "POST", "/api/v1/pods", pod_to_wire(p))
        if i % 10 == 5:
            # churn: relabel one node, retaint another, delete + recreate
            n = nodes[i % len(nodes)]
            w = node_to_wire(n)
            w["labels"]["churn"] = str(i)
            _call(base, "PUT", f"/api/v1/nodes/{n.name}", w)
            t = nodes[(i + 7) % len(nodes)]
            if last_tainted is not None and last_tainted.name != t.name:
                # lift the previous taint: its node is schedulable again
                # (NodeUpdate requeue hints reactivate parked pods)
                _call(base, "PUT", f"/api/v1/nodes/{last_tainted.name}",
                      node_to_wire(last_tainted))
            wt = node_to_wire(t)
            wt["taints"] = [{
                "key": "churn", "value": "x",
                # alternate soft/hard; the run ENDS on NoSchedule so the
                # store visibly holds a hard taint at the final assert
                "effect": "NoSchedule" if (i // 10) % 2 else
                          "PreferNoSchedule"}]
            _call(base, "PUT", f"/api/v1/nodes/{t.name}", wt)
            last_tainted = t
        if i % 40 == 21:
            victim = nodes[(i + 3) % len(nodes)]
            _call(base, "DELETE", f"/api/v1/nodes/{victim.name}")
            _call(base, "POST", "/api/v1/nodes", node_to_wire(victim))

    deadline = time.monotonic() + 120
    bound = {}
    while time.monotonic() < deadline:
        got = _call(base, "GET", "/api/v1/pods")
        bound = {p["name"]: p["nodeName"] for p in got if p["nodeName"]}
        if len(bound) >= len(pods):
            break
        time.sleep(0.25)
    assert len(bound) == len(pods), f"only {len(bound)}/{len(pods)} bound"
    # the churned labels/taints visibly landed in the server store, and the
    # final hard taint survived: NoSchedule filtering really ran over the
    # wire (pods kept binding around it — the 300/300 assert above)
    got_nodes = _call(base, "GET", "/api/v1/nodes")
    assert any("churn" in n["labels"] for n in got_nodes)
    assert any(t["effect"] == "NoSchedule"
               for n in got_nodes for t in n["taints"])
