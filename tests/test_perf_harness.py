"""The scheduler_perf harness doubles as integration tests via label filters
(reference misc/performance-config.yaml:1-19)."""

import os

import pytest

from kubernetes_tpu.perf import load_config, run_workload

CONFIG = os.path.join(os.path.dirname(__file__), "..", "kubernetes_tpu",
                      "perf", "configs", "performance-config.yaml")


def _short_workloads():
    return [wl for wl in load_config(CONFIG)
            if "integration-test" in wl.labels and "short" in wl.labels]


@pytest.mark.parametrize("wl", _short_workloads(),
                         ids=lambda wl: f"{wl.testcase}/{wl.name}")
def test_short_workload(wl):
    res = run_workload(wl)
    # Every measured pod must land (these configs are satisfiable).
    # Preemption testcases legitimately record failed attempts: a
    # preemptor's first cycle fails while its victims drain.
    assert res.failed == 0 or wl.testcase in ("PreemptionAsync",
                                              "PreemptionStorm")
    assert res.scheduled > 0
    assert "SchedulingThroughput" in res.metrics
    # CPU-mode smoke thresholds are intentionally loose; the perf labels run
    # full-scale on TPU with the reference floors.
    assert res.metrics["SchedulingThroughput"]["Average"] > 0


def test_all_performance_workloads_parse():
    wls = load_config(CONFIG)
    names = {f"{w.testcase}/{w.name}" for w in wls}
    assert "SchedulingBasic/5000Nodes_10000Pods" in names
    assert "SchedulingGangs/1000Nodes_250Groups" in names
    for w in wls:
        assert w.ops, f"{w.name} has no ops"


def test_scale_param():
    wls = [w for w in load_config(CONFIG, scale=0.01)
           if w.testcase == "SchedulingBasic" and w.name == "5000Nodes_10000Pods"]
    assert wls[0].params["nodes"] == 50
    assert wls[0].thresholds["SchedulingThroughput"] == pytest.approx(6.8)
