"""Watch-cache read plane + shard-filtered watch streams
(kubernetes_tpu/core/watchcache.py; docs/SHARDING.md levers).

Covers: ring interval replay / wraparound / 410-too-old fallback units;
cache-served LIST / summary / uid-hydration / `/metrics/resources`;
the filtered-stream equivalence fuzz (a shard member's scheduler cache
after a MixedChurn run over a filtered stream is identical to an
unfiltered oracle's, including affinity/spread/ports foreign pods and the
selector-transition upgrade path); the ~1/N decoded-full-event assertion;
slim-event suppression; and adoption hydration end to end."""

import json
import random
import threading
import time
import zlib
from urllib import request as urlrequest

import pytest

from kubernetes_tpu.core import Scheduler
from kubernetes_tpu.core.apiserver import (
    APIServer,
    HTTPClientset,
    pod_to_wire,
)
from kubernetes_tpu.core.watchcache import (
    ShardFilter,
    WatchCache,
    pod_from_slim,
    shard_of_wire,
    slim_object,
    wire_plain,
)
from kubernetes_tpu.testing.wrappers import make_node, make_pod


# ---------------------------------------------------------------------------
# WatchCache units: interval replay, wraparound, too-old, read surfaces
# ---------------------------------------------------------------------------


def _ev(rv, typ, obj):
    event = {"type": typ, "object": obj, "rv": rv}
    return rv, typ, obj, (json.dumps(event) + "\n").encode(), event


class TestWatchCacheUnits:
    def _pod_wire(self, i, node=""):
        p = make_pod().name(f"p{i}").req({"cpu": "100m"}).obj()
        w = pod_to_wire(p)
        w["nodeName"] = node
        return w

    def test_interval_replay_exact_tail(self):
        wc = WatchCache("pods", capacity=16)
        for i in range(1, 9):
            rv, typ, obj, data, event = _ev(i, "ADDED", self._pod_wire(i))
            wc.note_event(rv, typ, obj, data=data, event=event)
        tail = wc.events_since(5)
        assert [rv for rv, _e, _d in tail] == [6, 7, 8]
        assert wc.events_since(8) == []          # fully caught up
        assert wc.resumes == 2

    def test_ring_wraparound_drops_oldest(self):
        wc = WatchCache("pods", capacity=4)
        for i in range(1, 11):
            rv, typ, obj, data, event = _ev(i, "ADDED", self._pod_wire(i))
            wc.note_event(rv, typ, obj, data=data, event=event)
        # window is [7..10]: rv 6 still replays (ring head 7 <= 6+1)
        assert [rv for rv, _e, _d in wc.events_since(6)] == [7, 8, 9, 10]
        # ...but the OBJECT snapshot kept everything
        assert len(wc.list_wire()) == 10

    def test_too_old_resume_answers_none(self):
        wc = WatchCache("pods", capacity=4)
        for i in range(1, 11):
            rv, typ, obj, data, event = _ev(i, "ADDED", self._pod_wire(i))
            wc.note_event(rv, typ, obj, data=data, event=event)
        assert wc.events_since(3) is None        # 410 Gone analogue
        assert wc.too_old == 1

    def test_summary_and_bound_tracking(self):
        wc = WatchCache("pods")
        w1, w2 = self._pod_wire(1), self._pod_wire(2)
        wc.note_event(1, "ADDED", w1)
        wc.note_event(2, "ADDED", w2)
        wc.note_event(3, "BOUND", {"uid": w1["uid"], "nodeName": "n0"})
        s = wc.read_summary()
        assert (s["total"], s["bound"]) == (2, 1)
        wc.note_event(4, "DELETED", dict(w1, nodeName="n0"))
        s = wc.read_summary()
        assert (s["total"], s["bound"]) == (1, 0)

    def test_bound_event_is_copy_on_write(self):
        """A handed-out list_wire() dict must not mutate under a later
        BOUND (readers render outside every lock)."""
        wc = WatchCache("pods")
        w = self._pod_wire(1)
        wc.note_event(1, "ADDED", w)
        snap = wc.list_wire()[0]
        wc.note_event(2, "BOUND", {"uid": w["uid"], "nodeName": "n3"})
        assert snap["nodeName"] == ""
        assert wc.get(w["uid"])["nodeName"] == "n3"

    def test_render_resources_from_snapshot(self):
        wc = WatchCache("pods")
        w = self._pod_wire(1, node="n7")
        wc.note_event(1, "ADDED", w)
        text = wc.render_resources()
        assert 'node="n7"' in text and 'phase="Running"' in text
        assert 'resource="cpu"' in text


# ---------------------------------------------------------------------------
# slim wire helpers
# ---------------------------------------------------------------------------


class TestSlimWire:
    def test_wire_partition_agrees_with_object_partition(self):
        """The server-side filter and a member's admission predicate MUST
        compute the same shard for every pod (incl. gang pinning) — an
        owned pod arriving slim would be scheduled from a projection."""
        from kubernetes_tpu.shard.partition import shard_of_pod
        for i in range(64):
            p = make_pod().name(f"x{i}").namespace(f"ns{i % 3}").obj()
            if i % 4 == 0:
                p.pod_group = f"g{i % 5}"
            assert shard_of_wire(pod_to_wire(p), 3) == shard_of_pod(p, 3)

    def test_wire_plain_classification(self):
        plain = pod_to_wire(make_pod().name("a").req({"cpu": "1"}).obj())
        ports = pod_to_wire(make_pod().name("b").host_port(80).obj())
        spread = pod_to_wire(make_pod().name("c")
                             .spread_constraint(1, "zone").obj())
        aff = pod_to_wire(make_pod().name("d")
                          .pod_affinity("zone", {"app": "x"}).obj())
        naff = pod_to_wire(make_pod().name("e")
                           .node_affinity_in("k", ["v"]).obj())
        assert wire_plain(plain) and wire_plain(naff)
        assert not wire_plain(ports)
        assert not wire_plain(spread)
        assert not wire_plain(aff)

    def test_slim_projection_roundtrip(self):
        p = (make_pod().name("s").namespace("ns1").req({"cpu": "250m"})
             .priority(7).labels({"app": "x"}).obj())
        p.pod_group = "g1"
        slim = slim_object(pod_to_wire(p))
        got = pod_from_slim(slim)
        assert got.uid == p.uid and got.namespace == "ns1"
        assert got.pod_group == "g1" and got.priority == 7
        assert got.resource_request().milli_cpu == 250
        assert got.wire_slim and got.labels == {}

    def test_slim_merge_keeps_full_spec(self):
        p = make_pod().name("m").req({"cpu": "1"}).labels({"a": "b"}).obj()
        slim = slim_object(dict(pod_to_wire(p), nodeName="n1"))
        merged = pod_from_slim(slim, old=p)
        assert merged.node_name == "n1"
        assert merged.labels == {"a": "b"}          # spec kept
        assert not getattr(merged, "wire_slim", False)


# ---------------------------------------------------------------------------
# server fixtures
# ---------------------------------------------------------------------------


@pytest.fixture()
def api():
    server = APIServer()
    port = server.serve(0)
    try:
        yield server, f"http://127.0.0.1:{port}"
    finally:
        server.shutdown()


def _wait(pred, timeout=10.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {msg}")


def _wait_rv(api_server, clients, timeout=15.0):
    """Every client's pod/node watermark reached the server's rv and its
    scheduler inbox (if any) can drain deterministically."""
    def caught_up():
        for c in clients:
            for kind in ("pods", "nodes"):
                if (c._last_rv[kind] or 0) < api_server._seq[kind]:
                    return False
        return True
    _wait(caught_up, timeout, "watch streams to catch up")


# ---------------------------------------------------------------------------
# read plane over HTTP: cache-served LIST/summary/uids/resources + 410
# ---------------------------------------------------------------------------


class TestReadPlane:
    def test_list_summary_resources_served_from_cache(self, api):
        server, base = api
        server.store.create_node(make_node().name("n0").capacity(
            {"cpu": 8, "memory": "32Gi", "pods": 20}).obj())
        pods = [make_pod().name(f"p{i}").req({"cpu": "100m"}).obj()
                for i in range(5)]
        for p in pods:
            server.store.create_pod(p)
        server._bind_one(pods[0].uid, "n0")
        hits0 = server.watch_cache["pods"].hits

        def get(path):
            with urlrequest.urlopen(base + path, timeout=10) as r:
                return r.read().decode()

        lst = json.loads(get("/api/v1/pods"))
        assert len(lst) == 5
        assert sum(1 for w in lst if w["nodeName"]) == 1
        s = json.loads(get("/api/v1/pods?summary=true"))
        assert s == {"total": 5, "bound": 1}
        sub = json.loads(get(
            f"/api/v1/pods?uids={pods[1].uid},{pods[2].uid}"))
        assert {w["uid"] for w in sub} == {pods[1].uid, pods[2].uid}
        res = get("/metrics/resources")
        assert "kube_pod_resource_request" in res and 'node="n0"' in res
        assert server.watch_cache["pods"].hits >= hits0 + 4
        metrics = get("/metrics")
        assert "apiserver_watch_cache_hits_total" in metrics
        assert "apiserver_watch_events_slim_total" in metrics

    def test_too_old_reconnect_falls_back_to_relist(self):
        server = APIServer(backlog=8)
        port = server.serve(0)
        try:
            base = f"http://127.0.0.1:{port}"
            for i in range(4):
                server.store.create_pod(
                    make_pod().name(f"p{i}").req({"cpu": "1m"}).obj())
            cs = HTTPClientset(base)
            try:
                _wait_rv(server, [cs])
                # stall the reflector by killing its stream, then overflow
                # the ring while it is away
                for conn in list(cs._responses):
                    from kubernetes_tpu.core.apiserver import _shutdown_conn
                    _shutdown_conn(conn)
                for i in range(20):
                    server.store.create_pod(
                        make_pod().name(f"q{i}").req({"cpu": "1m"}).obj())
                _wait(lambda: len(cs.pods) == 24, msg="post-overflow sync")
                assert cs.relists["pods"] >= 2      # 410 -> Replace ran
                assert server.watch_cache["pods"].too_old >= 1
            finally:
                cs.close()
        finally:
            server.shutdown()


# ---------------------------------------------------------------------------
# filtered streams: 1/N decode, suppression, equivalence fuzz, adoption
# ---------------------------------------------------------------------------


def _owned(uid, index, count=2):
    return zlib.crc32(uid.encode()) % count == index


class TestShardFilteredStreams:
    def test_decoded_full_events_drop_to_half(self, api):
        """The acceptance 1/N: with 2 shards, each filtered stream decodes
        ~half the pods full and the rest slim; the unfiltered baseline
        decodes everything full."""
        server, base = api
        server.store.create_node(make_node().name("n0").capacity(
            {"cpu": 64, "memory": "256Gi", "pods": 400}).obj())
        n = 200
        for i in range(n):
            server.store.create_pod(
                make_pod().name(f"p{i}").req({"cpu": "10m"})
                .labels({"app": "bench"}).obj())
        oracle = HTTPClientset(base)
        f0 = HTTPClientset(base, shard=(0, 2))
        f1 = HTTPClientset(base, shard=(1, 2))
        try:
            _wait_rv(server, [oracle, f0, f1])
            assert oracle.watch_events_full == n + 1  # pods + the node
            assert oracle.watch_events_slim == 0
            for c in (f0, f1):
                full_pods = c.watch_events_full - 1   # the node is full
                assert full_pods + c.watch_events_slim == n
                assert n * 0.3 < full_pods < n * 0.7, full_pods
                assert c.watch_bytes_slim < c.watch_bytes_full
            # the two shards partition the pod set exactly
            assert (f0.watch_events_full + f1.watch_events_full - 2
                    == n)
            assert server.watch_slim_events == (
                f0.watch_events_slim + f1.watch_events_slim)
        finally:
            for c in (oracle, f0, f1):
                c.close()

    def test_unchanged_slim_modified_is_suppressed(self, api):
        """A foreign pending pod's spec-only update (gate lift) does not
        change the slim projection — the filtered stream drops it."""
        server, base = api
        pod = (make_pod().name("g").req({"cpu": "1m"})
               .scheduling_gate("hold").obj())
        # pick a shard index that does NOT own the pod
        idx = 1 if _owned(pod.uid, 0) else 0
        server.store.create_pod(pod)
        f = HTTPClientset(base, shard=(idx, 2))
        try:
            _wait_rv(server, [f])
            before = f.watch_events_slim + f.watch_events_full
            dropped0 = server.watch_filtered_events
            lifted = pod.clone_from_template(pod.name)
            lifted.uid = pod.uid
            lifted.scheduling_gates = []
            server.store.update_pod(lifted)
            _wait(lambda: server.watch_filtered_events > dropped0,
                  msg="suppressed event counter")
            # a marker event proves the stream is live, yet nothing arrived
            time.sleep(0.2)
            assert f.watch_events_slim + f.watch_events_full == before
            assert pod.uid in f.pods
        finally:
            f.close()

    def test_mixed_churn_filtered_cache_equals_oracle(self, api):
        """Equivalence fuzz: drive MixedChurn (plain + affinity + spread +
        host-port pods across namespaces, server-side binds, node churn,
        deletes) through one unfiltered and one shard-filtered clientset,
        each feeding a scheduler's cache; the filtered member's NodeInfo
        accounting must be identical — including the selector-transition
        upgrade that re-delivers previously-slim pods full."""
        server, base = api
        rng = random.Random(7)
        for i in range(6):
            server.store.create_node(
                make_node().name(f"n{i}")
                .capacity({"cpu": 64, "memory": "256Gi", "pods": 500})
                .zone(f"z{i % 3}").obj())

        oracle_cs = HTTPClientset(base)
        member_cs = HTTPClientset(base, shard=(0, 2))
        oracle = Scheduler(clientset=oracle_cs)
        member = Scheduler(clientset=member_cs)
        member.pod_admission = lambda p: _owned(p.uid, 0)
        try:
            live = []
            # Phase 1: plain pods only (slimming fully engaged)
            for i in range(60):
                p = (make_pod().name(f"plain{i}")
                     .namespace(f"ns{i % 3}")
                     .req({"cpu": f"{10 + (i % 5) * 10}m",
                           "memory": "16Mi"})
                     .labels({"app": f"a{i % 4}"}).obj())
                server.store.create_pod(p)
                live.append(p)
            # bind half server-side (BOUND events -> NodeInfo accounting)
            for p in rng.sample(live, 30):
                code, _ = server._bind_one(p.uid, f"n{rng.randrange(6)}")
                assert code == 200
            # Phase 2: wire-relevant pods join — ports, spread, affinity
            special = []
            for i in range(6):
                b = make_pod().name(f"port{i}").req({"cpu": "5m"})
                special.append(b.host_port(9000 + i).obj())
            for i in range(4):
                special.append(
                    make_pod().name(f"spread{i}").req({"cpu": "5m"})
                    .labels({"app": "a1"})
                    .spread_constraint(1, "zone",
                                       match_labels={"app": "a1"}).obj())
            for i in range(4):
                special.append(
                    make_pod().name(f"aff{i}").req({"cpu": "5m"})
                    .labels({"app": "a2"})
                    .pod_affinity("zone", {"app": "a2"}).obj())
            for p in special:
                server.store.create_pod(p)
                live.append(p)
            for p in rng.sample(special, 8):
                server._bind_one(p.uid, f"n{rng.randrange(6)}")
            # Phase 3: churn — more plains (now full: selector_refs > 0),
            # deletes, node updates
            for i in range(30):
                p = (make_pod().name(f"late{i}").namespace(f"ns{i % 3}")
                     .req({"cpu": "20m"}).labels({"app": f"a{i % 4}"}).obj())
                server.store.create_pod(p)
                live.append(p)
                if i % 3 == 0:
                    server._bind_one(p.uid, f"n{rng.randrange(6)}")
            for p in rng.sample(live, 15):
                server.store.delete_pod(p)
                live.remove(p)
            for i in range(3):
                node = server.store.nodes[f"n{i}"]
                import copy as _copy
                upd = _copy.deepcopy(node)
                upd.labels["churn"] = str(i)
                server.store.update_node(upd)

            _wait_rv(server, [oracle_cs, member_cs])
            oracle.drain_event_inbox()
            member.drain_event_inbox()

            assert member_cs.watch_events_slim > 0, "filter never engaged"

            def cache_view(s):
                out = {}
                for name, ni in s.cache.nodes.items():
                    pods = {pi.pod.uid for pi in ni.pods}
                    req = ni.requested
                    out[name] = {
                        "pods": pods,
                        "cpu": req.milli_cpu,
                        "mem": req.memory,
                        "ports": sorted(
                            (hp.host_port for pi in ni.pods
                             for hp in pi.pod.host_ports())),
                        "affinity": sorted(
                            pi.pod.uid for pi in ni.pods_with_affinity),
                        # label truth drives spread/affinity matching: must
                        # survive slimming + the upgrade path
                        "labels": sorted(
                            (pi.pod.uid, tuple(sorted(pi.pod.labels.items())))
                            for pi in ni.pods),
                    }
                return out

            ov, mv = cache_view(oracle), cache_view(member)
            assert ov == mv
            # informer truth on the projection facts for EVERY live pod
            assert set(oracle_cs.pods) == set(member_cs.pods)
            for uid, op in oracle_cs.pods.items():
                mp = member_cs.pods[uid]
                assert (op.node_name, op.namespace, op.priority) == \
                       (mp.node_name, mp.namespace, mp.priority)
                assert op.resource_request().milli_cpu == \
                       mp.resource_request().milli_cpu
        finally:
            oracle_cs.close()
            member_cs.close()

    def test_adoption_hydrates_slim_pods_before_scheduling(self, api):
        """ShardMember adoption: pods of an adopted range arrived slim on
        this member's static filter — the sweep hydrates the full wire
        before enqueueing, and per-event hydration covers new arrivals."""
        from kubernetes_tpu.shard.member import ShardMember

        server, base = api
        server.store.create_node(make_node().name("n0").capacity(
            {"cpu": 64, "memory": "256Gi", "pods": 100}).obj())
        cs = HTTPClientset(base, shard=(0, 2))
        sched = Scheduler(clientset=cs)
        member = ShardMember(sched, 0, 2, lease_duration=60.0)
        try:
            foreign = []
            for i in range(30):
                p = (make_pod().name(f"f{i}").req({"cpu": "10m"})
                     .node_selector({"zone": "nowhere"}).obj())
                if not _owned(p.uid, 0):
                    foreign.append(p)
                server.store.create_pod(p)
            _wait_rv(server, [cs])
            sched.drain_event_inbox()
            assert foreign and all(
                getattr(cs.pods[p.uid], "wire_slim", False)
                for p in foreign)
            assert not any(sched.queue.has_entity(p.uid) for p in foreign)

            # adopt the peer's range and sweep
            member.owned = {0, 1}
            added = member.sweep_pending()
            assert added == len(foreign)
            for p in foreign:
                got = cs.pods[p.uid]
                assert not getattr(got, "wire_slim", False)
                # the REAL spec arrived (projection had no nodeSelector)
                assert got.node_selector == {"zone": "nowhere"}
                assert sched.queue.has_entity(p.uid)

            # a NEW pod in the adopted range still arrives slim on the
            # static filter; the per-event path hydrates it on admission
            newcomers = []
            while len(newcomers) < 1:
                p = (make_pod().req({"cpu": "10m"})
                     .node_selector({"zone": "nowhere"}).obj())
                if not _owned(p.uid, 0):
                    newcomers.append(p)
                    server.store.create_pod(p)
            _wait_rv(server, [cs])
            sched.drain_event_inbox()
            got = cs.pods[newcomers[0].uid]
            assert not getattr(got, "wire_slim", False)
            assert got.node_selector == {"zone": "nowhere"}
            assert sched.queue.has_entity(newcomers[0].uid)
        finally:
            member.stop()
            cs.close()


# ---------------------------------------------------------------------------
# filtered RESUME: across reconnects (and the selector-ful refusal)
# ---------------------------------------------------------------------------


class TestReviewRegressions:
    def test_scheduler_construction_over_prepopulated_filtered_stream(
            self, api):
        """Deadlock regression: constructing a Scheduler over a filtered
        clientset against a cluster that ALREADY holds pending foreign
        pods must not hydrate (pod_admission is not attached yet) — the
        attach-time replay holds _dispatch_lock on this very thread, and
        hydrate_pods re-acquiring it hung construction forever."""
        server, base = api
        for i in range(10):
            server.store.create_pod(
                make_pod().name(f"pre{i}").req({"cpu": "1m"}).obj())
        cs = HTTPClientset(base, shard=(0, 2))
        try:
            done = {}

            def build():
                done["sched"] = Scheduler(clientset=cs)

            t = threading.Thread(target=build, daemon=True)
            t.start()
            t.join(timeout=20)
            assert "sched" in done, "Scheduler construction deadlocked"
            # foreign pods stayed slim AND unqueued (no shard member yet)
            slim = [u for u, p in cs.pods.items()
                    if getattr(p, "wire_slim", False)]
            assert slim
            for u in slim:
                assert not done["sched"].queue.has_entity(u)
        finally:
            cs.close()

    def test_resume_replays_projection_delta_missed_while_disconnected(
            self, api):
        """Suppression regression: a foreign pod's deletionTs set while
        the client was disconnected must survive the RESUME replay (prime
        runs AFTER the replay — priming first made the replayed MODIFIED
        compare equal to the primed current state and get dropped)."""
        server, base = api
        pod = (make_pod().name("d").req({"cpu": "1m"})
               .obj())
        pod.finalizers = ["keep"]  # delete parks with deletionTs (update)
        idx = 1 if _owned(pod.uid, 0) else 0
        server.store.create_pod(pod)
        f = HTTPClientset(base, shard=(idx, 2))
        try:
            _wait_rv(server, [f])
            assert f.pods[pod.uid].deletion_ts is None
            for conn in list(f._responses):
                from kubernetes_tpu.core.apiserver import _shutdown_conn
                _shutdown_conn(conn)
            server.store.delete_pod(pod)   # parks: MODIFIED w/ deletionTs
            _wait(lambda: f.pods.get(pod.uid) is not None
                  and f.pods[pod.uid].deletion_ts is not None,
                  msg="replayed deletionTs delta")
            assert f.resumes["pods"] >= 1  # it arrived via RESUME replay
        finally:
            f.close()

    def test_invalid_shard_spec_is_ignored_not_coerced(self, api):
        """shard=3/0 or shard=5/2 names no real slot: the server must
        serve the stream UNFILTERED instead of slimming every pod."""
        server, base = api
        for i in range(6):
            server.store.create_pod(
                make_pod().name(f"p{i}").req({"cpu": "1m"}).obj())
        for spec in ("3/0", "5/2", "-1/2", "x/y"):
            import http.client as hc
            conn = hc.HTTPConnection(base.split("//")[1], timeout=10)
            conn.request("GET", f"/api/v1/pods?watch=true&shard={spec}")
            resp = conn.getresponse()
            slim_seen = full_seen = 0
            # read through the SYNC marker
            while True:
                line = resp.readline()
                if not line:
                    break
                line = line.strip()
                if not line or line in (b",", b"\r"):
                    continue
                try:
                    ev = json.loads(line)
                except ValueError:
                    continue
                if ev.get("type") == "SYNC":
                    break
                obj = ev.get("object") or {}
                if obj.get("slim"):
                    slim_seen += 1
                else:
                    full_seen += 1
            conn.close()
            assert slim_seen == 0, f"spec {spec} slimmed pods"
            assert full_seen == 6
        with pytest.raises(ValueError):
            ShardFilter(3, 0)
        with pytest.raises(ValueError):
            ShardFilter(5, 2)


class TestFilteredResume:
    def test_filtered_stream_resumes_by_rv(self, api):
        server, base = api
        for i in range(10):
            server.store.create_pod(
                make_pod().name(f"p{i}").req({"cpu": "1m"}).obj())
        f = HTTPClientset(base, shard=(0, 2))
        try:
            _wait_rv(server, [f])
            relists0 = f.relists["pods"]
            for conn in list(f._responses):
                from kubernetes_tpu.core.apiserver import _shutdown_conn
                _shutdown_conn(conn)
            for i in range(5):
                server.store.create_pod(
                    make_pod().name(f"q{i}").req({"cpu": "1m"}).obj())
            _wait(lambda: len(f.pods) == 15 and f.resumes["pods"] >= 1,
                  msg="filtered RESUME")
            assert f.relists["pods"] == relists0    # zero re-lists
            assert server.watch_cache["pods"].resumes >= 1
        finally:
            f.close()

    def test_selector_ful_cluster_refuses_filtered_resume(self, api):
        """With live selector sources the per-stream slim set cannot be
        reconstructed: a filtered reconnect re-lists instead of silently
        resuming into an un-upgradable state."""
        server, base = api
        server.store.create_pod(
            make_pod().name("s").req({"cpu": "1m"})
            .spread_constraint(1, "zone").obj())
        for i in range(5):
            server.store.create_pod(
                make_pod().name(f"p{i}").req({"cpu": "1m"}).obj())
        f = HTTPClientset(base, shard=(0, 2))
        try:
            _wait_rv(server, [f])
            relists0 = f.relists["pods"]
            for conn in list(f._responses):
                from kubernetes_tpu.core.apiserver import _shutdown_conn
                _shutdown_conn(conn)
            server.store.create_pod(
                make_pod().name("x").req({"cpu": "1m"}).obj())
            _wait(lambda: f.relists["pods"] > relists0,
                  msg="filtered re-list under selector refs")
        finally:
            f.close()
