"""ComponentConfig, feature gates, and metrics (SURVEY.md §5)."""

import pytest

from kubernetes_tpu.core.config import PluginSet, ProfileConfig, SchedulerConfiguration
from kubernetes_tpu.core.features import (
    FeatureGates,
    GENERIC_WORKLOAD,
    TPU_BATCH_SCHEDULING,
    TPU_STATE_RESIDENCY,
)
from kubernetes_tpu.core.scheduler import Scheduler
from kubernetes_tpu.models.tpu_scheduler import TPUScheduler
from kubernetes_tpu.testing.wrappers import make_node, make_pod


class TestFeatureGates:
    def test_defaults(self):
        g = FeatureGates()
        assert g.enabled(GENERIC_WORKLOAD)
        assert g.enabled(TPU_BATCH_SCHEDULING)

    def test_override_and_unknown(self):
        g = FeatureGates({TPU_BATCH_SCHEDULING: False, TPU_STATE_RESIDENCY: False})
        assert not g.enabled(TPU_BATCH_SCHEDULING)
        with pytest.raises(ValueError):
            FeatureGates({"NoSuchGate": True})

    def test_dependency_validation(self):
        with pytest.raises(ValueError):
            FeatureGates({TPU_BATCH_SCHEDULING: False})  # residency depends on it


class TestComponentConfig:
    def test_plugin_set_resolve(self):
        ps = PluginSet(enabled=(("TaintToleration", 5),), disabled=("ImageLocality",))
        resolved = dict(ps.resolve())
        assert resolved["TaintToleration"] == 5
        assert "ImageLocality" not in resolved

    def test_from_dict_profile(self):
        cfg = SchedulerConfiguration.from_dict({
            "profiles": [{
                "schedulerName": "custom",
                "plugins": {"disabled": ["InterPodAffinity"]},
                "pluginConfig": [
                    {"name": "NodeResourcesFit",
                     "args": {"scoring_strategy": "MostAllocated"}}],
            }],
            "percentageOfNodesToScore": 20,
            "featureGates": {"GenericWorkload": True},
        })
        s = Scheduler(config=cfg)
        assert "custom" in s.profiles
        fw = s.profiles["custom"]
        assert fw.plugin("InterPodAffinity") is None
        assert fw.plugin("NodeResourcesFit").scoring_strategy == "MostAllocated"
        assert s.percentage_of_nodes_to_score == 20

    def test_custom_profile_schedules(self):
        cfg = SchedulerConfiguration.from_dict({
            "profiles": [{"schedulerName": "custom"}]})
        s = Scheduler(config=cfg)
        s.clientset.create_node(
            make_node().name("n0").capacity({"cpu": "4", "pods": 10}).obj())
        p = make_pod().name("p").req({"cpu": "1"}).scheduler_name("custom").obj()
        s.clientset.create_pod(p)
        s.run_until_idle()
        assert s.scheduled == 1

    def test_device_gate_off_uses_host_path(self):
        cfg = SchedulerConfiguration.from_dict({
            "featureGates": {"TPUBatchScheduling": False,
                             "TPUStateResidency": False}})
        s = TPUScheduler(config=cfg)
        s.clientset.create_node(
            make_node().name("n0").capacity({"cpu": "4", "pods": 10}).obj())
        s.clientset.create_pod(make_pod().name("p").req({"cpu": "1"}).obj())
        s.run_until_idle()
        assert s.scheduled == 1
        assert s.device_batches == 0


class TestMetrics:
    def test_schedule_attempt_series(self):
        s = Scheduler()
        s.clientset.create_node(
            make_node().name("n0").capacity({"cpu": "2", "pods": 10}).obj())
        s.clientset.create_pod(make_pod().name("fits").req({"cpu": "1"}).obj())
        s.clientset.create_pod(make_pod().name("huge").req({"cpu": "64"}).obj())
        s.run_until_idle()
        m = s.metrics
        assert m.schedule_attempts.value("scheduled", "default-scheduler") == 1
        assert m.schedule_attempts.value("unschedulable", "default-scheduler") >= 1
        assert m.scheduling_attempt_duration.count("scheduled", "default-scheduler") == 1
        text = s.expose_metrics()
        assert "scheduler_schedule_attempts_total" in text
        assert 'scheduler_pending_pods{queue="unschedulable"}' in text

    def test_preemption_metrics(self):
        s = Scheduler()
        s.clientset.create_node(
            make_node().name("n0").capacity({"cpu": "2", "pods": 10}).obj())
        s.clientset.create_pod(make_pod().name("low").req({"cpu": "2"}).priority(1).obj())
        s.run_until_idle()
        s.clientset.create_pod(make_pod().name("hi").req({"cpu": "2"}).priority(9).obj())
        s.run_until_idle()
        assert s.metrics.preemption_attempts.value() >= 1
        assert s.metrics.preemption_victims.count() == 1

    def test_batch_metrics(self):
        s = TPUScheduler()
        s.clientset.create_node(
            make_node().name("n0").capacity({"cpu": "8", "pods": 20}).obj())
        for i in range(5):
            s.clientset.create_pod(make_pod().name(f"p{i}").req({"cpu": "1"}).obj())
        s.run_until_idle()
        assert s.metrics.batch_attempts.value("dispatched") >= 1
        assert s.metrics.batch_size.count() >= 1
