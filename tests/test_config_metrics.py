"""ComponentConfig, feature gates, and metrics (SURVEY.md §5)."""

import pytest

from kubernetes_tpu.core.config import PluginSet, ProfileConfig, SchedulerConfiguration
from kubernetes_tpu.core.features import (
    FeatureGates,
    GENERIC_WORKLOAD,
    TPU_BATCH_SCHEDULING,
    TPU_STATE_RESIDENCY,
)
from kubernetes_tpu.core.scheduler import Scheduler
from kubernetes_tpu.models.tpu_scheduler import TPUScheduler
from kubernetes_tpu.testing.wrappers import make_node, make_pod


class TestFeatureGates:
    def test_defaults(self):
        g = FeatureGates()
        assert g.enabled(GENERIC_WORKLOAD)
        assert g.enabled(TPU_BATCH_SCHEDULING)

    def test_override_and_unknown(self):
        g = FeatureGates({TPU_BATCH_SCHEDULING: False, TPU_STATE_RESIDENCY: False})
        assert not g.enabled(TPU_BATCH_SCHEDULING)
        with pytest.raises(ValueError):
            FeatureGates({"NoSuchGate": True})

    def test_dependency_validation(self):
        with pytest.raises(ValueError):
            FeatureGates({TPU_BATCH_SCHEDULING: False})  # residency depends on it


class TestComponentConfig:
    def test_plugin_set_resolve(self):
        ps = PluginSet(enabled=(("TaintToleration", 5),), disabled=("ImageLocality",))
        resolved = dict(ps.resolve())
        assert resolved["TaintToleration"] == 5
        assert "ImageLocality" not in resolved

    def test_from_dict_profile(self):
        cfg = SchedulerConfiguration.from_dict({
            "profiles": [{
                "schedulerName": "custom",
                "plugins": {"disabled": ["InterPodAffinity"]},
                "pluginConfig": [
                    {"name": "NodeResourcesFit",
                     "args": {"scoring_strategy": "MostAllocated"}}],
            }],
            "percentageOfNodesToScore": 20,
            "featureGates": {"GenericWorkload": True},
        })
        s = Scheduler(config=cfg)
        assert "custom" in s.profiles
        fw = s.profiles["custom"]
        assert fw.plugin("InterPodAffinity") is None
        assert fw.plugin("NodeResourcesFit").scoring_strategy == "MostAllocated"
        assert s.percentage_of_nodes_to_score == 20

    def test_custom_profile_schedules(self):
        cfg = SchedulerConfiguration.from_dict({
            "profiles": [{"schedulerName": "custom"}]})
        s = Scheduler(config=cfg)
        s.clientset.create_node(
            make_node().name("n0").capacity({"cpu": "4", "pods": 10}).obj())
        p = make_pod().name("p").req({"cpu": "1"}).scheduler_name("custom").obj()
        s.clientset.create_pod(p)
        s.run_until_idle()
        assert s.scheduled == 1

    def test_device_gate_off_uses_host_path(self):
        cfg = SchedulerConfiguration.from_dict({
            "featureGates": {"TPUBatchScheduling": False,
                             "TPUStateResidency": False}})
        s = TPUScheduler(config=cfg)
        s.clientset.create_node(
            make_node().name("n0").capacity({"cpu": "4", "pods": 10}).obj())
        s.clientset.create_pod(make_pod().name("p").req({"cpu": "1"}).obj())
        s.run_until_idle()
        assert s.scheduled == 1
        assert s.device_batches == 0


class TestMetrics:
    def test_schedule_attempt_series(self):
        s = Scheduler()
        s.clientset.create_node(
            make_node().name("n0").capacity({"cpu": "2", "pods": 10}).obj())
        s.clientset.create_pod(make_pod().name("fits").req({"cpu": "1"}).obj())
        s.clientset.create_pod(make_pod().name("huge").req({"cpu": "64"}).obj())
        s.run_until_idle()
        m = s.metrics
        assert m.schedule_attempts.value("scheduled", "default-scheduler") == 1
        assert m.schedule_attempts.value("unschedulable", "default-scheduler") >= 1
        assert m.scheduling_attempt_duration.count("scheduled", "default-scheduler") == 1
        text = s.expose_metrics()
        assert "scheduler_schedule_attempts_total" in text
        assert 'scheduler_pending_pods{queue="unschedulable"}' in text

    def test_preemption_metrics(self):
        s = Scheduler()
        s.clientset.create_node(
            make_node().name("n0").capacity({"cpu": "2", "pods": 10}).obj())
        s.clientset.create_pod(make_pod().name("low").req({"cpu": "2"}).priority(1).obj())
        s.run_until_idle()
        s.clientset.create_pod(make_pod().name("hi").req({"cpu": "2"}).priority(9).obj())
        s.run_until_idle()
        assert s.metrics.preemption_attempts.value() >= 1
        assert s.metrics.preemption_victims.count() == 1

    def test_batch_metrics(self):
        s = TPUScheduler()
        s.clientset.create_node(
            make_node().name("n0").capacity({"cpu": "8", "pods": 20}).obj())
        for i in range(5):
            s.clientset.create_pod(make_pod().name(f"p{i}").req({"cpu": "1"}).obj())
        s.run_until_idle()
        assert s.metrics.batch_attempts.value("dispatched") >= 1
        assert s.metrics.batch_size.count() >= 1


def test_pre_bind_pre_flight_skips_and_runs():
    """PreBindPreFlight (runtime/framework.go:1875): all-Skip bypasses the
    PreBind phase; a declaring plugin still runs when it has work."""
    from kubernetes_tpu.core.framework import CycleState, Framework, OK, Status

    ran = []

    class Flighty:
        name = "Flighty"

        def __init__(self, skip):
            self._skip = skip

        def pre_bind_pre_flight(self, state, pod, node):
            return Status.skip() if self._skip else OK

        def pre_bind(self, state, pod, node):
            ran.append(self.name)
            return OK

    from kubernetes_tpu.testing.wrappers import make_pod
    pod = make_pod().name("p").obj()

    fw = Framework(plugins=[(Flighty(skip=True), 0)])
    state = CycleState()
    st = fw.run_pre_bind_pre_flight(state, pod, "n0")
    assert st.is_skip()
    assert "Flighty" in state.skip_pre_bind_plugins

    fw2 = Framework(plugins=[(Flighty(skip=False), 0)])
    state2 = CycleState()
    st2 = fw2.run_pre_bind_pre_flight(state2, pod, "n0")
    assert st2.is_success() and not st2.is_skip()
    fw2.run_pre_bind_plugins(state2, pod, "n0")
    assert ran == ["Flighty"]


def test_extension_point_latency_recorded():
    """framework_extension_point_duration_seconds fills per point during
    host scheduling cycles (metrics.go:265-615 series; perf artifact
    carries per-point percentiles)."""
    from kubernetes_tpu.core.clientset import FakeClientset
    from kubernetes_tpu.core.scheduler import Scheduler
    from kubernetes_tpu.testing.wrappers import make_node, make_pod

    cs = FakeClientset()
    sched = Scheduler(clientset=cs)
    cs.create_node(make_node().name("n0").capacity({"cpu": "4", "pods": 10}).obj())
    cs.create_node(make_node().name("n1").capacity({"cpu": "4", "pods": 10}).obj())
    cs.create_pod(make_pod().name("p").req({"cpu": "1"}).obj())
    sched.run_until_idle()
    hist = sched.metrics.framework_extension_point_duration
    for point in ("PreFilter", "Filter", "PreScore", "Score", "Reserve",
                  "Permit", "Bind"):
        assert hist.count(point, "Success", "") >= 1, point


def test_metric_async_recorder_flushes_off_thread():
    """metric_recorder.go analogue: observations buffer on the hot path and
    land in the histogram via the flusher thread; overflow drops are
    counted, close() drains."""
    import time as _t

    from kubernetes_tpu.core.metrics import Histogram, MetricAsyncRecorder

    h = Histogram("test_hist", "t", ("label",))
    rec = MetricAsyncRecorder(interval=0.01, capacity=8)
    for i in range(6):
        rec.observe(h, 0.001 * i, "x")
    deadline = _t.monotonic() + 5
    while _t.monotonic() < deadline and h.count("x") < 6:
        _t.sleep(0.005)
    assert h.count("x") == 6
    # overflow drops (non-blocking send semantics)
    rec._stop.set(); rec._thread.join(timeout=2)  # park the flusher
    for i in range(20):
        rec.observe(h, 0.1, "x")
    assert rec.dropped == 12
    rec.flush_now()
    assert h.count("x") == 14


def test_scheduler_configuration_validation():
    """ValidateKubeSchedulerConfiguration (validation.go:38): range checks,
    profile uniqueness, extender verb/weight requirements."""
    from kubernetes_tpu.core.config import ProfileConfig, SchedulerConfiguration

    assert SchedulerConfiguration().validate() == []

    bad = SchedulerConfiguration(
        percentage_of_nodes_to_score=150,
        pod_initial_backoff_seconds=0,
        pod_max_backoff_seconds=-1,
        max_batch=0,
        profiles=[ProfileConfig(scheduler_name="a"),
                  ProfileConfig(scheduler_name="a")],
        extenders=[{"filterVerb": "filter"},         # no urlPrefix
                   {"urlPrefix": "http://x", "weight": 0}])  # no verb, bad weight
    errs = bad.validate()
    joined = "\n".join(errs)
    assert "percentageOfNodesToScore" in joined
    assert "podInitialBackoffSeconds" in joined
    assert "podMaxBackoffSeconds" in joined
    assert "maxBatch" in joined
    assert "Duplicate" in joined
    assert "urlPrefix" in joined
    assert "at least one verb" in joined
    assert "positive integer" in joined
